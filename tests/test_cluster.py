"""Cluster coordination tests (model: reference ShardManagerSpec,
ShardAssignmentStrategySpec, FilodbClusterStateSpec)."""

import time

import pytest

from filodb_tpu.coordinator.cluster import (
    ClusterDiscovery,
    ShardManager,
    ShardMapper,
    ShardStatus,
)
from filodb_tpu.core.schemas import shardkey_hash


class TestShardMapper:
    def test_status_transitions_and_events(self):
        m = ShardMapper(4)
        events = []
        m.subscribe(events.append)
        m.update(0, ShardStatus.ASSIGNED, "node-a")
        m.update(0, ShardStatus.RECOVERY)
        m.update(0, ShardStatus.ACTIVE)
        assert m.status_of(0) == ShardStatus.ACTIVE
        assert m.node_of(0) == "node-a"
        assert [e.status for e in events] == [
            ShardStatus.ASSIGNED, ShardStatus.RECOVERY, ShardStatus.ACTIVE]

    def test_active_shards_routing(self):
        m = ShardMapper(4)
        for s, st in enumerate([ShardStatus.ACTIVE, ShardStatus.RECOVERY,
                                ShardStatus.DOWN, ShardStatus.UNASSIGNED]):
            m.update(s, st, "n")
        assert m.active_shards() == [0, 1]  # recovery shards still queryable

    def test_query_shards_pruned_by_shard_key(self):
        m = ShardMapper(32)
        for s in range(32):
            m.update(s, ShardStatus.ACTIVE, "n")
        h = shardkey_hash({"_ws_": "w", "_ns_": "n", "_metric_": "m"})
        shards = m.query_shards(h, spread=3)
        assert 1 <= len(shards) <= 8
        # same key always routes to the same shard set
        assert shards == m.query_shards(h, spread=3)


class TestShardManager:
    def test_join_assigns_evenly(self):
        mgr = ShardManager(8, shards_per_node=4)
        a = mgr.node_joined("a")
        b = mgr.node_joined("b")
        assert len(a) == 4 and len(b) == 4
        assert set(a) | set(b) == set(range(8))

    def test_node_leave_reassigns(self):
        mgr = ShardManager(8, shards_per_node=8, reassignment_damper_s=0)
        mgr.node_joined("a")
        mgr.node_joined("b")  # a full -> b gets nothing
        lost = mgr.node_left("a")
        assert set(lost) == set(range(8))
        assert all(mgr.mapper.node_of(s) == "b" for s in range(8))

    def test_ingestion_error_reassigns_once_then_dampers(self):
        mgr = ShardManager(2, shards_per_node=2, reassignment_damper_s=3600)
        mgr.node_joined("a")
        mgr.node_joined("b")
        assert mgr.ingestion_error(0) is True
        # second error within the damper window -> shard goes DOWN
        assert mgr.ingestion_error(0) is False
        assert mgr.mapper.status_of(0) == ShardStatus.DOWN

    def test_ingestion_error_moves_shard_to_another_node(self):
        """First error: the shard leaves the failing node and lands on a
        DIFFERENT node (reference doc/sharding.md auto-reassignment)."""
        mgr = ShardManager(2, shards_per_node=2, reassignment_damper_s=3600)
        mgr.node_joined("a")  # capacity 2: owns both shards
        mgr.node_joined("b")
        origin = mgr.mapper.node_of(0)
        assert origin == "a"
        assert mgr.ingestion_error(0) is True
        assert mgr.mapper.status_of(0) == ShardStatus.ASSIGNED
        assert mgr.mapper.node_of(0) == "b"
        assert mgr.damper_active(0)

    def test_damper_expiry_allows_reassignment_again(self):
        """After the damper window passes, a DOWN shard recovers via the
        normal reassignment path instead of staying dead forever."""
        t = [1000.0]
        mgr = ShardManager(2, shards_per_node=2, reassignment_damper_s=3600,
                           clock=lambda: t[0])
        mgr.node_joined("a")
        mgr.node_joined("b")
        assert mgr.ingestion_error(0) is True      # a -> b
        t[0] += 10
        assert mgr.ingestion_error(0) is False     # damper: DOWN, not bounced
        assert mgr.mapper.status_of(0) == ShardStatus.DOWN
        assert mgr.damper_active(0)
        t[0] += 3600
        assert not mgr.damper_active(0)
        assert mgr.ingestion_error(0) is True      # recoverable again
        assert mgr.mapper.status_of(0) == ShardStatus.ASSIGNED

    def test_fresh_manager_never_dampers_first_reassignment(self):
        """Regression: 'never reassigned' must read as infinitely old, even
        under clocks that start near zero (the damper suppresses REPEAT
        bounces only)."""
        mgr = ShardManager(2, shards_per_node=2, reassignment_damper_s=3600,
                           clock=lambda: 5.0)
        mgr.node_joined("a")
        mgr.node_joined("b")
        assert mgr.ingestion_error(0) is True

    def test_lifecycle_to_active(self):
        mgr = ShardManager(1, shards_per_node=1)
        mgr.node_joined("a")
        mgr.shard_recovering(0)
        assert mgr.mapper.status_of(0) == ShardStatus.RECOVERY
        mgr.shard_active(0)
        assert mgr.mapper.status_of(0) == ShardStatus.ACTIVE


class TestClusterDiscovery:
    def test_ordinal_ranges_cover_all_shards(self):
        d = ClusterDiscovery(num_shards=10, num_nodes=3)
        all_shards = []
        for o in range(3):
            all_shards.extend(d.shards_for_ordinal(o))
        assert sorted(all_shards) == list(range(10))
        # deterministic and contiguous
        assert d.shards_for_ordinal(0) == [0, 1, 2, 3]

    def test_health_tracking(self):
        d = ClusterDiscovery(4, 2, failure_detection_interval_s=10)
        now = time.time()
        d.heartbeat(0, now)
        d.heartbeat(1, now - 60)
        assert d.healthy_nodes(now) == [0]
        assert d.down_nodes(now) == [1]


class TestClusterServerIntegration:
    def test_shard_lifecycle_with_memstore(self):
        """ShardManager states drive which shards a planner queries."""
        from filodb_tpu.coordinator.planner import SingleClusterPlanner
        from filodb_tpu.core.schemas import Dataset
        from filodb_tpu.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.testkit import machine_metrics

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), range(4))
        ms.ingest_routed("ds", machine_metrics(n_series=20, n_samples=10), spread=2)
        mgr = ShardManager(4, shards_per_node=4)
        mgr.node_joined("self")
        for s in range(4):
            mgr.shard_active(s)
        planner = SingleClusterPlanner(ms, "ds", shard_nums=mgr.mapper.active_shards())
        assert len(planner.shards_for(None)) == 4
        # shard 2 goes down: planner built from active shards skips it
        mgr.mapper.update(2, ShardStatus.DOWN)
        planner2 = SingleClusterPlanner(ms, "ds", shard_nums=mgr.mapper.active_shards())
        assert 2 not in planner2.shards_for(None)
