"""Resource ledger & self-telemetry tests (doc/observability.md "Resource
accounting & self-monitoring"):

- device-ledger drift: after a query/ingest/evict soak, every ledger
  account's balance EXACTLY equals a cold walk of its cache's
  staged_nbytes — zero drift — and the warm canonical query still issues
  exactly ONE kernel dispatch with accounting enabled;
- per-tenant attribution round-trip: queries as two tenants accumulate
  tenant counters that sum to the query-wide QueryStats totals;
- /debug/resources and /debug/superblocks return consistent JSON;
- self-scrape proof: rate(filodb_kernel_dispatch_seconds_count[5m]) over
  the _system dataset answers through the standard query API;
- slow-query ring under concurrent record/configure, ordering, threshold
  edge; ?trace=true carries the new resource stats;
- Registry.remove + tenant series aging; HELP/TYPE + OpenMetrics +
  exemplars; tpu-watch probe gauges.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.api.http import serve_background
from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.ledger import LEDGER
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.metrics import REGISTRY, Registry, SlowQueryLog
from filodb_tpu.testkit import counter_batch
from filodb_tpu.ops import staging as ST

pytestmark = pytest.mark.observability

BASE = 1_600_000_000_000
N_SAMPLES = 240
HEAD_MS = BASE + N_SAMPLES * 10_000
START = (BASE + 600_000) / 1000
STEP = 60
Q = "sum by (job) (rate(http_requests_total[5m]))"


def _dispatch_total() -> int:
    total = 0
    with REGISTRY._lock:
        for (name, _labels), m in REGISTRY._metrics.items():
            if name == "filodb_kernel_dispatch_seconds":
                total += m.total
    return total


def _counter(name: str, **labels) -> float:
    return REGISTRY.counter(name, **labels).value


def _make_store(n_shards=4, n_series=24, stage_cache_bytes=2 << 30):
    ms = TimeSeriesMemStore(StoreConfig(stage_cache_bytes=stage_cache_bytes))
    ms.setup(Dataset("ds"), list(range(n_shards)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=n_series, n_samples=N_SAMPLES,
                            start_ms=BASE),
        spread=3,
    )
    return ms


def _assert_zero_drift():
    """Every live ledger account's balance equals a cold walk of its cache."""
    report = LEDGER.verify()
    bad = [a for a in report["accounts"]
           if a["actual"] is not None and a["bytes"] != a["actual"]]
    assert not bad, f"ledger drift: {bad}"
    for kind, slot in report["kinds"].items():
        assert slot["drift"] == 0, (kind, slot)


# ---------------------------------------------------------------------------
# device-resource ledger


class TestDeviceLedger:
    def test_drift_zero_after_query_ingest_evict_soak(self):
        """Seeded churn across every ledger event class — cold stages,
        cache hits, append repairs, superblock builds/extensions,
        byte-budget evictions, wholesale invalidation — then the ledger
        must agree with a cold walk EXACTLY."""
        # small stage budget: later stages evict earlier entries
        ms = _make_store(stage_cache_bytes=256 * 1024)
        fused = QueryEngine(ms, "ds")
        end = (HEAD_MS + 40 * 10_000) / 1000
        errors: list = []

        def ingester():
            try:
                for b in range(30):
                    ms.ingest_routed(
                        "ds",
                        counter_batch(n_series=24, n_samples=1,
                                      start_ms=HEAD_MS + b * 10_000),
                        spread=3,
                    )
            except Exception as e:  # pragma: no cover
                errors.append(e)

        th = threading.Thread(target=ingester)
        th.start()
        try:
            for i in range(20):
                fused.query_range(Q, START, end, STEP)
                # distinct windows churn distinct cache keys -> evictions
                fused.query_range(
                    "rate(http_requests_total[5m])", START + i, end, STEP
                )
        finally:
            th.join()
        assert not errors, errors
        _assert_zero_drift()
        # retention/headroom-style wholesale invalidation must credit too
        for sh in ms.shards("ds"):
            with sh._lock:
                sh.version += 1
                sh._record_effect(0, 0, True)
                sh._clear_stage_cache()
        _assert_zero_drift()
        for sh in ms.shards("ds"):
            assert sh.ledger.bytes == 0

    def test_gauges_published_at_scrape_time(self):
        ms = _make_store()
        eng = QueryEngine(ms, "ds")
        eng.query_range(Q, START, (BASE + 900_000) / 1000, STEP)
        text = REGISTRY.expose()
        assert 'filodb_device_bytes{kind="staged_block"}' in text
        assert 'filodb_device_bytes{kind="superblock"}' in text
        assert "filodb_device_alloc_bytes_total" in text
        # the gauge equals the walk of the LIVE accounts at scrape time
        _assert_zero_drift()

    def test_warm_query_single_dispatch_with_accounting(self):
        """Accounting must add no per-dispatch host sync: the warm fused
        canonical query stays exactly ONE kernel dispatch."""
        ms = _make_store()
        fused = QueryEngine(ms, "ds")
        end = (BASE + 900_000) / 1000
        fused.query_range(Q, START, end, STEP)  # cold: stage + compile
        fused.query_range(Q, START, end, STEP)  # warm-up second pass
        before = _dispatch_total()
        res = fused.query_range(Q, START, end, STEP)
        assert _dispatch_total() - before == 1
        assert res.stats.cache_hits >= 1  # superblock served from cache
        _assert_zero_drift()

    def test_evicted_superblock_credits_ledger(self):
        ms = _make_store()
        fused = QueryEngine(ms, "ds")
        end = (BASE + 900_000) / 1000
        fused.query_range(Q, START, end, STEP)
        cache = ms._superblock_cache
        assert len(cache) >= 1
        # drop everything through the cache API: balance must return to 0
        with cache._lock:
            keys = list(cache._d)
        for k in keys:
            cache.drop(k)
        assert cache.ledger.bytes == 0
        _assert_zero_drift()


# ---------------------------------------------------------------------------
# per-tenant attribution


class TestTenantAttribution:
    def test_round_trip_two_tenants(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), list(range(4)))
        for ws, ns, seed in (("tenA", "app1", 3), ("tenB", "app2", 4)):
            ms.ingest_routed(
                "ds",
                counter_batch(n_series=8, n_samples=120, start_ms=BASE,
                              ws=ws, ns=ns, seed=seed),
                spread=3,
            )
        eng = QueryEngine(ms, "ds")
        end = (BASE + 900_000) / 1000
        before = {
            (ws, ns): {
                "q": _counter("filodb_tenant_queries", ws=ws, ns=ns),
                "s": _counter("filodb_tenant_query_seconds", ws=ws, ns=ns),
                "k": _counter("filodb_tenant_kernel_seconds", ws=ws, ns=ns),
                "b": _counter("filodb_tenant_bytes_staged", ws=ws, ns=ns),
            }
            for ws, ns in (("tenA", "app1"), ("tenB", "app2"))
        }
        stats = {}
        for ws, ns in (("tenA", "app1"), ("tenB", "app2")):
            q = (f'sum(rate(http_requests_total{{_ws_="{ws}",'
                 f'_ns_="{ns}"}}[5m]))')
            res1 = eng.query_range(q, START, end, STEP)
            res2 = eng.query_range(q, START + 1, end, STEP)
            stats[(ws, ns)] = [res1.stats, res2.stats]
        for (ws, ns), runs in stats.items():
            b = before[(ws, ns)]
            assert _counter("filodb_tenant_queries", ws=ws, ns=ns) - b["q"] == 2
            # per-tenant counters sum to the query-wide QueryStats totals
            got_bytes = _counter("filodb_tenant_bytes_staged", ws=ws, ns=ns) - b["b"]
            assert got_bytes == sum(r.bytes_staged for r in runs)
            got_kernel = _counter("filodb_tenant_kernel_seconds", ws=ws, ns=ns) - b["k"]
            assert got_kernel == pytest.approx(
                sum(r.kernel_ns for r in runs) / 1e9, rel=1e-6, abs=1e-9
            )
            assert _counter("filodb_tenant_query_seconds", ws=ws, ns=ns) - b["s"] > 0
            assert runs[0].kernel_ns > 0

    def test_unpinned_query_attributes_to_unknown(self):
        ms = _make_store()
        eng = QueryEngine(ms, "ds")
        before = _counter("filodb_tenant_queries", ws="unknown", ns="unknown")
        eng.query_range(Q, START, (BASE + 900_000) / 1000, STEP)
        assert _counter("filodb_tenant_queries", ws="unknown", ns="unknown") \
            == before + 1

    def test_trace_root_tagged_with_tenant(self):
        ms = _make_store()
        eng = QueryEngine(ms, "ds")
        res = eng.query_range(
            'sum(rate(http_requests_total{_ws_="demo",_ns_="App-2"}[5m]))',
            START, (BASE + 900_000) / 1000, STEP,
        )
        assert res.trace.tags.get("ws") == "demo"
        assert res.trace.tags.get("ns") == "App-2"


# ---------------------------------------------------------------------------
# debug endpoints + trace stats over HTTP


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


class TestDebugEndpoints:
    def test_resources_and_superblocks_consistent(self):
        ms = _make_store()
        eng = QueryEngine(ms, "ds")
        end = (BASE + 900_000) / 1000
        eng.query_range(Q, START, end, STEP)
        eng.query_range(Q, START, end, STEP)  # superblock cache hit
        srv, port = serve_background(eng)
        try:
            res = _get_json(f"http://127.0.0.1:{port}/debug/resources")["data"]
            assert set(res) >= {"device_bytes", "kinds", "accounts", "tenants"}
            for kind, slot in res["kinds"].items():
                assert slot["drift"] == 0, (kind, slot)
            assert res["device_bytes"].get("superblock", 0) > 0
            sb = _get_json(f"http://127.0.0.1:{port}/debug/superblocks")["data"]
            assert sb["count"] == len(sb["entries"]) >= 1
            assert sb["bytes"] == sum(e["bytes"] for e in sb["entries"])
            entry = sb["entries"][0]
            assert entry["bytes"] > 0 and entry["hits"] >= 1
            assert "age_s" in entry and "last_outcome" in entry
            # the superblock cache's ledger balance is exactly this bytes
            # sum (the kind-wide device_bytes gauge may also include other
            # live caches in the process, so it can only be >=)
            assert sb["ledger_bytes"] == sb["bytes"]
            assert res["device_bytes"]["superblock"] >= sb["bytes"]
        finally:
            srv.shutdown()

    def test_unknown_dataset_is_400(self):
        ms = _make_store(n_shards=1, n_series=2)
        eng = QueryEngine(ms, "ds")
        srv, port = serve_background(eng)
        try:
            url = (f"http://127.0.0.1:{port}/api/v1/query_range?query="
                   + urllib.parse.quote(Q)
                   + f"&start={START}&end={(BASE + 900_000) / 1000}&step=60")
            # the engine's own dataset name routes to the default engine
            _get_json(url + "&dataset=ds")
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(url + "&dataset=_sytem")  # typo: 400, not wrong data
            assert ei.value.code == 400
        finally:
            srv.shutdown()

    def test_remote_stats_frames_carry_resource_fields(self):
        """The gRPC frame stream round-trips the NEW QueryStats fields
        (kernel_ns + cache events ride the in-band StatsExt frame; the
        StatsFrame proto keeps the 5 classic fields)."""
        from filodb_tpu.query.proto_plan import (frames_to_result,
                                                 result_to_frames)
        from filodb_tpu.query.rangevector import QueryResult, QueryStats

        res = QueryResult()
        res.stats = QueryStats(
            series_scanned=7, samples_scanned=700, cpu_ns=5, bytes_staged=99,
            kernel_ns=123_456, cache_hits=2, cache_misses=1, cache_extends=3,
        )
        got = frames_to_result(list(result_to_frames(res, stats_ext=True)))
        assert got.stats.as_dict() == res.stats.as_dict()
        # origin-opt-in: without the capability flag (an older origin) the
        # StatsExt frame must NOT be emitted — classic fields only
        legacy = frames_to_result(list(result_to_frames(res)))
        assert legacy.stats.kernel_ns == 0
        assert legacy.stats.bytes_staged == 99

    def test_trace_true_carries_resource_stats(self):
        ms = _make_store()
        eng = QueryEngine(ms, "ds")
        srv, port = serve_background(eng)
        try:
            out = _get_json(
                f"http://127.0.0.1:{port}/api/v1/query_range?query="
                + urllib.parse.quote(Q)
                + f"&start={START}&end={(BASE + 900_000) / 1000}&step=60"
                + "&trace=true"
            )["data"]
            st = out["stats"]
            assert st["kernelSeconds"] > 0
            assert st["cacheMisses"] >= 1
            assert {"cacheHits", "cacheExtends"} <= set(st)
            root_stats = out["trace"]["stats"]
            assert root_stats["kernel_ns"] > 0
            assert root_stats["cache_misses"] >= 1
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# self-scrape: the _system dataset


class TestSelfScrape:
    def test_rate_over_system_dataset_through_standard_api(self):
        from filodb_tpu.telemetry import SYSTEM_DATASET, SelfScraper

        ms = _make_store()
        eng = QueryEngine(ms, "ds")
        ms.setup(Dataset(SYSTEM_DATASET), range(4))
        scraper = SelfScraper(ms, interval_s=3600)
        sys_engine = QueryEngine(ms, SYSTEM_DATASET)
        now = int(time.time() * 1000)
        end = (BASE + 900_000) / 1000
        for k in range(5):
            eng.query_range(Q, START + k, end, STEP)  # grow dispatch counts
            n = scraper.scrape_once(now_ms=now + k * 15_000)
            assert n > 0
        srv, port = serve_background(
            eng, dataset_engines={SYSTEM_DATASET: sys_engine}
        )
        try:
            q = "rate(filodb_kernel_dispatch_seconds_count[5m])"
            out = _get_json(
                f"http://127.0.0.1:{port}/api/v1/query_range"
                f"?dataset={SYSTEM_DATASET}&query=" + urllib.parse.quote(q)
                + f"&start={(now + 30_000) / 1000}"
                + f"&end={(now + 60_000) / 1000}&step=15"
            )["data"]
            vals = [
                float(v) for series in out["result"]
                for _, v in series["values"] if v != "NaN"
            ]
            assert vals and max(vals) > 0  # the server's own dispatch rate
        finally:
            srv.shutdown()
        # histogram _count series landed in the counter schema (the parser
        # types histogram-family suffixes as cumulative)
        sh_schemas = {
            p.schema.name
            for sh in ms.shards(SYSTEM_DATASET)
            for p in sh.partitions.values()
            if p.tags.get("_metric_", "").endswith("_count")
        }
        assert sh_schemas <= {"prom-counter"}

    def test_scrape_counters_and_server_config_gate(self):
        from filodb_tpu.telemetry import SYSTEM_DATASET, SelfScraper

        ms = _make_store()
        ms.setup(Dataset(SYSTEM_DATASET), range(4))
        before = _counter("filodb_self_scrapes")
        scraper = SelfScraper(ms, interval_s=3600)
        scraper.scrape_once()
        assert _counter("filodb_self_scrapes") == before + 1
        assert _counter("filodb_self_scrape_samples") > 0

    def test_server_config_gate_end_to_end(self, tmp_path):
        """FiloServer with telemetry.self_scrape_interval_s wires the
        scraper + a _system engine, and ?dataset=_system answers PromQL
        over the server's own metrics through the standard query API."""
        from filodb_tpu.server import FiloServer
        from filodb_tpu.telemetry import SYSTEM_DATASET

        srv = FiloServer({
            "dataset": "ds",
            "shards": 2,
            "store_root": str(tmp_path / "store"),
            "telemetry": {"self_scrape_interval_s": 3600},
        })
        port = srv.start(port=0)
        try:
            assert srv.self_scraper is not None
            assert srv.system_engine is not None
            srv.memstore.ingest_routed(
                "ds",
                counter_batch(n_series=6, n_samples=N_SAMPLES, start_ms=BASE),
                spread=1,
            )
            now = int(time.time() * 1000)
            for k in range(5):
                # grow the server's own kernel-dispatch counts between
                # scrapes via real queries (distinct windows defeat caching)
                _get_json(
                    f"http://127.0.0.1:{port}/api/v1/query_range?query="
                    + urllib.parse.quote(Q)
                    + f"&start={START + k}&end={(BASE + 900_000) / 1000}&step=60"
                )
                assert srv.self_scraper.scrape_once(now_ms=now + k * 15_000) > 0
            q = "rate(filodb_kernel_dispatch_seconds_count[5m])"
            out = _get_json(
                f"http://127.0.0.1:{port}/api/v1/query_range"
                f"?dataset={SYSTEM_DATASET}&query=" + urllib.parse.quote(q)
                + f"&start={(now + 30_000) / 1000}"
                + f"&end={(now + 60_000) / 1000}&step=15"
            )["data"]
            vals = [
                float(v) for series in out["result"]
                for _, v in series["values"] if v != "NaN"
            ]
            assert vals and max(vals) > 0
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# slow-query log ring under concurrency


class TestSlowQueryRing:
    def test_concurrent_record_vs_configure_resize(self):
        log = SlowQueryLog(max_entries=8)
        errors: list = []
        stop = threading.Event()

        def recorder(tid: int):
            try:
                i = 0
                while not stop.is_set():
                    log.record(f"q{tid}-{i}", 1.0, dataset="ds")
                    i += 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def resizer():
            try:
                for n in (4, 16, 2, 32, 8) * 10:
                    log.configure(n)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=recorder, args=(t,)) for t in range(4)]
        rt = threading.Thread(target=resizer)
        for t in threads:
            t.start()
        rt.start()
        rt.join()
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors
        # final capacity from the last configure call wins
        assert len(log.entries()) <= 8
        log.record("final", 2.0, dataset="ds")
        assert log.entries()[0]["promql"] == "final"  # newest first

    def test_ring_ordering_newest_first(self):
        log = SlowQueryLog(max_entries=3)
        for i in range(7):
            log.record(f"q{i}", float(i), dataset="ds")
        got = [e["promql"] for e in log.entries()]
        assert got == ["q6", "q5", "q4"]

    def test_threshold_edge_records_at_exact_threshold(self):
        """_observe_slow records when elapsed >= threshold (never under)."""
        ms = _make_store(n_shards=1, n_series=2)
        eng = QueryEngine(ms, "ds",
                          PlannerParams(slow_query_threshold_s=0.0))
        from filodb_tpu.metrics import SLOW_QUERY_LOG

        SLOW_QUERY_LOG.clear()
        eng.query_range(Q, START, (BASE + 900_000) / 1000, STEP)
        entries = SLOW_QUERY_LOG.entries()
        assert entries and entries[0]["promql"] == Q
        # entries carry the new resource stats
        assert "kernel_ns" in entries[0]["stats"]
        SLOW_QUERY_LOG.clear()
        off = QueryEngine(ms, "ds",
                          PlannerParams(slow_query_threshold_s=None))
        off.query_range(Q, START, (BASE + 900_000) / 1000, STEP)
        assert not SLOW_QUERY_LOG.entries()


# ---------------------------------------------------------------------------
# registry: remove / aging / HELP-TYPE / OpenMetrics / exemplars


class TestRegistrySeries:
    def test_remove_series(self):
        r = Registry()
        r.gauge("g", a="1").set(5)
        assert 'g{a="1"} 5' in r.expose()
        assert r.remove("g", a="1") is True
        assert 'g{a="1"}' not in r.expose()
        assert r.remove("g", a="1") is False

    def test_tenant_series_age_out_on_publish(self):
        from filodb_tpu.metering import TenantIngestionMetering

        class _Rec:
            def __init__(self, prefix):
                self.prefix = prefix
                self.ts_count = 5
                self.active_ts_count = 3

        class _Card:
            def __init__(self):
                self.recs = [_Rec(("wsX", "nsX")), _Rec(("wsY", "nsY"))]

            def scan(self, prefix, depth):
                return list(self.recs)

        class _Shard:
            cardinality = _Card()

        class _MS:
            def shards(self, ds):
                return [_Shard]

        m = TenantIngestionMetering(_MS(), "ds")
        assert m.publish() == 2
        assert 'filodb_tenant_ts_total{ns="nsX",ws="wsX"}' in REGISTRY.expose()
        _Shard.cardinality.recs = [_Rec(("wsY", "nsY"))]  # wsX vanished
        assert m.publish() == 1
        text = REGISTRY.expose()
        assert 'ws="wsX"' not in text.split("filodb_tenant_ts_total", 1)[-1] \
            .split("\n# ", 1)[0]
        assert 'filodb_tenant_ts_total{ns="nsY",ws="wsY"}' in text

    def test_help_and_type_lines(self):
        r = Registry()
        r.counter("filodb_queries", dataset="ds").inc()
        r.gauge("up").set(1)
        r.histogram("lat").observe(0.2)
        text = r.expose()
        assert "# TYPE filodb_queries_total counter" in text
        assert "# HELP filodb_queries_total " in text
        assert "# TYPE up gauge" in text
        assert "# TYPE lat histogram" in text
        r.describe("up", "custom help")
        assert "# HELP up custom help" in r.expose()

    def test_openmetrics_negotiation_and_exemplars(self):
        r = Registry()
        r.counter("filodb_queries", dataset="ds").inc(3)
        r.histogram("lat").observe(0.003, exemplar={"trace_id": "abc123"})
        om = r.expose(openmetrics=True)
        assert "# TYPE filodb_queries counter" in om  # family w/o _total
        assert "filodb_queries_total{" in om  # sample keeps the suffix
        assert om.rstrip().endswith("# EOF")
        assert '# {trace_id="abc123"} 0.003' in om
        # text format 0.0.4 stays exemplar-free
        assert "trace_id" not in r.expose()

    def test_http_content_negotiation(self):
        ms = _make_store(n_shards=1, n_series=2)
        eng = QueryEngine(ms, "ds")
        eng.query_range(Q, START, (BASE + 900_000) / 1000, STEP)
        srv, port = serve_background(eng)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            with urllib.request.urlopen(req) as resp:
                assert "openmetrics-text" in resp.headers["Content-Type"]
                body = resp.read().decode()
            assert body.rstrip().endswith("# EOF")
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as resp:
                assert "text/plain" in resp.headers["Content-Type"]
                assert "# EOF" not in resp.read().decode()
        finally:
            srv.shutdown()

    def test_latency_histogram_carries_trace_exemplar(self):
        ms = _make_store(n_shards=1, n_series=2)
        eng = QueryEngine(ms, "ds")
        res = eng.query_range(Q, START, (BASE + 900_000) / 1000, STEP)
        om = REGISTRY.expose(openmetrics=True)
        line = next(
            l for l in om.splitlines()
            if l.startswith("filodb_query_latency_seconds_bucket")
            and "trace_id" in l
        )
        assert res.trace.trace_id[:4] in line or "trace_id=" in line


# ---------------------------------------------------------------------------
# tpu-watch probe gauges


class TestTpuWatchCollector:
    def test_log_parses_into_gauges(self, tmp_path):
        from filodb_tpu.telemetry import register_tpu_watch_collector

        log = tmp_path / "TPU_WATCH_LOG.txt"
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        log.write_text(
            f"{stamp} watchdog start: probe every 120s\n"
            f"{stamp} probe TIMEOUT after 30s (wedged plugin)\n"
            f"{stamp} probe FAIL rc=1: no device\n"
            f"{stamp} probe OK: TPU_OK tpu v5e\n"
            f"{stamp} ATTESTED quick: {{}}\n"
        )
        r = Registry()
        register_tpu_watch_collector(str(log), registry=r)
        text = r.expose()
        assert "filodb_tpu_probes 3" in text
        assert "filodb_tpu_probes_ok 1" in text
        assert "filodb_tpu_probe_healthy 1" in text
        assert "filodb_tpu_bench_attested 1" in text
        # empty/missing log: healthy gauge reads -1, never crashes
        r2 = Registry()
        register_tpu_watch_collector(str(tmp_path / "missing.txt"), registry=r2)
        assert "filodb_tpu_probe_healthy -1" in r2.expose()
