"""Tests for auxiliary components: index metadata store, partkey sync,
spread provider (model: reference IndexMetadataStore / synchronization /
spread-assignment specs)."""

import numpy as np

from filodb_tpu.coordinator.spread import SpreadChange, SpreadProvider
from filodb_tpu.core.filters import equals
from filodb_tpu.memstore.index import PartKeyIndex
from filodb_tpu.memstore.index_metadata import (
    EphemeralIndexMetadataStore,
    FileIndexMetadataStore,
    IndexState,
)
from filodb_tpu.memstore.synchronization import (
    PartKeyUpdatesConsumer,
    PartKeyUpdatesPublisher,
)


class TestIndexMetadata:
    def test_lifecycle(self):
        s = EphemeralIndexMetadataStore()
        assert s.get("ds", 0).state == IndexState.EMPTY
        s.update("ds", 0, IndexState.BUILDING, 1000)
        s.update("ds", 0, IndexState.SYNCED, 2000)
        m = s.get("ds", 0)
        assert m.state == IndexState.SYNCED and m.checkpoint_ms == 2000

    def test_file_backed_survives_restart(self, tmp_path):
        s1 = FileIndexMetadataStore(str(tmp_path))
        s1.update("ds", 3, IndexState.BUILDING, 5000)
        s2 = FileIndexMetadataStore(str(tmp_path))
        m = s2.get("ds", 3)
        assert m.state == IndexState.BUILDING and m.checkpoint_ms == 5000


class TestPartKeySync:
    def test_publish_drain_apply(self):
        pub = PartKeyUpdatesPublisher(shard_num=2)
        for i in range(5):
            pub.publish({"_metric_": f"m{i}", "host": "a"}, start_ts=i * 100)
        updates = pub.drain()
        assert len(updates) == 5 and not pub.updates
        peer = PartKeyIndex()
        n = PartKeyUpdatesConsumer(peer).apply(updates)
        assert n == 5
        assert len(peer.part_ids_from_filters([equals("host", "a")], 0, 2**62)) == 5

    def test_capacity_drops(self):
        pub = PartKeyUpdatesPublisher(0, capacity=2)
        for i in range(4):
            pub.publish({"m": str(i)}, 0)
        assert len(pub.updates) == 2 and pub.dropped == 2


class TestSpreadProvider:
    def test_default_and_override(self):
        sp = SpreadProvider(3, [
            SpreadChange((("_ns_", "big-app"), ("_ws_", "demo")), 6),
        ])
        assert sp.spread_for({"_ws_": "demo", "_ns_": "small"}) == 3
        assert sp.spread_for({"_ws_": "demo", "_ns_": "big-app"}) == 6

    def test_from_config(self):
        sp = SpreadProvider.from_config({
            "default": 2,
            "overrides": [{"keys": {"_ws_": "w"}, "spread": 5}],
        })
        assert sp.spread_for({"_ws_": "other"}) == 2
        assert sp.spread_for({"_ws_": "w", "_ns_": "anything"}) == 5
