"""Multi-cluster federation integration test: two live servers, queries
spanning both via PromQL-over-HTTP remote execs (model: reference multi-jvm
specs + MultiPartitionPlannerSpec executed end-to-end)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine, SingleClusterPlanner
from filodb_tpu.coordinator.planners import (
    HighAvailabilityPlanner,
    FailureTimeRange,
    MultiPartitionPlanner,
    PartitionAssignment,
)
from filodb_tpu.query.exec.plans import QueryContext
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.server import FiloServer
from filodb_tpu.testkit import counter_batch

BASE = 1_600_000_000_000
START_S = (BASE + 600_000) / 1000
END_S = (BASE + 1_800_000) / 1000


@pytest.fixture(scope="module")
def two_clusters():
    """Cluster A holds _ns_=App-A, cluster B holds _ns_=App-B."""
    srv_a = FiloServer({"dataset": "prometheus", "shards": 2})
    srv_b = FiloServer({"dataset": "prometheus", "shards": 2})
    port_a = srv_a.start(port=0)
    port_b = srv_b.start(port=0)
    srv_a.memstore.ingest_routed(
        "prometheus", counter_batch(n_series=6, n_samples=200, start_ms=BASE, ns="App-A"), spread=1)
    srv_b.memstore.ingest_routed(
        "prometheus", counter_batch(n_series=4, n_samples=200, start_ms=BASE, ns="App-B"), spread=1)
    yield srv_a, srv_b, f"http://127.0.0.1:{port_a}", f"http://127.0.0.1:{port_b}"
    srv_a.stop()
    srv_b.stop()


def test_remote_partition_query_over_http(two_clusters):
    srv_a, srv_b, _, url_b = two_clusters
    local = SingleClusterPlanner(srv_a.memstore, "prometheus")

    def locate(keys):
        if keys.get("_ns_") == "App-B":
            return PartitionAssignment("b", url_b)
        return PartitionAssignment("a", None)

    mp = MultiPartitionPlanner(local, locate)
    plan = query_range_to_logical_plan(
        'sum(rate(http_requests_total{_ns_="App-B"}[5m]))', START_S, END_S, 60)
    res = mp.materialize(plan).execute(QueryContext(srv_a.memstore, "prometheus"))
    # matches what cluster B computes locally
    want = QueryEngine(srv_b.memstore, "prometheus").query_range(
        'sum(rate(http_requests_total{_ns_="App-B"}[5m]))', START_S, END_S, 60)
    got_vals = res.grids[0].values_np()
    want_vals = want.grids[0].values_np()
    np.testing.assert_allclose(got_vals, want_vals, rtol=1e-3, equal_nan=True)


def test_cross_partition_binary_join_over_http(two_clusters):
    srv_a, _, _, url_b = two_clusters
    local = SingleClusterPlanner(srv_a.memstore, "prometheus")

    def locate(keys):
        if keys.get("_ns_") == "App-B":
            return PartitionAssignment("b", url_b)
        return PartitionAssignment("a", None)

    mp = MultiPartitionPlanner(local, locate)
    plan = query_range_to_logical_plan(
        'sum(rate(http_requests_total{_ns_="App-A"}[5m]))'
        ' + sum(rate(http_requests_total{_ns_="App-B"}[5m]))',
        START_S, END_S, 60)
    res = mp.materialize(plan).execute(QueryContext(srv_a.memstore, "prometheus"))
    series = list(res.all_series())
    assert len(series) == 1
    _, _, vals = series[0]
    assert (vals > 0).all()


def test_ha_failover_executes_remotely(two_clusters):
    """Local cluster marked failed for a window: those steps must come from
    the buddy over HTTP and stitch with local results."""
    srv_a, srv_b, _, url_b = two_clusters
    # buddy (B) needs the same data as A for failover semantics; give it App-A too
    srv_b.memstore.ingest_routed(
        "prometheus", counter_batch(n_series=6, n_samples=200, start_ms=BASE, ns="App-A"), spread=1)
    local = SingleClusterPlanner(srv_a.memstore, "prometheus")
    fail = FailureTimeRange(BASE + 900_000, BASE + 1_200_000)
    ha = HighAvailabilityPlanner(local, url_b, lambda: [fail])
    plan = query_range_to_logical_plan(
        'sum(rate(http_requests_total{_ns_="App-A"}[5m]))', START_S, END_S, 60)
    res = ha.materialize(plan).execute(QueryContext(srv_a.memstore, "prometheus"))
    want = QueryEngine(srv_b.memstore, "prometheus").query_range(
        'sum(rate(http_requests_total{_ns_="App-A"}[5m]))', START_S, END_S, 60)
    got_map = {tuple(l.items()): (t, v) for l, t, v in res.all_series()}
    want_map = {tuple(l.items()): (t, v) for l, t, v in want.all_series()}
    assert got_map.keys() == want_map.keys()
    for k in got_map:
        tg, vg = got_map[k]
        tw, vw = want_map[k]
        np.testing.assert_array_equal(tg, tw)
        np.testing.assert_allclose(vg, vw, rtol=1e-3)


def test_remote_partition_query_over_grpc(two_clusters):
    """Federation over the binary plan transport: the foreign-partition
    subtree ships as protobuf to cluster B's gRPC RemoteExec."""
    from filodb_tpu.api.grpc_exec import serve_grpc

    srv_a, srv_b, _, _ = two_clusters
    gsrv, gport = serve_grpc(srv_b.engine, port=0, host="127.0.0.1")
    try:
        local = SingleClusterPlanner(srv_a.memstore, "prometheus")

        def locate(keys):
            if keys.get("_ns_") == "App-B":
                return PartitionAssignment("b", f"grpc://127.0.0.1:{gport}")
            return PartitionAssignment("a", None)

        mp = MultiPartitionPlanner(local, locate)
        q = 'sum(rate(http_requests_total{_ns_="App-B"}[5m]))'
        plan = query_range_to_logical_plan(q, START_S, END_S, 60)
        tree = mp.materialize(plan)
        assert type(tree).__name__ == "GrpcPlanRemoteExec"
        res = tree.execute(QueryContext(srv_a.memstore, "prometheus"))
        want = QueryEngine(srv_b.memstore, "prometheus").query_range(
            q, START_S, END_S, 60)
        np.testing.assert_allclose(
            res.grids[0].values_np(), want.grids[0].values_np(),
            rtol=1e-3, equal_nan=True)
    finally:
        gsrv.stop(grace=0)
