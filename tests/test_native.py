"""C++ codec library parity tests (model: reference cargo tests for
filodb_core + DoubleVectorSimdBenchmark correctness checks)."""

import numpy as np
import pytest

from filodb_tpu import native
from filodb_tpu.core import encodings as E


@pytest.fixture(scope="module")
def has_native():
    if native.lib() is None:
        pytest.skip("native library unavailable (no g++?)")
    return True


class TestNativeNibblePack:
    @pytest.mark.parametrize("seed", range(4))
    def test_pack_parity_with_python(self, has_native, seed):
        rng = np.random.default_rng(seed)
        choices = [
            rng.integers(0, 2**63, 1000, dtype=np.uint64),
            (rng.integers(0, 2**20, 777, dtype=np.uint64) << np.uint64(12)),
            np.zeros(100, dtype=np.uint64),
            rng.integers(0, 3, 511, dtype=np.uint64),
        ]
        v = choices[seed % len(choices)]
        assert native.nibble_pack_native(v) == E._nibble_pack_py(v)

    def test_unpack_parity(self, has_native):
        rng = np.random.default_rng(7)
        v = rng.integers(0, 2**50, 999, dtype=np.uint64)
        packed = E._nibble_pack_py(v)
        np.testing.assert_array_equal(native.nibble_unpack_native(packed, len(v)), v)

    def test_roundtrip_through_dispatch(self, has_native):
        # encodings.nibble_pack now routes through C++; full roundtrip
        rng = np.random.default_rng(9)
        v = rng.integers(0, 2**40, 10_000, dtype=np.uint64)
        np.testing.assert_array_equal(E.nibble_unpack(E.nibble_pack(v), len(v)), v)

    def test_malformed_input_rejected(self, has_native):
        out = native.nibble_unpack_native(b"\x01", 8)  # truncated group
        assert out is None


class TestNanReductions:
    def test_nan_sum_matches_numpy(self, has_native):
        rng = np.random.default_rng(1)
        v = rng.standard_normal(100_000)
        v[rng.integers(0, len(v), 1000)] = np.nan
        assert abs(native.nan_sum(v) - np.nansum(v)) < 1e-6
        assert native.nan_count(v) == np.count_nonzero(~np.isnan(v))

    def test_all_nan(self, has_native):
        v = np.full(100, np.nan)
        assert native.nan_sum(v) == 0.0
        assert native.nan_count(v) == 0


class TestEncodedColumnsViaNative:
    def test_double_vector_roundtrip_large(self, has_native):
        rng = np.random.default_rng(3)
        v = 50 + rng.standard_normal(50_000)
        enc = E.encode_double(v)
        np.testing.assert_array_equal(E.decode_double(enc), v)

    def test_timestamps_roundtrip_large(self, has_native):
        ts = 1_600_000_000_000 + np.arange(50_000, dtype=np.int64) * 10_000
        ts += np.random.default_rng(4).integers(-100, 100, 50_000)
        enc = E.encode_int64(ts)
        np.testing.assert_array_equal(E.decode(enc), ts)
