"""Selective on-demand paging: page-ins read only the needed frames via the
store manifest (reference OnDemandPagingShard.scala:147 +
CassandraColumnStore.readRawPartitions:774 — bytes read scale with the query,
not with the store)."""

import os

import numpy as np

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset, canonical_partkey
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.store.columnstore import LocalColumnStore
from filodb_tpu.store.flush import FlushCoordinator
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def _store_bytes(root):
    total = 0
    for dp, _, fns in os.walk(root):
        for fn in fns:
            if fn.startswith("chunks-"):
                total += os.path.getsize(os.path.join(dp, fn))
    return total


def _setup(tmp_path, n_series=50, n_samples=300):
    store = LocalColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100, retention_ms=1_000_000))
    ms.setup(Dataset("ds"), [0])
    sh = ms.shard("ds", 0)
    sh.odp_store = store
    ms.ingest("ds", 0, machine_metrics(n_series=n_series, n_samples=n_samples, start_ms=BASE))
    FlushCoordinator(ms, store).flush_shard("ds", 0)
    return store, ms, sh


class TestSelectiveRead:
    def test_manifest_written_with_frames(self, tmp_path):
        store, ms, sh = _setup(tmp_path, n_series=4)
        mpath = tmp_path / "ds" / "shard-0" / "manifest.jsonl"
        assert mpath.exists()
        entries = store._manifest("ds", 0)
        # 4 series x 3 sealed chunks of 100 (last partial stays in buffer)
        assert len(entries) == sum(
            1 for _ in store.read_chunks("ds", 0)
        )

    def test_selective_matches_full_scan(self, tmp_path):
        store, ms, sh = _setup(tmp_path, n_series=6)
        part = next(iter(sh.partitions.values()))
        pk = part.partkey
        want = [
            (h["start"], h["end"])
            for h, _, _ in store.read_chunks("ds", 0)
            if canonical_partkey(h["tags"]) == pk
        ]
        got = [
            (h["start"], h["end"])
            for h, _, _ in store.read_chunks_selective("ds", 0, [pk], 0, 2**62)
        ]
        assert sorted(got) == sorted(want) and len(got) > 0

    def test_bytes_read_proportional_to_request(self, tmp_path):
        """VERDICT done-criterion: bytes-read proportional to the queried
        partitions, not the store."""
        store, ms, sh = _setup(tmp_path, n_series=50)
        total = _store_bytes(tmp_path)
        part = next(iter(sh.partitions.values()))
        store.stats_selective_bytes = 0
        got = list(store.read_chunks_selective("ds", 0, [part.partkey], 0, 2**62))
        assert len(got) == 3  # this series' sealed chunks only
        # 1 of 50 series: selective read must touch ~2% of the store
        assert store.stats_selective_bytes < total * 0.05

    def test_time_range_prunes_frames(self, tmp_path):
        store, ms, sh = _setup(tmp_path, n_series=4)
        part = next(iter(sh.partitions.values()))
        # only the first sealed chunk overlaps [BASE, BASE+500s]
        got = list(store.read_chunks_selective("ds", 0, [part.partkey], BASE, BASE + 500_000))
        assert len(got) == 1

    def test_premanifest_store_falls_back(self, tmp_path):
        store, ms, sh = _setup(tmp_path, n_series=4)
        os.remove(tmp_path / "ds" / "shard-0" / "manifest.jsonl")
        store._manifest_cache.clear()
        part = next(iter(sh.partitions.values()))
        got = list(store.read_chunks_selective("ds", 0, [part.partkey], 0, 2**62))
        assert len(got) == 3

    def test_premanifest_store_backfilled_on_next_flush(self, tmp_path):
        """Upgrade path: a shard written before manifests existed gets its
        manifest rebuilt from the segments on the next flush, so selective
        reads see pre-upgrade chunks too."""
        store, ms, sh = _setup(tmp_path, n_series=4, n_samples=250)
        os.remove(tmp_path / "ds" / "shard-0" / "manifest.jsonl")
        store._manifest_cache.clear()
        # more data + flush -> backfill then append
        ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=300, start_ms=BASE + 2_500_000))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        part = next(iter(sh.partitions.values()))
        got = list(store.read_chunks_selective("ds", 0, [part.partkey], 0, 2**62))
        full = [
            h for h, _, _ in store.read_chunks("ds", 0)
            if canonical_partkey(h["tags"]) == part.partkey
        ]
        assert len(got) == len(full) and len(got) >= 4

    def test_orphaned_frame_recovered_by_manifest_repair(self, tmp_path):
        """Review regression: a frame durable in the segment whose manifest
        line was lost (crash between the two appends) is re-indexed on the
        next manifest load — even when later appends wrote past it."""
        store, ms, sh = _setup(tmp_path, n_series=3)
        mpath = tmp_path / "ds" / "shard-0" / "manifest.jsonl"
        lines = mpath.read_bytes().splitlines(keepends=True)
        assert len(lines) >= 6
        # drop a MIDDLE entry: simulates the orphan with later appends intact
        mpath.write_bytes(b"".join(lines[:2] + lines[3:]))
        store._manifest_cache.clear()
        entries = store._manifest("ds", 0)
        assert len(entries) == len(lines)  # repair recovered the orphan
        # and the manifest file itself was healed
        store._manifest_cache.clear()
        assert len(store._manifest("ds", 0)) == len(lines)

    def test_torn_manifest_line_mid_file_skipped(self, tmp_path):
        """A merged/garbage line in the middle of the manifest corrupts only
        itself — later entries stay visible, and the repair pass re-indexes
        the frame the corrupted line described from the segment bytes."""
        store, ms, sh = _setup(tmp_path, n_series=2)
        mpath = tmp_path / "ds" / "shard-0" / "manifest.jsonl"
        lines = mpath.read_bytes().splitlines(keepends=True)
        assert len(lines) >= 4
        corrupted = lines[0] + b'{"pk": "dead' + b"".join(lines[1:])
        mpath.write_bytes(corrupted)
        store._manifest_cache.clear()
        entries = store._manifest("ds", 0)
        # the merged line destroyed one entry; gap repair recovered its frame
        assert len(entries) == len(lines)


class TestSelectiveOdpEndToEnd:
    def test_odp_pages_only_queried_partition(self, tmp_path):
        store, ms, sh = _setup(tmp_path, n_series=50)
        engine = QueryEngine(ms, "ds")
        full_start, full_end = (BASE + 600_000) / 1000, (BASE + 2_400_000) / 1000
        want = engine.query_range(
            'heap_usage0{instance="host-3"}', full_start, full_end, 60.0
        ).grids[0].values_np().copy()
        sh.evict_for_retention(now_ms=BASE + 300 * 10_000)
        store.stats_selective_bytes = 0
        got = engine.query_range(
            'heap_usage0{instance="host-3"}', full_start, full_end, 60.0
        )
        assert sh.odp_stats_pages > 0
        np.testing.assert_allclose(got.grids[0].values_np(), want, rtol=1e-5, equal_nan=True)
        # one of 50 series paged in: a full-scan page-in would read ~everything
        assert store.stats_selective_bytes < _store_bytes(tmp_path) * 0.1
