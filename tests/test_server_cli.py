"""Server lifecycle + CLI tests (model: reference FiloServer boot flow +
CliMain debug tools)."""

import json
import urllib.request
import urllib.parse

import numpy as np
import pytest

from filodb_tpu.cli import main as cli_main
from filodb_tpu.server import FiloServer
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def test_server_boot_flush_recover(tmp_path):
    cfg = {
        "dataset": "prometheus",
        "shards": 2,
        "store_root": str(tmp_path / "store"),
        "max_chunk_size": 100,
    }
    srv = FiloServer(cfg)
    port = srv.start(port=0)
    try:
        srv.memstore.ingest_routed(
            "prometheus", machine_metrics(n_series=6, n_samples=250, start_ms=BASE), spread=1
        )
        res = srv.flush_now()
        assert res.chunks_written > 0
        q = urllib.parse.quote("heap_usage0")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/v1/query?query={q}&time={(BASE + 2_000_000) / 1000}"
        ) as r:
            out = json.loads(r.read())
        assert len(out["data"]["result"]) == 6
    finally:
        srv.stop()

    # boot a second server on the same store: data must come back
    srv2 = FiloServer(cfg)
    port2 = srv2.start(port=0)
    try:
        assert sum(sh.num_partitions for sh in srv2.memstore.shards("prometheus")) == 6
        q = urllib.parse.quote("avg(heap_usage0)")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port2}/api/v1/query?query={q}&time={(BASE + 2_000_000) / 1000}"
        ) as r:
            out = json.loads(r.read())
        assert len(out["data"]["result"]) == 1
    finally:
        srv2.stop()


def test_cli_partkey(capsys):
    cli_main(["partkey", 'cpu{job="api", dc="us"}'])
    out = json.loads(capsys.readouterr().out)
    assert out["tags"]["_metric_"] == "cpu"
    assert "partkey_hash" in out and "shard" in out


def test_cli_against_server(tmp_path, capsys):
    srv = FiloServer({"dataset": "prometheus", "shards": 2})
    port = srv.start(port=0)
    host = f"http://127.0.0.1:{port}"
    try:
        csv_file = tmp_path / "in.csv"
        csv_file.write_text(
            "\n".join(f"cpu,host=h{i % 2},{BASE + i * 1000},{float(i)}" for i in range(20))
        )
        cli_main(["ingest-csv", "--host", host, str(csv_file)])
        out = json.loads(capsys.readouterr().out)
        assert out["data"]["ingested"] == 20
        cli_main(["labels", "--host", host])
        out = json.loads(capsys.readouterr().out)
        assert "host" in out["data"]
        cli_main(["query", "--host", host, "cpu", "--time", str((BASE + 100_000) / 1000)])
        out = json.loads(capsys.readouterr().out)
        assert len(out["data"]["result"]) == 2
    finally:
        srv.stop()


def test_server_downsamples_at_flush():
    srv = FiloServer({
        "shards": 1,
        "max_chunk_size": 100,
        "downsample": {"enabled": True, "periods_m": [5]},
    })
    srv.memstore.ingest("prometheus", 0,
                        machine_metrics(n_series=2, n_samples=300, start_ms=BASE))
    srv.flush_now()
    ds_shard = srv.memstore.shard("prometheus_5m", 0)
    assert ds_shard.num_partitions == 2
    part = ds_shard.partitions[0]
    ts, avg = part.samples_in_range(0, 2**62, "avg")
    assert len(ts) >= 9  # 300 samples @10s = 50min -> >=9 5m periods
    # downsampled data is queryable through a downsample planner
    from filodb_tpu.coordinator.planners import DownsampleClusterPlanner
    from filodb_tpu.query.exec.plans import QueryContext
    from filodb_tpu.query.promql import query_range_to_logical_plan

    planner = DownsampleClusterPlanner(srv.memstore, "prometheus_5m")
    plan = query_range_to_logical_plan(
        "max_over_time(heap_usage0[10m])", (BASE + 600_000) / 1000, (BASE + 2_400_000) / 1000, 300)
    res = planner.materialize(plan).execute(QueryContext(srv.memstore, "prometheus_5m"))
    assert sum(g.n_series for g in res.grids) == 2


def test_cli_admin_jobs(tmp_path, capsys):
    """downsample-batch, cardbust, copy-store against a flushed store."""
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.memstore.shard import StoreConfig
    from filodb_tpu.store.columnstore import LocalColumnStore
    from filodb_tpu.store.flush import FlushCoordinator

    src = str(tmp_path / "src")
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("prometheus"), [0])
    ms.ingest("prometheus", 0, machine_metrics(n_series=4, n_samples=300, start_ms=BASE))
    FlushCoordinator(ms, LocalColumnStore(src)).flush_shard("prometheus", 0)

    cli_main(["downsample-batch", "--store", src, "--periods", "5"])
    out = json.loads(capsys.readouterr().out)
    assert out["downsampled_rows"] > 0 and out["chunks_written"] > 0

    cli_main(["copy-store", "--src", src, "--dst", str(tmp_path / "dst")])
    out = json.loads(capsys.readouterr().out)
    assert out["partkeys_copied"] == 4

    cli_main(["cardbust", "--store", src, 'heap_usage0{instance="host-0"}'])
    out = json.loads(capsys.readouterr().out)
    assert out["series_deleted"] == 1


def test_downsample_datasets_persist_and_recover(tmp_path):
    cfg = {
        "shards": 1,
        "max_chunk_size": 100,
        "store_root": str(tmp_path / "store"),
        "downsample": {"enabled": True, "periods_m": [5]},
    }
    srv = FiloServer(cfg)
    srv.start(port=0)
    try:
        srv.memstore.ingest("prometheus", 0,
                            machine_metrics(n_series=2, n_samples=300, start_ms=BASE))
        srv.flush_now()
        assert srv.memstore.shard("prometheus_5m", 0).num_partitions == 2
    finally:
        srv.stop()
    # fresh boot: the downsample dataset must come back from the store
    srv2 = FiloServer(cfg)
    srv2.start(port=0)
    try:
        sh = srv2.memstore.shard("prometheus_5m", 0)
        assert sh.num_partitions == 2
        part = sh.partitions[0]
        ts, avg = part.samples_in_range(0, 2**62, "avg")
        assert len(ts) >= 9
    finally:
        srv2.stop()
