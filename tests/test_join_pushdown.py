"""Binary-join pushdown (reference materializeBinaryJoin pushdown,
SingleClusterPlanner.scala:640-760, gated by target-schema colocation).

Sound case here: a dataset sharded purely by (_ws_, _ns_) at spread 0 — the
target-schema analog — where any two series of one workspace/namespace
colocate, so joins run per shard and concatenate."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine, SingleClusterPlanner
from filodb_tpu.core.schemas import Dataset, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000
WSNS_OPTS = DatasetOptions(shard_key_columns=("_ws_", "_ns_"))


@pytest.fixture(scope="module")
def ms():
    m = TimeSeriesMemStore()
    m.setup(Dataset("prometheus", options=WSNS_OPTS), range(4))
    for ns in ("ns-a", "ns-b", "ns-c"):
        m.ingest_routed("prometheus", machine_metrics(
            n_series=4, n_samples=120, start_ms=BASE, metric="req_total", ns=ns), spread=0)
        m.ingest_routed("prometheus", machine_metrics(
            n_series=4, n_samples=120, start_ms=BASE, metric="err_total", ns=ns, seed=9), spread=0)
    return m


def _plan(ms, q, spread=0):
    pl = SingleClusterPlanner(ms, "prometheus", params=PlannerParams(spread=spread))
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    return pl.materialize(query_range_to_logical_plan(q, start, end, 60))


def test_golden_pushdown_plan(ms):
    """Different metrics join per shard: sound because the metric is NOT a
    shard-key column in this dataset."""
    ep = _plan(ms, "err_total / req_total")
    tree = ep.print_tree()
    assert tree.startswith("E~DistConcatExec"), tree
    # one join per shard that the data occupies, each below the concat
    assert tree.count("BinaryJoinExec") >= 2
    assert "ReduceAggregate" not in tree


def test_pushdown_parity_with_root_join(ms):
    """VERDICT done-criterion: engine result parity pushdown vs root join."""
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    eng_push = QueryEngine(ms, "prometheus", PlannerParams(spread=0))
    # spread=3 planner disables pushdown -> root join over the same data
    eng_root = QueryEngine(ms, "prometheus", PlannerParams(spread=3))
    q = "err_total / req_total"
    a = eng_push.query_range(q, start, end, 60)
    b = eng_root.query_range(q, start, end, 60)
    am = {tuple(sorted(g0.items())): g.values_np()[i]
          for g in a.grids for i, g0 in enumerate(g.labels)}
    bm = {tuple(sorted(g0.items())): g.values_np()[i]
          for g in b.grids for i, g0 in enumerate(g.labels)}
    assert set(am) == set(bm) and len(am) == 12
    for k in am:
        np.testing.assert_allclose(am[k], bm[k], rtol=1e-6, equal_nan=True)


def test_no_pushdown_when_matching_breaks_shard_keys(ms):
    # on(instance): pairs may cross namespaces -> cross shards -> root join
    ep = _plan(ms, 'err_total / on(instance, _ws_) req_total')
    assert ep.print_tree().startswith("E~BinaryJoinExec")


def test_no_pushdown_with_spread(ms):
    ep = _plan(ms, "err_total / req_total", spread=3)
    assert ep.print_tree().startswith("E~BinaryJoinExec")


def test_no_pushdown_when_metric_is_shard_key():
    """Default datasets key placement on the metric; default join matching
    ignores __name__, so pushdown must not fire."""
    m = TimeSeriesMemStore()
    m.setup(Dataset("prometheus"), range(4))
    m.ingest_routed("prometheus", machine_metrics(n_series=4, n_samples=60, start_ms=BASE), spread=0)
    pl = SingleClusterPlanner(m, "prometheus", params=PlannerParams(spread=0))
    start, end = (BASE + 400_000) / 1000, (BASE + 500_000) / 1000
    ep = pl.materialize(query_range_to_logical_plan("a / b", start, end, 60))
    assert ep.print_tree().startswith("E~BinaryJoinExec")


def test_set_op_pushdown(ms):
    ep = _plan(ms, "err_total and req_total")
    assert ep.print_tree().startswith("E~DistConcatExec")
    # parity
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    eng_push = QueryEngine(ms, "prometheus", PlannerParams(spread=0))
    eng_root = QueryEngine(ms, "prometheus", PlannerParams(spread=3))
    a = eng_push.query_range("err_total and req_total", start, end, 60)
    b = eng_root.query_range("err_total and req_total", start, end, 60)
    n_a = sum(g.n_series for g in a.grids)
    n_b = sum(g.n_series for g in b.grids)
    assert n_a == n_b > 0


def test_no_pushdown_on_empty_on(ms):
    """Review regression: explicit on() matches on the empty key — pairs
    cross shards, so pushdown must not fire."""
    ep = _plan(ms, "err_total and on() req_total")
    assert ep.print_tree().startswith("E~SetOperatorExec")
