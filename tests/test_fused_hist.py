"""Fused histogram & epilogue pipeline (doc/perf.md).

Parity contract: the single-dispatch histogram path (3-D superblock ->
fused hist range_fn -> per-bucket segment-sum -> optional device-side
histogram_quantile) and the fused topk/bottomk/quantile epilogues must
agree with the reference scatter/partial-merge tree — identical NaN masks
and label sets, values within float32 accumulation-order tolerance — across
native-histogram selectors, classic-histogram suffix rewrites (_sum /
_count / _bucket incl. le= and +Inf selection), and heterogeneous bucket
schemes across shards.

Plus the O(1) dispatch guarantee: the canonical SRE query
``histogram_quantile(0.99, sum by (le) (rate(m_bucket[5m])))`` plans to the
fused path (no fused_fallback span tag) and issues exactly ONE kernel
dispatch warm; topk/quantile epilogues likewise, returning only [k, J] /
[G, J] arrays to the host.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.histograms import custom_buckets
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import METRIC_TAG, PROM_HISTOGRAM, Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import counter_batch, histogram_batch

pytestmark = pytest.mark.perf

BASE = 1_600_000_000_000
N_SHARDS = 4
START = (BASE + 600_000) / 1000
END = START + 900
STEP = 60

HQ_QUERY = (
    'histogram_quantile(0.99, '
    'sum by (le) (rate(http_request_latency_bucket[5m])))'
)


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    ms.ingest_routed(
        "ds",
        histogram_batch(n_series=24, n_samples=240, start_ms=BASE,
                        metric="http_request_latency"),
        spread=2,
    )
    ms.ingest_routed(
        "ds", counter_batch(n_series=24, n_samples=240, start_ms=BASE),
        spread=2,
    )
    return ms


@pytest.fixture(scope="module")
def engines(store):
    fused = QueryEngine(store, "ds")
    ref = QueryEngine(store, "ds", PlannerParams(fused_aggregate=False))
    return fused, ref


def _rows(res):
    out = {}
    for g in res.grids:
        for lbls, vals in zip(g.labels, g.values_np()):
            out[tuple(sorted(lbls.items()))] = np.asarray(vals)
    return out


def _hist_rows(res):
    out = {}
    for g in res.grids:
        h = g.hist_np()
        if h is None:
            continue
        for lbls, cube in zip(g.labels, h):
            out[tuple(sorted(lbls.items()))] = (np.asarray(cube),
                                                np.asarray(g.les, np.float64))
    return out


def assert_parity(fused, ref, q, start=START, end=END, step=STEP, **kw):
    rf = fused.query_range(q, start, end, step, **kw)
    rr = ref.query_range(q, start, end, step, **kw)
    a, b = _rows(rf), _rows(rr)
    assert a.keys() == b.keys(), (q, sorted(a), sorted(b))
    for k in a:
        na, nb = np.isnan(a[k]), np.isnan(b[k])
        assert (na == nb).all(), (q, k, "NaN masks differ")
        np.testing.assert_allclose(
            a[k][~na], b[k][~nb], rtol=2e-5, atol=1e-6, err_msg=f"{q} {k}"
        )
    ha, hb = _hist_rows(rf), _hist_rows(rr)
    assert ha.keys() == hb.keys(), q
    for k in ha:
        ca, la = ha[k]
        cb, lb = hb[k]
        np.testing.assert_allclose(la, lb, err_msg=f"{q} {k} les")
        na, nb = np.isnan(ca), np.isnan(cb)
        assert (na == nb).all(), (q, k, "hist NaN masks differ")
        np.testing.assert_allclose(
            ca[~na], cb[~nb], rtol=2e-5, atol=1e-6, err_msg=f"{q} {k} hist"
        )
    return rf, rr


def _plan_root(engine, q, start=START, end=END, step=STEP):
    from filodb_tpu.query.promql import query_range_to_logical_plan

    plan = query_range_to_logical_plan(q, start, end, step)
    return engine.planner.materialize(plan)


def _dispatch_total() -> int:
    from filodb_tpu.testkit import kernel_dispatch_total

    return kernel_dispatch_total()


def _fallback_counts() -> dict:
    from filodb_tpu.metrics import REGISTRY

    out = {}
    with REGISTRY._lock:
        for (name, lbls), m in REGISTRY._metrics.items():
            if name == "filodb_fused_fallback":
                out[dict(lbls)["reason"]] = m.value
    return out


def _span_names_and_fallbacks(sp, acc):
    acc.append((sp.name, sp.tags.get("fused_fallback")))
    for c in sp.children:
        _span_names_and_fallbacks(c, acc)
    return acc


# -- histogram parity --------------------------------------------------------


@pytest.mark.parametrize("q", [
    HQ_QUERY,
    "histogram_quantile(0.9, sum(rate(http_request_latency[5m])))",
    "histogram_quantile(0.5, sum(increase(http_request_latency[5m])))",
    "histogram_quantile(0.99, sum(sum_over_time(http_request_latency[3m])))",
    "histogram_quantile(0.9, sum(last_over_time(http_request_latency[3m])))",
    "histogram_quantile(0.9, sum by (instance) (rate(http_request_latency[5m])))",
])
def test_fused_hist_quantile_parity(engines, q):
    assert_parity(*engines, q)


@pytest.mark.parametrize("q", [
    "sum(rate(http_request_latency[5m]))",           # [G, J, B] hist grids
    "sum(rate(http_request_latency_bucket[5m]))",    # suffix -> native hist
    "sum(rate(http_request_latency_sum[5m]))",       # _sum column override
    "sum(rate(http_request_latency_count[5m]))",     # _count column override
    'sum(rate(http_request_latency_bucket{le="0.5"}[5m]))',   # one bucket
    'sum(rate(http_request_latency_bucket{le="+Inf"}[5m]))',  # top bucket
])
def test_fused_hist_suffix_parity(engines, q):
    assert_parity(*engines, q)


def test_fused_hist_missing_bucket_is_empty_on_both(engines):
    fused, ref = engines
    q = 'sum(rate(http_request_latency_bucket{le="0.123"}[5m]))'
    rf = fused.query_range(q, START, END, STEP)
    rr = ref.query_range(q, START, END, STEP)
    assert not _rows(rf) and not _rows(rr)


def test_fused_hist_plan_and_no_fallback(engines):
    fused, ref = engines
    root = _plan_root(fused, HQ_QUERY)
    assert type(root).__name__ == "FusedAggregateExec"
    assert root.hist_quantile == pytest.approx(0.99)
    assert type(_plan_root(ref, HQ_QUERY)).__name__ != "FusedAggregateExec"
    rf = fused.query_range(HQ_QUERY, START, END, STEP)
    spans = _span_names_and_fallbacks(rf.trace, [])
    assert not any(fb for _, fb in spans), spans  # no fused_fallback tag


def test_fused_hist_quantile_single_dispatch_warm(engines):
    fused, _ = engines
    for _ in range(2):  # stage + compile + fill every cache
        fused.query_range(HQ_QUERY, START, END, STEP)
    before = _dispatch_total()
    fused.query_range(HQ_QUERY, START, END, STEP)
    assert _dispatch_total() - before == 1, (
        "warm fused histogram_quantile(sum by (le) (rate)) must issue "
        "exactly ONE kernel dispatch"
    )


def test_fused_hist_unsupported_shapes_fall_back(engines):
    """Non-sum hist aggregates and non-hist range functions delegate to the
    reference tree (which raises the reference errors), tagging the span and
    bumping filodb_fused_fallback_total{reason=...}."""
    from filodb_tpu.query.exec.transformers import QueryError

    fused, _ = engines
    before = _fallback_counts()
    with pytest.raises(QueryError):
        fused.query_range(
            "sum(avg_over_time(http_request_latency[3m]))", START, END, STEP)
    with pytest.raises(QueryError):
        fused.query_range(
            "count(rate(http_request_latency[5m]))", START, END, STEP)
    after = _fallback_counts()
    assert after.get("hist_func", 0) == before.get("hist_func", 0) + 1
    assert after.get("hist_op", 0) == before.get("hist_op", 0) + 1


def test_hist_fallback_does_not_double_count_stats(store):
    """hist_op/hist_func fallbacks are decided BEFORE the fused path bumps
    scan stats (and, cold, before it stages a [S, T, B] superblock): only
    the reference tree's own bumps land, so per-request max_samples limits
    and EXPLAIN ANALYZE see the true scan count, not 2x."""
    from filodb_tpu.query.exec.plans import QueryContext
    from filodb_tpu.query.exec.transformers import QueryError

    q = "count(rate(http_request_latency[5m]))"
    scanned = []
    for params in (None, PlannerParams(fused_aggregate=False)):
        eng = QueryEngine(store, "ds", params)
        ctx = QueryContext(store, "ds")
        with pytest.raises(QueryError):
            _plan_root(eng, q).execute(ctx)
        scanned.append((ctx.stats.series_scanned, ctx.stats.samples_scanned))
    assert scanned[0] == scanned[1]
    assert scanned[0][1] > 0


def test_fused_fallback_counter_partial_results(engines):
    fused, ref = engines
    before = _fallback_counts()
    assert_parity(
        fused, ref, "sum(rate(http_request_latency[5m]))",
        allow_partial_results=True,
    )
    after = _fallback_counts()
    assert after.get("partial_results", 0) >= before.get("partial_results", 0) + 1


# -- heterogeneous bucket schemes across shards ------------------------------


def _hetero_store():
    """Scheme A on shards 0-1, scheme B (A plus two extra bounds) on shards
    2-3 — the mid-rollout shape. Cumulative counts are consistent across
    schemes, so the union remap is exact and both paths must agree."""
    rng = np.random.default_rng(5)
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(4)))
    scheme_a = custom_buckets([0.1, 0.5, 1, 5])
    scheme_b = custom_buckets([0.1, 0.25, 0.5, 1, 2.5, 5])
    m = 200
    ts = BASE + np.arange(m, dtype=np.int64) * 10_000
    for i in range(16):
        shard = i % 4
        scheme = scheme_a if shard < 2 else scheme_b
        b = scheme.num_buckets
        tags = {METRIC_TAG: "lat_hetero", "_ws_": "w", "_ns_": "n",
                "instance": f"h{i}"}
        incr = rng.poisson(2.0, size=(m, b)).astype(np.float64)
        incr[:, -1] = incr.sum(1)
        hist = np.cumsum(np.cumsum(incr, axis=1), axis=0)
        count = hist[:, -1]
        total = np.cumsum(rng.uniform(0, 5, size=m))
        ms.shard("ds", shard).ingest_series(SeriesBatch(
            PROM_HISTOGRAM, tags, ts,
            {"sum": total, "count": count, "h": hist},
            bucket_les=scheme.bounds(),
        ))
    return ms


def test_fused_hist_heterogeneous_schemes_parity():
    ms = _hetero_store()
    fused = QueryEngine(ms, "ds")
    ref = QueryEngine(ms, "ds", PlannerParams(fused_aggregate=False))
    start = (BASE + 400_000) / 1000
    for q in (
        "histogram_quantile(0.9, sum by (le) (rate(lat_hetero_bucket[5m])))",
        "sum(rate(lat_hetero[5m]))",
    ):
        rf, rr = assert_parity(fused, ref, q, start, start + 600, 60)
    # the merged scheme is the union of both shards' bounds
    hist = [g for g in fused.query_range(
        "sum(rate(lat_hetero[5m]))", start, start + 600, 60).grids
        if g.les is not None]
    assert len(hist) == 1
    np.testing.assert_allclose(
        np.asarray(hist[0].les, np.float64)[:-1],
        [0.1, 0.25, 0.5, 1, 2.5, 5],
    )
    assert np.isinf(np.asarray(hist[0].les, np.float64)[-1])


def test_bucket_slice_missing_scheme_parity_and_stats():
    """lat_hetero_bucket{le="0.25"}: scheme-A shards lack the bound and are
    dropped by the slice, scheme-B shards contribute. Values match the
    reference, and scanned-stats/limit accounting stays PRE-slice on both
    paths (the dropped shards were still scanned, exactly as the reference
    bumps before slicing) — on the superblock cache hit too."""
    from filodb_tpu.query.exec.plans import QueryContext

    ms = _hetero_store()
    fused = QueryEngine(ms, "ds")
    ref = QueryEngine(ms, "ds", PlannerParams(fused_aggregate=False))
    start = (BASE + 400_000) / 1000
    q = 'sum(rate(lat_hetero_bucket{le="0.25"}[5m]))'
    assert_parity(fused, ref, q, start, start + 600, 60)
    scanned = []
    for eng in (fused, fused, ref):  # 2nd fused run = superblock cache hit
        ctx = QueryContext(ms, "ds")
        res = _plan_root(eng, q, start, start + 600, 60).execute(ctx)
        assert res.grids
        scanned.append((ctx.stats.series_scanned, ctx.stats.samples_scanned))
    assert scanned[0] == scanned[1] == scanned[2]
    assert scanned[0][0] == 16  # all 16 series scanned, dropped shards incl.


def test_intra_shard_scheme_mismatch_falls_back():
    """Partitions WITHIN one shard disagreeing on bounds (same B, different
    les) cannot stage as one [S, T, B] block — the fused path must fall
    back (reason hist_scheme) instead of silently attributing one scheme's
    counts to the other's bounds."""
    rng = np.random.default_rng(7)
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    m = 120
    ts = BASE + np.arange(m, dtype=np.int64) * 10_000
    for i, bounds in enumerate(([0.1, 1, 5], [0.2, 1, 5])):
        scheme = custom_buckets(bounds)
        b = scheme.num_buckets
        incr = rng.poisson(2.0, size=(m, b)).astype(np.float64)
        incr[:, -1] = incr.sum(1)
        hist = np.cumsum(np.cumsum(incr, axis=1), axis=0)
        ms.shard("ds", 0).ingest_series(SeriesBatch(
            PROM_HISTOGRAM,
            {METRIC_TAG: "lat_mixed", "_ws_": "w", "_ns_": "n",
             "instance": f"h{i}"},
            ts, {"sum": hist[:, -1] * 0.1, "count": hist[:, -1], "h": hist},
            bucket_les=scheme.bounds(),
        ))
    eng = QueryEngine(ms, "ds")
    before = _fallback_counts()
    start = (BASE + 400_000) / 1000
    eng.query_range("sum(rate(lat_mixed[5m]))", start, start + 300, 60)
    after = _fallback_counts()
    assert after.get("hist_scheme", 0) == before.get("hist_scheme", 0) + 1


def test_remap_buckets_forward_fill():
    """Missing bounds take the nearest lower bound's cumulative count (0
    below the first) — monotone, and exact for nested schemes."""
    from filodb_tpu.core.histograms import remap_buckets, union_les

    src = np.array([0.5, 1.0, np.inf])
    dst = union_les([src, np.array([0.25, 0.5, 1.0, 2.5, np.inf])])
    np.testing.assert_allclose(dst[:-1], [0.25, 0.5, 1.0, 2.5])
    arr = np.array([[3.0, 7.0, 10.0]])
    out = remap_buckets(arr, src, dst)
    # 0.25 < first bound -> 0; 2.5 takes C(1.0)=7; +Inf copies through
    np.testing.assert_allclose(out, [[0.0, 3.0, 7.0, 7.0, 10.0]])


# -- fused topk/bottomk/quantile epilogues -----------------------------------


@pytest.mark.parametrize("q", [
    "topk(3, rate(http_requests_total[5m]))",
    "bottomk(2, rate(http_requests_total[5m]))",
    "topk(5, http_requests_total)",
    "quantile(0.9, rate(http_requests_total[5m]))",
    "quantile by (job) (0.5, rate(http_requests_total[5m]))",
    "quantile(0.25, http_requests_total)",
])
def test_fused_epilogue_parity(engines, q):
    assert_parity(*engines, q)


def test_fused_topk_single_dispatch_and_compact_transfer(engines):
    """Warm fused topk = ONE instrumented kernel dispatch (range kernel +
    epilogue in one compiled program), and only the [k, J] winner set comes
    back: the device entry point returns [k, J_pad] arrays, never [S, J]."""
    from filodb_tpu.ops import aggregations as AGG

    fused, _ = engines
    q = "topk(3, rate(http_requests_total[5m]))"
    for _ in range(2):
        fused.query_range(q, START, END, STEP)
    before = _dispatch_total()
    res = fused.query_range(q, START, END, STEP)
    assert _dispatch_total() - before == 1
    # at most k rows reach the result; per step at most k finite values
    vals = np.vstack([g.values_np() for g in res.grids])
    assert (np.isfinite(vals).sum(axis=0) <= 3).all()

    # direct transfer-shape check on the device entry point
    from filodb_tpu.ops.kernels import RangeParams
    from filodb_tpu.ops.staging import stage_series

    rng = np.random.default_rng(0)
    m = 64
    ts = BASE + np.arange(m, dtype=np.int64) * 10_000
    series = [(ts, rng.uniform(1, 9, size=m)) for _ in range(10)]
    block = stage_series(series, BASE).to_device()
    params = RangeParams(BASE + 300_000, 60_000, 8, 300_000)
    v, i = AGG.fused_topk("sum_over_time", block, 3, False, params)
    assert v.shape[0] == 3 and i.shape[0] == 3  # [k, J_pad], not [S, J]


def test_fused_quantile_single_dispatch_warm(engines):
    fused, _ = engines
    q = "quantile(0.9, rate(http_requests_total[5m]))"
    for _ in range(2):
        fused.query_range(q, START, END, STEP)
    before = _dispatch_total()
    fused.query_range(q, START, END, STEP)
    assert _dispatch_total() - before == 1


def test_fused_topk_sees_new_ingest(engines):
    """Epilogue results flow through the same shard-version-keyed superblock
    cache: ingest invalidates, and parity holds after."""
    fused, ref = engines
    q = "topk(4, sum_over_time(http_requests_total[10m]))"
    end = (BASE + 260 * 10_000) / 1000
    fused.query_range(q, START, end, STEP)
    fused.memstore.ingest_routed(
        "ds",
        counter_batch(n_series=24, n_samples=260, start_ms=BASE, seed=99),
        spread=2,
    )
    assert_parity(fused, ref, q, START, end)


# -- superblock byte accounting (3-D blocks) ---------------------------------


def test_hist_superblock_evicts_scalar_entries():
    """The B axis multiplies a histogram superblock's footprint; eviction
    must see TRUE device bytes (staged_nbytes incl. 3-D vals + [S, B]
    baselines), so a big hist entry evicts scalar entries instead of
    overshooting the byte budget."""
    from filodb_tpu.ops import staging as ST

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(2)))
    ms.ingest_routed(
        "ds",
        histogram_batch(n_series=8, n_samples=200, start_ms=BASE,
                        metric="http_request_latency"),
        spread=1,
    )
    ms.ingest_routed(
        "ds", counter_batch(n_series=8, n_samples=200, start_ms=BASE),
        spread=1,
    )
    eng = QueryEngine(ms, "ds")
    scalar_q = "sum(rate(http_requests_total[5m]))"
    hist_q = "sum(rate(http_request_latency[5m]))"
    # measure both entries' true accounting under an unbounded budget
    eng.query_range(scalar_q, START, END, STEP)
    eng.query_range(hist_q, START, END, STEP)
    cache = ms._superblock_cache
    with cache._lock:
        sizes = {e[1].is_hist: e[2] for e in cache._d.values()}
        blocks = {e[1].is_hist: e[1].block for e in cache._d.values()}
    scalar_nbytes, hist_nbytes = sizes[False], sizes[True]
    # the hist block is bigger despite having NO raw sidecar and a narrower
    # padded T (no live-edge headroom): the B axis dominates
    assert hist_nbytes > scalar_nbytes, (
        "3-D bucket block bytes must reflect the B axis"
    )
    # and the accounting matches the blocks' true device footprint
    assert hist_nbytes == ST.staged_nbytes(blocks[True])
    assert scalar_nbytes == ST.staged_nbytes(blocks[False])
    # budget fits the histogram entry but NOT histogram + scalar: caching
    # the hist superblock must evict the scalar entry, not blow the budget
    ms._superblock_cache = ST.SuperblockCache(
        max_entries=8, max_bytes=hist_nbytes + scalar_nbytes // 2
    )
    eng.query_range(scalar_q, START, END, STEP)
    assert len(ms._superblock_cache) == 1
    eng.query_range(hist_q, START, END, STEP)
    with ms._superblock_cache._lock:
        entries = list(ms._superblock_cache._d.values())
    assert len(entries) == 1, "hist superblock must evict the scalar entry"
    assert entries[0][1].is_hist
