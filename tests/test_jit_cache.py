"""Recompilation control (SURVEY §7 calls the jit cache the #1 risk):
varying query times/windows/steps over same-shaped data must reuse a tiny
set of compiled programs — only shape-bucket changes may compile anew."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.ops import kernels as K
from filodb_tpu.ops import mxu_kernels as MX
from filodb_tpu.testkit import counter_batch, machine_metrics

BASE = 1_600_000_000_000


def test_query_variations_do_not_recompile():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0, 1])
    ms.ingest_routed("ds", machine_metrics(n_series=10, n_samples=300, start_ms=BASE), spread=1)
    ms.ingest_routed("ds", counter_batch(n_series=10, n_samples=300, start_ms=BASE), spread=1)
    engine = QueryEngine(ms, "ds")

    def run_variations():
        for k in range(6):
            start = (BASE + 600_000 + k * 70_000) / 1000
            end = start + 600 + k * 60  # varying step counts (same 64-bucket)
            engine.query_range("sum(rate(http_requests_total[5m]))", start, end, 60)
            engine.query_range("avg_over_time(heap_usage0[3m])", start, end, 30)
            engine.query_range("max_over_time(heap_usage0[2m])", start, end, 60)

    run_variations()
    c_range = K.range_kernel._cache_size()
    c_mxu = MX.mxu_range_kernel._cache_size()
    c_minmax = MX.mxu_minmax._cache_size()
    # re-run with shifted times: NOTHING may recompile
    run_variations()
    assert K.range_kernel._cache_size() == c_range
    assert MX.mxu_range_kernel._cache_size() == c_mxu
    assert MX.mxu_minmax._cache_size() == c_minmax


def test_step_count_bucketing_bounds_cache():
    # num_steps pads to 64s: 1..64 steps share one compilation
    assert K.pad_steps(1) == K.pad_steps(64) == 64
    assert K.pad_steps(65) == K.pad_steps(128) == 128


def test_series_count_bucketing():
    from filodb_tpu.ops.staging import pad_series

    assert pad_series(3) == pad_series(8) == 8
    assert pad_series(9) == pad_series(32) == 32
    assert pad_series(100_000) == 131072
