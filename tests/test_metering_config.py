"""Metering, churn finder, config defaults tests (model: reference
TenantIngestionMetering + LabelChurnFinder + GlobalConfig specs)."""

import pytest

from filodb_tpu.config import DEFAULTS, load_config
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.cardinality import QuotaExceededError
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.metering import LabelChurnFinder, TenantIngestionMetering
from filodb_tpu.metrics import REGISTRY
from filodb_tpu.server import FiloServer
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


class TestMetering:
    def test_tenant_gauges_published(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0, 1])
        ms.ingest_routed("prometheus", machine_metrics(n_series=12, n_samples=5, start_ms=BASE), spread=1)
        m = TenantIngestionMetering(ms, "prometheus")
        n = m.publish()
        assert n == 1
        g = REGISTRY.gauge("filodb_tenant_ts_total", ws="demo", ns="App-2")
        assert g.value == 12


class TestChurnFinder:
    def test_churn_across_windows(self):
        f = LabelChurnFinder(["instance"])
        for i in range(10):
            f.observe({"instance": f"h{i}"})
        first = f.roll()
        assert first["instance"]["distinct"] == 10
        assert first["instance"]["churn_ratio"] == 1.0
        # second window: half repeats, half new
        for i in range(5, 15):
            f.observe({"instance": f"h{i}"})
        second = f.roll()
        assert second["instance"]["distinct"] == 10
        assert second["instance"]["new"] == 5
        assert second["instance"]["churn_ratio"] == 0.5

    def test_scan_shard(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=7, n_samples=3, start_ms=BASE))
        f = LabelChurnFinder(["instance", "job"])
        f.scan_shard(ms.shard("ds", 0))
        out = f.roll()
        assert out["instance"]["distinct"] == 7
        assert out["job"]["distinct"] == 1


class TestConfig:
    def test_defaults_and_overrides(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text('{"shards": 2, "query": {"max_series": 5}}')
        cfg = load_config(str(p), overrides={"http_port": 1234})
        assert cfg["shards"] == 2
        assert cfg["http_port"] == 1234
        assert cfg["query"]["max_series"] == 5
        assert cfg["query"]["lookback_ms"] == DEFAULTS["query"]["lookback_ms"]

    def test_server_applies_quotas(self):
        srv = FiloServer({
            "shards": 1,
            "quotas": [{"prefix": ["demo", "App-2"], "quota": 3}],
        })
        ms = srv.memstore
        with pytest.raises(QuotaExceededError):
            ms.ingest("prometheus", 0, machine_metrics(n_series=10, n_samples=2, start_ms=BASE))

    def test_server_applies_query_limits(self):
        srv = FiloServer({"shards": 1, "query": {"max_series": 2}})
        srv.memstore.ingest("prometheus", 0, machine_metrics(n_series=5, n_samples=3, start_ms=BASE))
        from filodb_tpu.query.exec.transformers import QueryError

        with pytest.raises(QueryError):
            srv.engine.query_range("heap_usage0", (BASE + 60_000) / 1000, (BASE + 120_000) / 1000, 60)
