"""Peer-level mergeable aggregation components (L.PartialAggregate).

Federation used to ship raw series unions for count/avg/stddev/quantile —
O(series) on the wire where the reference exchanges O(groups) mergeable
AggregateItems (RowAggregator.scala:28,114, AggrOverRangeVectors.scala:224,
QuantileRowAggregator's t-digests). gRPC plan-transport peers now receive
PartialAggregate and return __comp__-labeled component grids ((sum,count)
for avg, (sum,sumsq,count) for stddev, log-linear sketch counts for
quantile) that the coordinator merges exactly like local shard partials.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query import logical as L
from filodb_tpu.testkit import counter_batch, machine_metrics

START = 1_600_000_000_000


def _walk(plan):
    yield plan
    for c in plan.children():
        yield from _walk(c)


# ---------------------------------------------------------------------------
# plan-level: the peer leaf carries PartialAggregate for component ops


@pytest.mark.parametrize("op", ["count", "avg", "stddev", "stdvar", "sum"])
def test_peer_leaf_ships_partial_aggregate(op):
    from filodb_tpu.api.grpc_exec import GrpcPlanRemoteExec
    from filodb_tpu.query.promql import query_range_to_logical_plan

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    eng = QueryEngine(
        ms, "prometheus",
        PlannerParams(num_shards=4, peer_endpoints=("grpc://127.0.0.1:1",)),
    )
    lp = query_range_to_logical_plan(
        f"{op}(rate(http_requests_total[5m]))",
        START / 1000 + 400, START / 1000 + 1000, 60,
    )
    tree = eng.planner.materialize(lp)
    remotes = [p for p in _walk(tree) if isinstance(p, GrpcPlanRemoteExec)]
    assert remotes, "peer endpoint must produce a plan-transport leaf"
    for r in remotes:
        assert isinstance(r.logical_plan, L.PartialAggregate)
        assert r.logical_plan.op == op


def test_partial_aggregate_proto_roundtrip():
    from filodb_tpu.query.proto_plan import plan_from_bytes, plan_to_bytes

    p = L.PartialAggregate(
        "avg",
        L.RawSeries(filters=(), start_ms=1, end_ms=2),
        (),
        by=("instance",),
        without=None,
    )
    q = plan_from_bytes(plan_to_bytes(p))
    assert q == p


def test_sketch_grid_frames_roundtrip():
    """Quantile sketch cubes (les-less hist payloads, mostly zeros) must
    survive the gRPC frames, including the sparse encoding."""
    from filodb_tpu.query.proto_plan import frames_to_result, result_to_frames
    from filodb_tpu.query.rangevector import Grid, QueryResult

    rng = np.random.default_rng(0)
    G, J, B = 3, 16, 4097
    counts = np.zeros((G, J, B), np.float32)
    # ~100 nonzero bins per (g, j): the realistic sketch shape
    for g in range(G):
        for j in range(J):
            bins = rng.choice(B, 100, replace=False)
            counts[g, j, bins] = rng.integers(1, 50, 100)
    grid = Grid(
        [{"g": str(i), "__comp__": "sketch"} for i in range(G)],
        START, 60_000, J,
        np.full((G, J), np.nan, np.float32),
        hist=counts,
    )
    res = QueryResult(grids=[grid])
    frames = list(result_to_frames(res))
    total = sum(len(f.SerializeToString()) for f in frames)
    dense = G * J * B * 4
    assert total < dense / 4, "sparse cube encoding must beat dense"
    back = frames_to_result(iter(frames))
    np.testing.assert_array_equal(back.grids[0].hist_np(), counts)
    assert back.grids[0].labels == grid.labels


# ---------------------------------------------------------------------------
# wire size: O(groups) components, not O(series) raw rows


def test_partial_wire_size_is_o_groups():
    from filodb_tpu.query.promql import query_range_to_logical_plan
    from filodb_tpu.query.proto_plan import result_to_frames

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed(
        "prometheus",
        machine_metrics(n_series=256, n_samples=60, start_ms=START),
        spread=2,
    )
    eng = QueryEngine(ms, "prometheus", PlannerParams(num_shards=4))
    s, e = START / 1000 + 400, START / 1000 + 580

    def wire_bytes(res):
        return sum(len(f.SerializeToString()) for f in result_to_frames(res))

    # what a partial-pushed peer ships: per-group components
    lp = query_range_to_logical_plan("avg(heap_usage0)", s, e, 60)
    partial = eng.planner.materialize(
        L.PartialAggregate("avg", lp.inner, (), None, None)
    )
    from filodb_tpu.query.exec.plans import PartialReduceExec

    assert isinstance(partial, PartialReduceExec)
    partial_res = eng._run(partial, eng.context())
    comps = {l["__comp__"] for g in partial_res.grids for l in g.labels}
    assert comps == {"sum", "count"}
    # what the raw path ships: every series
    raw_res = eng.query_range("heap_usage0", s, e, 60)
    n_raw = sum(g.n_series for g in raw_res.grids)
    assert n_raw == 256
    pb = wire_bytes(partial_res)
    rb = wire_bytes(raw_res)
    assert pb < rb / 20, f"partials {pb}B must be far under raw {rb}B"


# ---------------------------------------------------------------------------
# end-to-end 2-server parity


class TestTwoServerPartials:
    @pytest.fixture(scope="class")
    def cluster(self):
        from filodb_tpu.api.grpc_exec import serve_grpc
        from filodb_tpu.server import FiloServer

        base = {"dataset": "prometheus", "shards": 8, "grpc_port": 0,
                "query": {"timeout_s": 300}}
        a = FiloServer({**base, "distributed": {"owned_shards": [0, 1, 2, 3]}})
        b = FiloServer({**base, "distributed": {"owned_shards": [4, 5, 6, 7]}})
        a.start(port=0)
        b.start(port=0)
        for srv in (a, b):
            srv.local_engine = QueryEngine(
                srv.memstore, srv.dataset,
                PlannerParams(num_shards=8, deadline_s=300),
            )
        ga, pa = serve_grpc(a.engine, port=0, host="127.0.0.1",
                            local_engine=a.local_engine)
        gb, pb_ = serve_grpc(b.engine, port=0, host="127.0.0.1",
                             local_engine=b.local_engine)
        a.engine.planner.params.peer_endpoints = (f"grpc://127.0.0.1:{pb_}",)
        b.engine.planner.params.peer_endpoints = (f"grpc://127.0.0.1:{pa}",)

        batch = counter_batch(n_series=24, n_samples=120, start_ms=START)
        gauge = machine_metrics(n_series=24, n_samples=120, start_ms=START)
        na = a.memstore.ingest_routed("prometheus", batch, spread=3)
        nb = b.memstore.ingest_routed("prometheus", batch, spread=3)
        a.memstore.ingest_routed("prometheus", gauge, spread=3)
        b.memstore.ingest_routed("prometheus", gauge, spread=3)
        assert na > 0 and nb > 0

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(8))
        ms.ingest_routed("prometheus",
                         counter_batch(n_series=24, n_samples=120, start_ms=START),
                         spread=3)
        ms.ingest_routed("prometheus",
                         machine_metrics(n_series=24, n_samples=120, start_ms=START),
                         spread=3)
        oracle = QueryEngine(ms, "prometheus")
        yield a, b, oracle
        ga.stop(grace=0)
        gb.stop(grace=0)
        a.stop()
        b.stop()

    def _grids_map(self, res):
        return {
            tuple(sorted(l.items())): v
            for l, _, v in res.all_series()
        }

    @pytest.mark.parametrize("q", [
        "count(rate(http_requests_total[5m]))",
        "avg(rate(http_requests_total[5m]))",
        "stddev(rate(http_requests_total[5m]))",
        "stdvar(rate(http_requests_total[5m]))",
        "avg by (instance) (heap_usage0)",
        "stddev(heap_usage0)",
    ])
    def test_component_ops_match_single_host(self, cluster, q):
        a, _, oracle = cluster
        s, e = START / 1000 + 400, START / 1000 + 1100
        want = self._grids_map(oracle.query_range(q, s, e, 60))
        got = self._grids_map(a.engine.query_range(q, s, e, 60))
        assert want.keys() == got.keys()
        for k in want:
            w, g = want[k], got[k]
            np.testing.assert_array_equal(np.isnan(w), np.isnan(g), err_msg=q)
            ok = ~np.isnan(w)
            np.testing.assert_allclose(g[ok], w[ok], rtol=1e-4, err_msg=q)

    def test_histogram_sum_rate_matches_single_host(self, cluster):
        """Native-histogram sum across peers: the peer ships per-group
        bucket-cube partials (__comp__=hist riding the hist field), not raw
        bucket series."""
        a, _, oracle = cluster
        from filodb_tpu.testkit import histogram_batch

        for srv in (a.memstore, cluster[1].memstore):
            srv.ingest_routed(
                "prometheus",
                histogram_batch(n_series=12, n_samples=60, start_ms=START),
                spread=3,
            )
        ms_o = oracle.memstore
        ms_o.ingest_routed(
            "prometheus",
            histogram_batch(n_series=12, n_samples=60, start_ms=START),
            spread=3,
        )
        s, e = START / 1000 + 400, START / 1000 + 580
        q = "histogram_quantile(0.9, sum(rate(http_request_latency[5m])))"
        want = self._grids_map(oracle.query_range(q, s, e, 60))
        got = self._grids_map(a.engine.query_range(q, s, e, 60))
        assert want.keys() == got.keys()
        for k in want:
            w, g = want[k], got[k]
            ok = ~np.isnan(w)
            np.testing.assert_allclose(g[ok], w[ok], rtol=1e-4)

    def test_quantile_matches_single_host_within_sketch_error(self, cluster):
        a, _, oracle = cluster
        s, e = START / 1000 + 400, START / 1000 + 1100
        q = "quantile(0.9, heap_usage0)"
        want = self._grids_map(oracle.query_range(q, s, e, 60))
        got = self._grids_map(a.engine.query_range(q, s, e, 60))
        assert want.keys() == got.keys()
        for k in want:
            w, g = want[k], got[k]
            ok = ~np.isnan(w)
            # log-linear sketch: ~2.2% relative bin error at SUB=32
            np.testing.assert_allclose(g[ok], w[ok], rtol=0.05)
