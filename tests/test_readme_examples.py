"""Keep README examples honest: the quickstart Python API snippet must run
exactly as documented."""

import numpy as np

from filodb_tpu.testkit import counter_batch

BASE = 1_600_000_000_000


def test_readme_python_api_snippet():
    # --- verbatim from README (with a concrete batch + times) ---
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset

    batch = counter_batch(n_series=6, n_samples=120, start_ms=BASE,
                          metric="latency")
    start_s, end_s = (BASE + 400_000) / 1000, (BASE + 1_000_000) / 1000

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", batch, spread=3)   # RecordBatch
    engine = QueryEngine(ms, "prometheus")
    res = engine.query_range("sum(rate(latency[5m]))", start_s, end_s, 60)
    # --- end snippet ---
    series = list(res.all_series())
    assert len(series) == 1
    assert np.isfinite(series[0][2]).all()


def test_readme_histogram_snippet_query_shape():
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.testkit import histogram_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus",
                     histogram_batch(n_series=4, n_samples=120, start_ms=BASE,
                                     metric="latency"), spread=2)
    engine = QueryEngine(ms, "prometheus")
    res = engine.query_range(
        "histogram_quantile(0.9, sum(rate(latency[5m])))",
        (BASE + 400_000) / 1000, (BASE + 1_000_000) / 1000, 60)
    assert len(list(res.all_series())) == 1
