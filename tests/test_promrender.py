"""Native JSON sample renderer (promrender.cpp) vs the Python renderer, and
the chunked-streaming serving edge (reference PrometheusModel.scala render +
executeStreaming ExecPlan.scala:146)."""

import json

import numpy as np
import pytest

from filodb_tpu import native as N
from filodb_tpu.api import promjson as J
from filodb_tpu.query.rangevector import Grid, QueryResult

BASE = 1_600_000_000_000

needs_native = pytest.mark.skipif(
    N.render_lib() is None, reason="native render lib unavailable"
)


def _parse_frag(frag: bytes):
    return [(t, float(v)) for t, v in json.loads(frag)]


class TestNativeRenderParity:
    CASES = [
        np.array([1.5, np.nan, -np.inf, np.inf, 0.0, -0.0]),
        np.array([1e-300, 1e300, 1e-05, 123456789.123456789, -2.5e-10]),
        np.array([np.nan, np.nan]),
        np.array([], dtype=np.float64),
        np.random.default_rng(0).standard_normal(500) * 1e6,
    ]

    @needs_native
    @pytest.mark.parametrize("idx", range(len(CASES)))
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_native_matches_python(self, idx, dtype):
        vals = self.CASES[idx].astype(dtype)
        ts = (BASE + np.arange(len(vals), dtype=np.int64) * 10_000) / 1e3
        native = N.render_values(ts, vals)
        assert native is not None
        # python reference fragment
        keep = ~np.isnan(vals)
        want = [
            [float(t), J._fmt(v)] for t, v in zip(ts[keep], vals[keep])
        ]
        got = json.loads(native)
        assert len(got) == len(want)
        for (gt, gv), (wt, wv) in zip(got, want):
            assert gt == wt
            if wv == "NaN":
                assert gv == "NaN"
            elif wv in ("+Inf", "-Inf"):
                assert gv == wv
            else:
                # shortest-roundtrip forms may differ textually ("2" vs
                # "2.0") but must parse to the identical double
                assert float(gv) == float(wv)

    @needs_native
    def test_f32_widens_like_python(self):
        # float(np.float32(0.1)) == 0.10000000149011612: the native cast
        # must produce a string parsing to exactly that double
        vals = np.array([0.1], dtype=np.float32)
        ts = np.array([1600000000.0])
        frag = json.loads(N.render_values(ts, vals))
        assert float(frag[0][1]) == float(np.float32(0.1))


def _result(n_series=30, n_steps=40, with_raw=False):
    rng = np.random.default_rng(1)
    vals = rng.standard_normal((n_series, n_steps)).astype(np.float32)
    vals[0, :] = np.nan  # all-NaN series must be dropped like render_matrix
    vals[1, ::3] = np.nan
    g = Grid([{"_metric_": "m", "i": str(i)} for i in range(n_series)],
             BASE, 60_000, n_steps, vals)
    res = QueryResult(grids=[g])
    if with_raw:
        ts = BASE + np.arange(17, dtype=np.int64) * 10_000
        res.raw = [({"_metric_": "raw0"}, ts, rng.standard_normal(17))]
    return res


class TestStreamMatrix:
    @pytest.mark.parametrize("with_raw", [False, True])
    def test_stream_equals_dict_render(self, with_raw):
        res = _result(with_raw=with_raw)
        stats = {"seriesScanned": 3}
        body = b"".join(J.stream_matrix(res, stats))
        got = json.loads(body)
        want_data = J.render_matrix(res)
        assert got["status"] == "success"
        assert got["data"]["resultType"] == "matrix"
        assert got["data"]["stats"] == stats
        got_rows = got["data"]["result"]
        want_rows = want_data["result"]
        assert len(got_rows) == len(want_rows)
        for gr, wr in zip(got_rows, want_rows):
            assert gr["metric"] == wr["metric"]
            assert len(gr["values"]) == len(wr["values"])
            for (gt, gv), (wt, wv) in zip(gr["values"], wr["values"]):
                assert float(gt) == float(wt)
                if wv in ("NaN", "+Inf", "-Inf"):
                    assert gv == wv
                else:
                    assert float(gv) == float(wv)

    def test_small_chunk_target_yields_many_chunks(self):
        res = _result(n_series=50)
        chunks = list(J.stream_matrix(res, None, chunk_target=1024))
        assert len(chunks) > 3
        json.loads(b"".join(chunks))  # still one valid document


class TestHttpStreaming:
    def test_query_range_streams_chunked_above_threshold(self, monkeypatch):
        import urllib.request

        from filodb_tpu.api.http import PromApiHandler, serve_background
        from filodb_tpu.coordinator.planner import QueryEngine
        from filodb_tpu.core.schemas import Dataset
        from filodb_tpu.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.testkit import counter_batch

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(2))
        ms.ingest_routed(
            "prometheus",
            counter_batch(n_series=20, n_samples=120, start_ms=BASE),
            spread=1,
        )
        engine = QueryEngine(ms, "prometheus")
        monkeypatch.setattr(PromApiHandler, "STREAM_MIN_SAMPLES", 100)
        srv, port = serve_background(engine)
        try:
            url = (
                f"http://127.0.0.1:{port}/api/v1/query_range?"
                f"query=http_requests_total&start={(BASE + 400_000) / 1000}"
                f"&end={(BASE + 1_000_000) / 1000}&step=60"
            )
            with urllib.request.urlopen(url) as resp:
                assert resp.headers.get("Transfer-Encoding") == "chunked"
                doc = json.loads(resp.read())
            assert doc["status"] == "success"
            assert len(doc["data"]["result"]) == 20
            assert doc["data"]["stats"]["seriesScanned"] == 20
        finally:
            srv.shutdown()
