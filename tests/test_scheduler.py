"""Query dispatch scheduler (query/scheduler.py; doc/operations.md).

Cross-query micro-batching: concurrent fused queries sharing a hot
superblock + grid/epilogue signature must launch as ONE batched kernel,
with each lane's result BIT-EQUAL to its own unbatched execution — the
batched programs unroll the exact single-query math (range grids computed
once per unique window), so equality is structural and asserted exactly,
never within tolerance. Batching disabled must be byte-identical to the
pre-scheduler engine (plan shapes included).

Admission control: per-tenant token-bucket rate/concurrency quotas and the
global queue-depth bound shed with AdmissionRejected -> HTTP 429 +
Retry-After + a structured warning; in-quota tenants complete while an
over-quota one sheds (fairness soak), and a shed REMOTE child degrades
exactly like a faulted one under allow_partial_results.

All batching tests drive the window with a test-controlled waiter + the
scheduler's queue-depth snapshot (no sleeps for correctness), and
admission tests use a fake clock — deterministic by construction.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.scheduler import (
    AdmissionController,
    AdmissionRejected,
    DispatchScheduler,
    TokenBucket,
)
from filodb_tpu.testkit import (
    counter_batch,
    histogram_batch,
    kernel_dispatch_total,
    machine_metrics,
)

pytestmark = pytest.mark.scheduler

BASE = 1_600_000_000_000
N_SHARDS = 8
START = (BASE + 600_000) / 1000
END = START + 900
STEP = 60


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=48, n_samples=240, start_ms=BASE),
        spread=3,
    )
    ms.ingest_routed(
        "ds", machine_metrics(n_series=48, n_samples=240, start_ms=BASE),
        spread=3,
    )
    ms.ingest_routed(
        "ds",
        histogram_batch(n_series=24, n_samples=240, start_ms=BASE,
                        metric="http_request_latency"),
        spread=3,
    )
    return ms


@pytest.fixture()
def engines(store):
    """(batched, sequential-twin, plain). The sequential twin shares the
    batched engine's params (same range-aligned plans) but a DISABLED
    scheduler, so batched-vs-sequential comparisons isolate exactly the
    batching of the kernel launch; plain is the fully default engine."""
    sched = DispatchScheduler(window_ms=100, max_batch=32)
    batched = QueryEngine(store, "ds", PlannerParams(
        batch_window_ms=100, dispatch_scheduler=sched))
    seq = QueryEngine(store, "ds", PlannerParams(
        batch_window_ms=100, dispatch_scheduler=DispatchScheduler(0)))
    plain = QueryEngine(store, "ds", PlannerParams())
    return batched, sched, seq, plain


def _rows(res):
    out = {}
    for g in res.grids:
        for lbls, vals in zip(g.labels, g.values_np()):
            out[tuple(sorted(lbls.items()))] = np.asarray(vals)
    return out


def _run_coalesced(engine, sched, queries, expected_lanes=None):
    """Run ``queries`` concurrently with the batch window held open until
    every query has submitted (and every expected lane joined), then
    release — deterministic group composition regardless of thread
    scheduling."""
    hold = threading.Event()
    sched._waiter = lambda ev, s: hold.wait(30)
    q0 = sched.stats["queries"]
    results: dict = {}
    errors: dict = {}

    def worker(q):
        try:
            results[q] = engine.query_range(q, START, END, STEP)
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errors[q] = e

    threads = [threading.Thread(target=worker, args=(q,)) for q in queries]
    for t in threads:
        t.start()
    want = expected_lanes if expected_lanes is not None else len(queries)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        snap = sched.snapshot()
        if (snap["queries"] - q0 >= len(queries)
                and snap["queued_lanes"] >= want):
            break
        time.sleep(0.002)
    hold.set()
    for t in threads:
        t.join(60)
    sched._waiter = None  # restore the production waiter
    assert not errors, errors
    return results


def assert_bit_equal(res_a, res_b, ctx=""):
    a, b = _rows(res_a), _rows(res_b)
    assert a.keys() == b.keys(), (ctx, sorted(a)[:3], sorted(b)[:3])
    for k in a:
        assert np.array_equal(a[k], b[k], equal_nan=True), (ctx, k)


# ---------------------------------------------------------------------------
# batched-vs-sequential parity across the epilogue families
# ---------------------------------------------------------------------------


# Per family: group-by variants sharing one window (and one pow2
# group-count bucket) genuinely COALESCE into one batched launch;
# cross-window / cross-bucket entries ride along to cover the solo path of
# the same scheduler round.
FAMILY_QUERIES = {
    "agg_sum": [
        "sum(rate(http_requests_total[5m]))",
        "sum by (_ws_) (rate(http_requests_total[5m]))",
        "sum by (job) (rate(http_requests_total[5m]))",
        "sum(rate(http_requests_total[4m]))",
        "sum(rate(http_requests_total[5m] offset 1m))",
    ],
    "agg_grouped": [
        "sum by (instance) (rate(http_requests_total[5m]))",
        "sum by (instance,job) (rate(http_requests_total[5m]))",
    ],
    "agg_minmax": [
        "max by (instance) (avg_over_time(heap_usage0[5m]))",
        "max by (instance,job) (avg_over_time(heap_usage0[5m]))",
        "min(avg_over_time(heap_usage0[5m]))",
    ],
    "agg_stddev": [
        "stddev(rate(http_requests_total[5m]))",
        "stddev by (_ns_) (rate(http_requests_total[5m]))",
    ],
    "topk": [
        "topk(3, rate(http_requests_total[5m]))",
        "topk(3, rate(http_requests_total[4m]))",
        "bottomk(2, rate(http_requests_total[5m]))",
    ],
    "quantile": [
        "quantile(0.9, rate(http_requests_total[5m]))",
        "quantile(0.5, rate(http_requests_total[5m]))",
        "quantile(0.99, rate(http_requests_total[5m]))",
    ],
    "hist": [
        "sum by (le) (rate(http_request_latency_bucket[5m]))",
        "sum by (le,_ws_) (rate(http_request_latency_bucket[5m]))",
    ],
    "hist_quantile": [
        "histogram_quantile(0.99, sum by (le) "
        "(rate(http_request_latency_bucket[5m])))",
        "histogram_quantile(0.5, sum by (le) "
        "(rate(http_request_latency_bucket[5m])))",
        "histogram_quantile(0.9, sum by (le) "
        "(rate(http_request_latency_bucket[4m])))",
    ],
}


@pytest.mark.parametrize("family", sorted(FAMILY_QUERIES))
def test_batched_parity_bit_equal(engines, family):
    """Each lane of a coalesced batch must be BIT-equal to its own
    sequential (unbatched) execution — across agg/topk/quantile/hist
    epilogue families, mixed windows, offsets and group-by variants."""
    batched, sched, seq, _plain = engines
    queries = FAMILY_QUERIES[family]
    expected = {q: seq.query_range(q, START, END, STEP) for q in queries}
    got = _run_coalesced(batched, sched, queries)
    for q in queries:
        assert_bit_equal(got[q], expected[q], ctx=q)


def test_batched_parity_vs_plain_engine(engines):
    """The batched engine's answers also agree with the fully-default
    engine (whose plans stage the narrower unaligned range): NaN masks
    identical, values within f32 accumulation tolerance — the range
    alignment never changes results beyond staging-baseline ulps."""
    batched, sched, _seq, plain = engines
    queries = FAMILY_QUERIES["agg_sum"]
    got = _run_coalesced(batched, sched, queries)
    for q in queries:
        a, b = _rows(got[q]), _rows(plain.query_range(q, START, END, STEP))
        assert a.keys() == b.keys(), q
        for k in a:
            na, nb = np.isnan(a[k]), np.isnan(b[k])
            assert (na == nb).all(), (q, k)
            np.testing.assert_allclose(
                a[k][~na], b[k][~nb], rtol=2e-5, atol=1e-6, err_msg=str(q)
            )


def test_mesh_batched_parity(store):
    """The sharded batched programs (shard_map twins) agree bit-for-bit
    with sequential execution on a degenerate 1-device mesh — the same
    program shape the multi-chip path compiles."""
    import jax

    from filodb_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices()[:1])
    sched = DispatchScheduler(window_ms=100)
    batched = QueryEngine(store, "ds", PlannerParams(
        mesh=mesh, batch_window_ms=100, dispatch_scheduler=sched))
    seq = QueryEngine(store, "ds", PlannerParams(
        mesh=mesh, batch_window_ms=100,
        dispatch_scheduler=DispatchScheduler(0)))
    queries = [
        "sum(rate(http_requests_total[5m]))",
        "sum by (instance) (rate(http_requests_total[4m]))",
    ]
    expected = {q: seq.query_range(q, START, END, STEP) for q in queries}
    got = _run_coalesced(batched, sched, queries)
    for q in queries:
        assert_bit_equal(got[q], expected[q], ctx=q)


# ---------------------------------------------------------------------------
# ONE dispatch per coalesced group
# ---------------------------------------------------------------------------


def test_coalesced_group_is_one_dispatch(engines):
    """A warm coalesced group of Q>1 queries sharing the superblock, grid
    and epilogue family issues exactly ONE kernel dispatch (the PR 4/5
    dispatch counter)."""
    batched, sched, _seq, _plain = engines
    queries = [
        "sum(rate(http_requests_total[5m]))",
        "sum by (_ws_) (rate(http_requests_total[5m]))",
        "sum by (_ns_) (rate(http_requests_total[5m]))",
    ]
    # two full rounds: stage the superblock, memoize gids/window matrices,
    # compile the width-4 batched executable
    for _ in range(2):
        _run_coalesced(batched, sched, queries)
    before = kernel_dispatch_total()
    _run_coalesced(batched, sched, queries)
    assert kernel_dispatch_total() - before == 1, (
        "a warm coalesced group must issue exactly ONE kernel dispatch"
    )


def test_compatible_window_groups_merge_into_one_batch(engines):
    """Lanes group per window triple, but the sealing leader re-merges
    still-open groups that agree on everything else (merge_key) into ONE
    mixed-window launch: each lane stays bit-equal to its own sequential
    execution (the u_map machinery computes one range grid per unique
    window), and the merge is counted in
    filodb_batch_merged_windows_total."""
    from filodb_tpu.metrics import REGISTRY

    batched, sched, seq, _plain = engines
    # same selector + group-by (one g_bucket), three windows whose
    # 5m-aligned staging ranges coincide -> one superblock, three
    # window-groups, all merge-compatible
    queries = [
        "sum(rate(http_requests_total[5m]))",
        "sum(rate(http_requests_total[4m]))",
        "sum(rate(http_requests_total[3m]))",
    ]
    expected = {q: seq.query_range(q, START, END, STEP) for q in queries}
    merged_before = sched.stats["merged_windows"]
    got = _run_coalesced(batched, sched, queries)
    assert sched.stats["merged_windows"] > merged_before, (
        "compatible window-groups must re-merge into one batch"
    )
    for q in queries:
        assert_bit_equal(got[q], expected[q], ctx=q)
    assert "filodb_batch_merged_windows_total" in REGISTRY.expose()


def test_identical_specs_dedup_onto_one_lane(engines):
    """Identical dispatch specs from distinct queries share one lane (the
    lane-level single-flight): the batch stays minimal and both callers get
    the same answer."""
    batched, sched, _seq, _plain = engines
    # distinct PromQL strings (whitespace), identical dispatch spec after
    # planning — the engine-level identical-query single-flight keys on the
    # STRING, so both reach the batcher and must share one lane
    queries = [
        "sum by (_ws_) (rate(http_requests_total[5m]))",
        "sum by (_ws_)  (rate(http_requests_total[5m]))",
    ]
    coalesced_before = sched.stats["coalesced"]
    got = _run_coalesced(batched, sched, queries, expected_lanes=1)
    assert sched.stats["coalesced"] > coalesced_before
    assert_bit_equal(got[queries[0]], got[queries[1]])


def test_batch_failure_falls_back_to_unbatched(engines, monkeypatch):
    """A batched-path failure must not lose queries: the leader re-runs
    every lane unbatched and the group is counted as a fallback."""
    import filodb_tpu.query.scheduler as QS

    batched, sched, seq, _plain = engines

    def boom(requests):
        raise RuntimeError("injected batch failure")

    monkeypatch.setattr(QS, "_run_batch", boom)
    queries = [
        "sum(rate(http_requests_total[5m]))",
        "sum by (_ns_) (rate(http_requests_total[5m]))",
    ]
    fallback_before = sched.stats["fallback"]
    got = _run_coalesced(batched, sched, queries)
    assert sched.stats["fallback"] == fallback_before + 1
    for q in queries:
        assert_bit_equal(got[q], seq.query_range(q, START, END, STEP), q)


# ---------------------------------------------------------------------------
# batching disabled == today's engine
# ---------------------------------------------------------------------------


def test_batching_disabled_is_todays_plans(store):
    """window=0 (the default) must be byte-identical to the pre-scheduler
    engine: same golden plan shapes, same staged ranges, bit-equal
    results."""
    from filodb_tpu.query.promql import query_range_to_logical_plan

    off = QueryEngine(store, "ds", PlannerParams(batch_window_ms=0))
    plain = QueryEngine(store, "ds", PlannerParams())
    q = "sum by (instance) (rate(http_requests_total[5m]))"
    plan = query_range_to_logical_plan(q, START, END, STEP)
    ep_off = off.planner.materialize(plan)
    ep_plain = plain.planner.materialize(plan)
    assert ep_off.print_tree() == ep_plain.print_tree()
    assert ep_off.raw_start_ms == ep_plain.raw_start_ms
    assert ep_off.raw_end_ms == ep_plain.raw_end_ms
    assert_bit_equal(
        off.query_range(q, START, END, STEP),
        plain.query_range(q, START, END, STEP),
    )


def test_batching_enabled_keeps_plan_shapes(store):
    """Batching is a runtime dispatch concern: enabling it must not change
    the PLAN tree (golden plan shapes unchanged) — only the staged range
    aligns."""
    from filodb_tpu.query.promql import query_range_to_logical_plan

    on = QueryEngine(store, "ds", PlannerParams(batch_window_ms=5))
    plain = QueryEngine(store, "ds", PlannerParams())
    for q in (
        "sum by (instance) (rate(http_requests_total[5m]))",
        "topk(3, rate(http_requests_total[5m]))",
        "histogram_quantile(0.99, sum by (le) "
        "(rate(http_request_latency_bucket[5m])))",
    ):
        plan = query_range_to_logical_plan(q, START, END, STEP)
        assert (on.planner.materialize(plan).print_tree()
                == plain.planner.materialize(plan).print_tree()), q


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestTokenBucket:
    def test_burst_then_rate(self):
        clk = FakeClock()
        b = TokenBucket(rate=2.0, burst=3.0, clock=clk)
        assert [b.try_take() for _ in range(3)] == [0.0, 0.0, 0.0]
        wait = b.try_take()
        assert wait == pytest.approx(0.5)  # 1 token at 2/s
        clk.t += 0.5
        assert b.try_take() == 0.0
        clk.t += 10.0  # refill caps at burst
        assert b.balance() == pytest.approx(3.0)

    def test_zero_rate_never_refills(self):
        b = TokenBucket(rate=0.0, burst=1.0, clock=FakeClock())
        assert b.try_take() == 0.0
        assert b.try_take() == float("inf")


class TestAdmission:
    def test_rate_quota_sheds_with_retry_after(self):
        clk = FakeClock()
        ctl = AdmissionController(
            {"demo/App-2": {"rate": 1.0, "burst": 2}}, clock=clk
        )
        with ctl.admit("demo", "App-2"):
            pass
        with ctl.admit("demo", "App-2"):
            pass
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("demo", "App-2")
        assert ei.value.outcome == "shed_rate"
        assert 0 < ei.value.retry_after_s <= 60
        w = ei.value.warning()
        assert w["reason"] == "admission_rejected"
        assert w["ws"] == "demo"
        clk.t += 1.5  # a token accrues
        with ctl.admit("demo", "App-2"):
            pass

    def test_concurrency_quota_and_release(self):
        ctl = AdmissionController(
            {"*": {"max_concurrent": 1}}, clock=FakeClock()
        )
        slot = ctl.admit("t", "a")
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("t", "a")
        assert ei.value.outcome == "shed_concurrency"
        # a DIFFERENT tenant has its own bucket under the "*" default
        with ctl.admit("t", "b"):
            pass
        with slot:
            pass  # release
        with ctl.admit("t", "a"):
            pass

    def test_global_queue_depth_bound(self):
        ctl = AdmissionController({}, max_queued=2, clock=FakeClock())
        s1 = ctl.admit("x", "1")
        s2 = ctl.admit("y", "2")
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("z", "3")
        assert ei.value.outcome == "shed_queue"
        s1.__exit__(None, None, None)
        with ctl.admit("z", "3"):
            pass
        s2.__exit__(None, None, None)

    def test_snapshot_shows_balances_and_sheds(self):
        clk = FakeClock()
        ctl = AdmissionController(
            {"demo/App-2": {"rate": 1.0, "burst": 1}}, clock=clk
        )
        with ctl.admit("demo", "App-2"):
            pass
        with pytest.raises(AdmissionRejected):
            ctl.admit("demo", "App-2")
        snap = ctl.snapshot()
        t = snap["tenants"]["demo/App-2"]
        assert t["shed"] == 1
        assert t["tokens"] is not None
        assert snap["shed_total"] == 1

    def test_admission_counter_has_bounded_labels(self):
        from filodb_tpu.metrics import REGISTRY

        ctl = AdmissionController({}, max_queued=0, clock=FakeClock())
        with ctl.admit("some-ws", "some-ns"):
            pass
        with REGISTRY._lock:
            keys = [k for k in REGISTRY._metrics if k[0] == "filodb_admission"]
        assert any(
            dict(lbls).get("outcome") == "admitted"
            and dict(lbls).get("ws") in ("some-ws", "overflow")
            for _, lbls in keys
        )


def test_quota_shed_fairness_under_soak(store):
    """Threaded soak: tenant A floods past its rate quota, tenant B stays
    in quota. Every B query completes; A is shed (429 semantics) with a
    positive Retry-After; no cross-tenant interference."""
    ctl = AdmissionController({"demo/App-2": {"rate": 2.0, "burst": 2}})
    engine = QueryEngine(store, "ds", PlannerParams(admission=ctl))
    q_a = 'sum(rate(http_requests_total{_ws_="demo",_ns_="App-2"}[5m]))'
    q_b = "sum(avg_over_time(heap_usage0[5m]))"  # tenant resolves unknown
    engine.query_range(q_b, START, END, STEP)  # warm (unmetered tenant)
    a_ok, a_shed, b_ok, b_err = [], [], [], []

    def tenant_a():
        for _ in range(6):
            try:
                engine.query_range(q_a, START, END, STEP)
                a_ok.append(1)
            except AdmissionRejected as e:
                assert e.retry_after_s > 0
                a_shed.append(e)

    def tenant_b():
        for _ in range(4):
            try:
                engine.query_range(q_b, START, END, STEP)
                b_ok.append(1)
            except Exception as e:  # noqa: BLE001
                b_err.append(e)

    threads = [threading.Thread(target=tenant_a) for _ in range(2)]
    threads += [threading.Thread(target=tenant_b) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not b_err, b_err
    assert len(b_ok) == 8  # in-quota tenant: every query served
    assert a_shed, "over-quota tenant must shed"
    assert len(a_ok) >= 2  # burst admits some


# ---------------------------------------------------------------------------
# API surfaces: HTTP 429 + /debug/scheduler; remote shed degrades partial
# ---------------------------------------------------------------------------


@pytest.fixture()
def shedding_server(store):
    from filodb_tpu.api.http import serve_background

    # near-zero refill: once the burst is drained the server sheds every
    # further request (deterministic 429s for the test's duration)
    ctl = AdmissionController(
        {"*": {"rate": 0.001, "burst": 4}},
    )
    sched = DispatchScheduler(window_ms=1.0)
    engine = QueryEngine(store, "ds", PlannerParams(
        admission=ctl, batch_window_ms=1.0, dispatch_scheduler=sched,
        coalesce_identical=False))
    srv, port = serve_background(engine, port=0)
    yield engine, ctl, port
    srv.shutdown()


def _http_get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_http_429_retry_after_and_warning(shedding_server):
    _engine, ctl, port = shedding_server
    q = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
    path = f"/api/v1/query_range?query={q}&start={START}&end={END}&step=60"
    # exhaust the burst so the next request sheds
    with ctl._lock:
        st = ctl._state("unknown/unknown")
    if st.bucket is not None:
        while st.bucket.try_take() == 0.0:
            pass
    code, headers, body = _http_get(port, path)
    assert code == 429
    assert int(headers["Retry-After"]) >= 1
    assert body["status"] == "error"
    assert body["errorType"] == "throttled"
    w = body["warnings"][0]
    assert w["reason"] == "admission_rejected"
    assert w["retry_after_s"] > 0


def test_debug_scheduler_endpoint(shedding_server):
    _engine, _ctl, port = shedding_server
    q = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
    _http_get(port, f"/api/v1/query_range?query={q}&start={START}&end={END}"
                    "&step=60")
    code, _h, body = _http_get(port, "/debug/scheduler")
    assert code == 200
    data = body["data"]
    assert data["batch"]["window_ms"] == pytest.approx(1.0)
    assert "queries" in data["batch"]
    assert "tenants" in data["admission"]
    assert "in_flight" in data["admission"]


def test_fetch_json_maps_429_to_admission_rejected(shedding_server):
    from filodb_tpu.coordinator.planners import fetch_json

    _engine, ctl, port = shedding_server
    with ctl._lock:
        st = ctl._state("unknown/unknown")
    while st.bucket.try_take() == 0.0:
        pass
    q = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
    with pytest.raises(AdmissionRejected) as ei:
        fetch_json(
            f"http://127.0.0.1:{port}/api/v1/query_range?query={q}"
            f"&start={START}&end={END}&step=60"
        )
    assert ei.value.retry_after_s >= 1
    assert ei.value.outcome == "shed_remote"
    # peer-health classification: sustained shedding opens the breaker,
    # but a shed is never blindly retried into the shed window
    assert ei.value.endpoint_failure is True
    assert ei.value.retryable is False


def test_grpc_error_frame_roundtrip():
    from filodb_tpu.query.proto_plan import _raise_remote_error

    payload = json.dumps({
        "error": "tenant demo/App-2 over rate quota",
        "retry_after_s": 2.5, "ws": "demo", "ns": "App-2",
    })
    with pytest.raises(AdmissionRejected) as ei:
        _raise_remote_error("AdmissionRejected", payload)
    assert ei.value.retry_after_s == pytest.approx(2.5)
    assert ei.value.ws == "demo"
    assert ei.value.outcome == "shed_remote"


def test_shed_remote_child_degrades_like_faulted(store):
    """Under allow_partial_results a remote child shed by its peer's
    admission control becomes a structured warning + survivors served —
    exactly the PR 2 lost-child contract."""
    from filodb_tpu.query.exec.plans import (
        NonLeafExecPlan,
        QueryContext,
    )
    from filodb_tpu.query.rangevector import QueryResult

    class OkChild(NonLeafExecPlan):
        def __init__(self):
            super().__init__([])

        def do_execute(self, ctx):
            return QueryResult()

    class ShedChild(OkChild):
        is_remote = True
        endpoint = "grpc://peer:1"

        def do_execute(self, ctx):
            raise AdmissionRejected(
                "remote peer shed request", retry_after_s=2.0,
                outcome="shed_remote",
            )

    class Merge(NonLeafExecPlan):
        supports_partial = True

        def do_execute(self, ctx):
            results = self.execute_children(ctx)
            return results[0]

    ctx = QueryContext(store, "ds")
    ctx.allow_partial_results = True
    merge = Merge([OkChild(), ShedChild()])
    merge.execute(ctx)
    assert ctx.warnings, "shed child must record a structured warning"
    assert any("shed" in w.get("error", "") for w in ctx.warnings)

    # strict mode: the shed propagates as the typed rejection
    ctx2 = QueryContext(store, "ds")
    with pytest.raises(AdmissionRejected):
        Merge([OkChild(), ShedChild()]).execute(ctx2)
