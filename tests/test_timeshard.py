"""Time-sharded execution with ring halo exchange vs the single-device
kernel — windows crossing slice boundaries must be exact (the ring-attention
halo correctness test)."""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.staging import stage_series
from filodb_tpu.parallel import timeshard as TS

BASE = 1_600_000_000_000


def make_block(n_series=5, n=600, seed=0, counter=False, irregular=True):
    rng = np.random.default_rng(seed)
    series = []
    for i in range(n_series):
        if irregular:
            ts = BASE + np.cumsum(rng.integers(5_000, 15_000, n)).astype(np.int64)
        else:
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            k = n // 2 + i * 10
            vals[k:] -= vals[k] - rng.uniform(0, 4)
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        series.append((ts, vals))
    return series, stage_series(series, BASE, counter_corrected=counter)


# long range: many steps so each of the 8 devices owns a span
PARAMS = K.RangeParams(BASE + 400_000, 30_000, 160, 300_000)


@pytest.mark.parametrize("func,counter", [
    ("sum_over_time", False),
    ("avg_over_time", False),
    ("max_over_time", False),
    ("last_over_time", False),
    ("rate", True),
    ("increase", True),
])
def test_timeshard_matches_single_device(func, counter):
    mesh = TS.make_time_mesh()
    assert mesh.devices.size == 8
    _, block = make_block(counter=counter)
    got = np.asarray(
        TS.run_timesharded(mesh, func, block, PARAMS, is_counter=counter)
    )[:5]
    want = np.asarray(
        K.run_range_function(func, block, PARAMS, is_counter=counter)
    )[:5, : PARAMS.num_steps]
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want), err_msg=func)
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=1e-4, atol=1e-4, err_msg=func)


def test_boundary_windows_use_halo():
    """A window entirely fed by halo samples (big gap at a slice boundary)
    must still produce values, proving the ppermute halo works."""
    mesh = TS.make_time_mesh()
    _, block = make_block(n=600, seed=3)
    params = K.RangeParams(BASE + 400_000, 30_000, 160, 600_000)  # 10m windows
    got = np.asarray(TS.run_timesharded(mesh, "count_over_time", block, params))[:5]
    want = np.asarray(K.run_range_function("count_over_time", block, params))[:5, :160]
    np.testing.assert_array_equal(got, want)
    # sanity: interior steps genuinely span slice boundaries (J_dev=20 steps
    # per device; window 10m covers ~60 samples at ~10s spacing)
    assert np.nanmax(want) >= 50


def test_regular_grid_timeshard():
    mesh = TS.make_time_mesh()
    _, block = make_block(irregular=False)
    got = np.asarray(TS.run_timesharded(mesh, "sum_over_time", block, PARAMS))[:5]
    want = np.asarray(K.run_range_function("sum_over_time", block, PARAMS))[:5, :160]
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=1e-4)
