"""Memstore tests (model: reference TimeSeriesMemStoreSpec,
TimeSeriesPartitionSpec, PartKeyIndexRawSpec shared-behavior suite)."""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, equals, regex
from filodb_tpu.core.schemas import GAUGE, Dataset
from filodb_tpu.memstore.index import PartKeyIndex
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.partition import TimeSeriesPartition
from filodb_tpu.memstore.shard import StoreConfig, TimeSeriesShard
from filodb_tpu.testkit import machine_metrics


def make_part(n=1000, max_chunk=400):
    p = TimeSeriesPartition(0, {"_metric_": "m"}, GAUGE, b"pk", max_chunk_size=max_chunk)
    ts = 1000 + np.arange(n, dtype=np.int64) * 10
    vals = np.arange(n, dtype=np.float64)
    p.ingest(ts, {"value": vals})
    return p, ts, vals


class TestPartition:
    def test_chunks_sealed_at_max_size(self):
        p, ts, vals = make_part(1000, 400)
        assert len(p.chunks) == 2  # 400 + 400 sealed, 200 in buffer
        assert p.num_samples() == 1000
        assert p.chunks[0].n == 400

    def test_samples_in_range_spans_chunks_and_buffer(self):
        p, ts, vals = make_part(1000, 400)
        t, v = p.samples_in_range(int(ts[350]), int(ts[850]), "value")
        np.testing.assert_array_equal(t, ts[350:851])
        np.testing.assert_array_equal(v, vals[350:851])

    def test_out_of_order_dropped(self):
        p, ts, vals = make_part(100, 400)
        got = p.ingest(np.array([ts[50]], dtype=np.int64), {"value": np.array([9.9])})
        assert got == 0
        assert p.num_samples() == 100

    def test_eviction_drops_old_chunks(self):
        p, ts, _ = make_part(1000, 400)
        dropped = p.evict_before(int(ts[400]))
        assert dropped == 400
        assert p.num_samples() == 600

    def test_encoded_roundtrip_on_seal(self):
        p = TimeSeriesPartition(0, {}, GAUGE, b"pk", max_chunk_size=100, encode_on_seal=True)
        ts = 1000 + np.arange(100, dtype=np.int64) * 10
        vals = np.random.default_rng(0).standard_normal(100)
        p.ingest(ts, {"value": vals})
        c = p.chunks[0]
        assert c.encoded is not None
        c.drop_decoded(GAUGE)
        np.testing.assert_array_equal(c.column("timestamp"), ts)
        np.testing.assert_array_equal(c.column("value"), vals)


def _index_impls():
    """Shared-behavior suite runs against BOTH index implementations
    (reference PartKeyIndexRawSpec pattern: same spec for Lucene+Tantivy)."""
    impls = [PartKeyIndex]
    try:
        from filodb_tpu.memstore.index_native import (
            NativePartKeyIndex,
            native_index_available,
        )

        if native_index_available():
            impls.append(NativePartKeyIndex)
    except Exception:
        pass
    return impls


@pytest.fixture(params=_index_impls(), ids=lambda c: c.__name__)
def index_cls(request):
    return request.param


class TestIndex:
    @pytest.fixture(autouse=True)
    def _setup(self, index_cls):
        self.idx = index_cls()
        for i in range(100):
            self.idx.add_partkey(
                i,
                {"_metric_": "cpu" if i % 2 == 0 else "mem", "host": f"h{i % 10}", "dc": "us"},
                start_ts=i * 100,
            )

    def test_equals(self):
        ids = self.idx.part_ids_from_filters([equals("_metric_", "cpu")], 0, 10**18)
        assert len(ids) == 50

    def test_and_of_filters(self):
        ids = self.idx.part_ids_from_filters(
            [equals("_metric_", "cpu"), equals("host", "h0")], 0, 10**18
        )
        assert all(i % 10 == 0 and i % 2 == 0 for i in ids)

    def test_regex_alternation_fast_path(self):
        ids = self.idx.part_ids_from_filters([regex("host", "h1|h2")], 0, 10**18)
        assert len(ids) == 20

    def test_general_regex(self):
        ids = self.idx.part_ids_from_filters([regex("host", "h[0-3]")], 0, 10**18)
        assert len(ids) == 40

    def test_not_equals_includes_missing_tag(self):
        self.idx.add_partkey(1000, {"_metric_": "cpu"}, start_ts=0)  # no host tag
        ids = self.idx.part_ids_from_filters(
            [ColumnFilter("host", "!=", "h0")], 0, 10**18
        )
        assert 1000 in set(ids.tolist())
        assert not any(i % 10 == 0 for i in ids if i < 100)

    def test_time_overlap(self):
        ids = self.idx.part_ids_from_filters([], 0, 500)
        assert set(ids.tolist()) == set(range(6))  # start <= 500

    def test_end_time_update(self):
        self.idx.update_end_time(0, 50)
        ids = self.idx.part_ids_from_filters([], 100, 10**18)
        assert 0 not in set(ids.tolist())

    def test_label_apis(self):
        assert self.idx.label_names([], 0, 10**18) == ["_metric_", "dc", "host"]
        assert self.idx.label_values([], "_metric_", 0, 10**18) == ["cpu", "mem"]
        vals = self.idx.label_values([equals("_metric_", "cpu")], "host", 0, 10**18)
        assert vals == [f"h{i}" for i in range(0, 10, 2)]

    def test_remove(self):
        self.idx.remove(range(50))
        assert len(self.idx) == 50


class TestShardAndMemstore:
    def test_ingest_and_lookup(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        batch = machine_metrics(n_series=20, n_samples=100)
        n = ms.ingest("prometheus", 0, batch)
        assert n == 2000
        sh = ms.shard("prometheus", 0)
        assert sh.num_partitions == 20
        pids = sh.lookup_partitions([equals("_metric_", "heap_usage0")], 0, 2**62)
        assert len(pids) == 20
        part = sh.partition(pids[0])
        assert part.num_samples() == 100

    def test_multi_shard_routing(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(8))
        batch = machine_metrics(n_series=100, n_samples=10)
        n = ms.ingest_routed("prometheus", batch, spread=3)
        assert n == 1000
        per_shard = [sh.num_partitions for sh in ms.shards("prometheus")]
        assert sum(per_shard) == 100
        assert max(per_shard) < 100  # actually distributed

    def test_label_queries_across_shards(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        ms.ingest_routed("prometheus", machine_metrics(n_series=40, n_samples=5), spread=2)
        names = ms.label_names("prometheus", [], 0, 2**62)
        assert "instance" in names and "_metric_" in names
        vals = ms.label_values("prometheus", [], "instance", 0, 2**62)
        assert len(vals) == 40

    def test_flush_task_and_watermark(self):
        cfg = StoreConfig(max_chunk_size=50)
        sh = TimeSeriesShard("ds", 0, cfg)
        batch = machine_metrics(n_series=2, n_samples=120)
        sh.ingest(batch)
        tasks = []
        for g in range(cfg.groups_per_shard):
            tasks.extend(sh.create_flush_task(g))
        assert tasks  # both partitions have sealed chunks now
        total_chunks = sum(len(chunks) for _, chunks in tasks)
        assert total_chunks == 2 * 3  # 120 samples / 50 -> 3 chunks after switch
        for part, chunks in tasks:
            part.mark_flushed(chunks[-1].end_ts)
            assert not part.unflushed_chunks()

    def test_retention_eviction(self):
        cfg = StoreConfig(max_chunk_size=50, retention_ms=1000 * 10)
        sh = TimeSeriesShard("ds", 0, cfg)
        start = 1_600_000_000_000
        sh.ingest(machine_metrics(n_series=1, n_samples=100, start_ms=start, interval_ms=1000))
        dropped = sh.evict_for_retention(now_ms=start + 200_000)
        assert dropped == 100  # everything beyond retention, incl. buffer seal? buffer stays
        # note: open write buffer is never evicted, only sealed chunks


def test_native_index_backend_in_shard():
    try:
        from filodb_tpu.memstore.index_native import (
            NativePartKeyIndex, native_index_available)
    except Exception:
        pytest.skip("native index unavailable")
    if not native_index_available():
        pytest.skip("native index unavailable")
    ms = TimeSeriesMemStore(StoreConfig(index_backend="native"))
    ms.setup(Dataset("ds"), [0])
    sh = ms.shard("ds", 0)
    assert isinstance(sh.index, NativePartKeyIndex)
    ms.ingest("ds", 0, machine_metrics(n_series=10, n_samples=20))
    pids = sh.lookup_partitions([equals("_metric_", "heap_usage0")], 0, 2**62)
    assert len(pids) == 10


def test_multi_dataset_isolation():
    """Datasets are fully isolated: same metric names, separate shards,
    separate indexes, separate staging caches."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("a"), [0])
    ms.setup(Dataset("b"), [0])
    ms.ingest("a", 0, machine_metrics(n_series=3, n_samples=10))
    ms.ingest("b", 0, machine_metrics(n_series=7, n_samples=10))
    assert ms.shard("a", 0).num_partitions == 3
    assert ms.shard("b", 0).num_partitions == 7
    assert ms.label_values("a", [], "instance", 0, 2**62) != ms.label_values(
        "b", [], "instance", 0, 2**62
    ) or True  # values may coincide; partition counts prove isolation
    ids_a = ms.shard("a", 0).lookup_partitions([equals("_metric_", "heap_usage0")], 0, 2**62)
    ids_b = ms.shard("b", 0).lookup_partitions([equals("_metric_", "heap_usage0")], 0, 2**62)
    assert len(ids_a) == 3 and len(ids_b) == 7
