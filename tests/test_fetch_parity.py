"""Forced-fetch parity: the TPU matmul fetch path executed on CPU.

The MXU kernels choose their one-hot-selection fetch strategy per backend at
trace time (gather on CPU, one-hot matmul on TPU) — so without forcing, CI on
the CPU backend would never execute the exact code that runs on the real
chip. FILODB_MXU_FETCH forces a strategy (ops/mxu_kernels.fetch_strategy);
these tests assert gather <-> matmul equality across the function matrix for
both the regular-grid and jittered-grid paths, plus the harmonize
re-verification fallback (the round-4 advisor high-severity class: per-shard
grids must never be silently mis-aggregated).

Window-semantics contract: reference PeriodicSamplesMapper.scala:256.
"""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.mxu_jitter import JITTER_FUNCS, run_jitter_range_function
from filodb_tpu.ops.mxu_kernels import MXU_FUNCS, run_mxu_range_function
from filodb_tpu.ops.staging import harmonize_nominal, stage_series

BASE = 1_600_000_000_000
INTERVAL = 10_000


def _series(n_series=6, n=300, seed=0, counter=False, jitter=0.0):
    rng = np.random.default_rng(seed)
    nominal = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL
    out = []
    for i in range(n_series):
        ts = nominal
        if jitter:
            ts = nominal + np.rint(
                rng.uniform(-jitter, jitter, n) * INTERVAL
            ).astype(np.int64)
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            k = n // 2 + i
            vals[k:] -= vals[k] - rng.uniform(0, 5)
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        out.append((ts, vals))
    return out


def _run_forced(monkeypatch, fetch, runner, func, series, counter, args=()):
    monkeypatch.setenv("FILODB_MXU_FETCH", fetch)
    block = stage_series(series, BASE, counter_corrected=counter)
    params = K.RangeParams(BASE + 400_000, 60_000, 20, 300_000)
    out = runner(func, block, params, is_counter=counter, args=args)
    assert out is not None
    return np.asarray(out)[: len(series), :20]


@pytest.mark.parametrize("func", sorted(MXU_FUNCS))
def test_regular_gather_matmul_parity(func, monkeypatch):
    counter = func in ("rate", "increase", "irate")
    series = _series(seed=11, counter=counter)
    args = (600.0,) if func == "predict_linear" else ()
    g = _run_forced(monkeypatch, "gather", run_mxu_range_function,
                    func, series, counter, args)
    m = _run_forced(monkeypatch, "matmul", run_mxu_range_function,
                    func, series, counter, args)
    np.testing.assert_array_equal(g, m, err_msg=func)


@pytest.mark.parametrize("func", sorted(JITTER_FUNCS))
def test_jitter_gather_matmul_parity(func, monkeypatch):
    counter = func in ("rate", "increase", "irate")
    series = _series(seed=12, counter=counter, jitter=0.05)
    g = _run_forced(monkeypatch, "gather", run_jitter_range_function,
                    func, series, counter)
    m = _run_forced(monkeypatch, "matmul", run_jitter_range_function,
                    func, series, counter)
    np.testing.assert_array_equal(g, m, err_msg=func)


def test_forced_matmul_matches_general_path(monkeypatch):
    """The matmul fetch (the code the real TPU runs) must match the general
    gather-path oracle, not just the CPU fetch twin."""
    series = _series(seed=13, counter=True, jitter=0.05)
    params = K.RangeParams(BASE + 400_000, 60_000, 20, 300_000)
    monkeypatch.setenv("FILODB_MXU_FETCH", "matmul")
    block = stage_series(series, BASE, counter_corrected=True)
    assert block.nominal_ts is not None
    fast = np.asarray(
        run_jitter_range_function("rate", block, params, is_counter=True)
    )[: len(series), :20]
    monkeypatch.delenv("FILODB_MXU_FETCH")
    general = stage_series(series, BASE, counter_corrected=True)
    general.nominal_ts = None  # force the general per-sample path
    slow = np.asarray(
        K.run_range_function("rate", general, params, is_counter=True)
    )[: len(series), :20]
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow))
    ok = ~np.isnan(slow)
    np.testing.assert_allclose(fast[ok], slow[ok], rtol=1e-3, atol=1e-3)


def test_bad_fetch_strategy_rejected(monkeypatch):
    from filodb_tpu.ops.mxu_kernels import fetch_strategy

    monkeypatch.setenv("FILODB_MXU_FETCH", "bogus")
    with pytest.raises(ValueError):
        fetch_strategy()


# ---- harmonize re-verification regression (round-4 advisor high severity) --


def _jitter_blocks(per_shard_counts, seed=5):
    """One near-regular staged block per shard; shard i drops
    per_shard_counts[i] trailing samples, so sample counts differ."""
    rng = np.random.default_rng(seed)
    blocks = []
    for s, drop in enumerate(per_shard_counts):
        series = []
        for i in range(3):
            n = 120 - drop
            dev = np.rint(rng.uniform(-0.1, 0.1, n) * INTERVAL).astype(np.int64)
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL + dev
            series.append((ts, np.cumsum(rng.uniform(0, 10, n))))
        blocks.append(stage_series(series, BASE, counter_corrected=True))
    return blocks


def test_harmonize_rejects_unequal_counts():
    blocks = _jitter_blocks([0, 0, 1])
    assert all(b.nominal_ts is not None for b in blocks)
    assert harmonize_nominal(blocks) is False
    # and blocks are untouched: each keeps its own grid
    assert all(b.nominal_ts is not None for b in blocks)


def test_mesh_engine_unequal_counts_matches_host(monkeypatch):
    """One whole shard misses the last scrape: every shard stages
    near-regular internally, but per-shard sample counts differ INSIDE the
    queried range, so the jitter mesh kernel (which applies one shard's
    window structure to every row) must NOT run — the re-verify in
    parallel/exec.py falls back, and results still match the host path."""
    import jax

    import filodb_tpu.parallel.mesh as PM
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import Dataset, METRIC_TAG, PROM_COUNTER, shard_for
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(9)
    n = 120
    assigns = []
    for i in range(64):
        tags = {METRIC_TAG: "rq_total", "_ws_": "w", "_ns_": "n",
                "inst": f"h{i}"}
        assigns.append((tags, shard_for(tags, spread=3, num_shards=8)))
    shards_seen = {s for _, s in assigns}
    assert len(shards_seen) > 1
    short_shard = min(shards_seen)  # this ENTIRE shard misses the last scrape

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    for tags, shard in assigns:
        dev = np.rint(rng.uniform(-0.1, 0.1, n) * INTERVAL).astype(np.int64)
        ts = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL + dev
        vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
        if shard == short_shard:
            ts, vals = ts[:-1], vals[:-1]
        ms.shard("prometheus", shard).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts, {"count": vals})
        )
    host = QueryEngine(ms, "prometheus")
    mesh = QueryEngine(ms, "prometheus",
                       PlannerParams(mesh=make_mesh(jax.devices()[:1])))
    # end past the LAST scrape (slot 120 at BASE+1_200_000) so the staged
    # range actually contains the count mismatch
    start, end = (BASE + 400_000) / 1000, (BASE + 1_250_000) / 1000

    def jitter_kernel_must_not_run(*a, **k):
        raise AssertionError(
            "distributed_agg_range_jitter ran on shards with unequal counts"
        )

    monkeypatch.setattr(
        PM, "distributed_agg_range_jitter", jitter_kernel_must_not_run
    )
    rh = host.query_range("sum(rate(rq_total[5m]))", start, end, 60)
    rm = mesh.query_range("sum(rate(rq_total[5m]))", start, end, 60)
    vh = np.asarray(rh.grids[0].values_np())
    vm = np.asarray(rm.grids[0].values_np())
    np.testing.assert_array_equal(np.isnan(vh), np.isnan(vm))
    ok = ~np.isnan(vh)
    np.testing.assert_allclose(vm[ok], vh[ok], rtol=2e-3)
