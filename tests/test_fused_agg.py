"""Fused single-dispatch cross-shard aggregation (doc/perf.md).

Parity contract: FusedAggregateExec must agree with the reference
``ReduceAggregateExec -> N x SelectRawPartitionsExec`` tree across
counters, gauges, jittered grids and the partial-results fallback — NaN
(absence) masks bit-identical, values within float32 accumulation-order
tolerance (order-independent ops min/max/count compare exactly).

Plus the O(1) dispatch guarantee: a warm ``sum(rate())`` over 8 shards
issues exactly ONE kernel dispatch (asserted via the JIT dispatch
counters), and the superblock/window-matrix caches behave (shard-version
invalidation, single construction under race, LRU on hit).
"""

import threading

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import counter_batch, machine_metrics

pytestmark = pytest.mark.perf

BASE = 1_600_000_000_000
N_SHARDS = 8


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=48, n_samples=300, start_ms=BASE), spread=3
    )
    ms.ingest_routed(
        "ds",
        counter_batch(n_series=24, n_samples=300, start_ms=BASE,
                      metric="http_errors_total", resets=True, seed=11),
        spread=3,
    )
    ms.ingest_routed(
        "ds", machine_metrics(n_series=48, n_samples=300, start_ms=BASE), spread=3
    )
    return ms


@pytest.fixture(scope="module")
def engines(store):
    fused = QueryEngine(store, "ds")
    ref = QueryEngine(store, "ds", PlannerParams(fused_aggregate=False))
    return fused, ref


START = (BASE + 600_000) / 1000
END = START + 1200
STEP = 60


def _rows(res):
    out = {}
    for g in res.grids:
        for lbls, vals in zip(g.labels, g.values_np()):
            out[tuple(sorted(lbls.items()))] = np.asarray(vals)
    return out


def assert_parity(fused, ref, q, start=START, end=END, step=STEP,
                  exact=None, **kw):
    """exact=None auto-detects: the count aggregate is bit-identical by
    construction (it counts non-NaN series, and the NaN masks are asserted
    equal); everything else allows float32 accumulation-order ulps between
    the single fused program and the per-shard kernel + partial-merge
    reference (min/max are order-independent as AGGREGATES, but their
    per-series INPUTS may differ in ulp across kernel variants)."""
    rf = fused.query_range(q, start, end, step, **kw)
    rr = ref.query_range(q, start, end, step, **kw)
    a, b = _rows(rf), _rows(rr)
    assert a.keys() == b.keys(), (q, sorted(a), sorted(b))
    if exact is None:
        exact = q.startswith("count(") or q.startswith("count by")
    for k in a:
        na, nb = np.isnan(a[k]), np.isnan(b[k])
        assert (na == nb).all(), (q, k, "NaN masks differ")
        if exact:
            assert (a[k][~na] == b[k][~nb]).all(), (q, k)
        else:
            np.testing.assert_allclose(
                a[k][~na], b[k][~nb], rtol=2e-5, atol=1e-6, err_msg=f"{q} {k}"
            )
    return rf, rr


def _plan_root(engine, q, start=START, end=END, step=STEP):
    from filodb_tpu.query.promql import query_range_to_logical_plan

    plan = query_range_to_logical_plan(q, start, end, step)
    return engine.planner.materialize(plan)


# -- parity ------------------------------------------------------------------


@pytest.mark.parametrize("q", [
    "sum(rate(http_requests_total[5m]))",
    "sum by (instance) (rate(http_requests_total[5m]))",
    "avg(increase(http_requests_total[5m]))",
    "max(irate(http_requests_total[5m]))",
    "count by (job) (delta(http_requests_total[5m]))",
    "sum(rate(http_errors_total[5m]))",  # counters WITH resets
    "min(changes(http_requests_total[5m]))",
])
def test_fused_parity_counters(engines, q):
    assert_parity(*engines, q)


@pytest.mark.parametrize("q", [
    "sum(avg_over_time(heap_usage0[3m]))",
    "avg by (instance) (max_over_time(heap_usage0[2m]))",
    "min(min_over_time(heap_usage0[3m]))",
    "max(stddev_over_time(heap_usage0[3m]))",
    "count(last_over_time(heap_usage0[3m]))",
    "sum(heap_usage0)",       # plain selector (lookback last)
    "sum by (job) (heap_usage0)",
])
def test_fused_parity_gauges(engines, q):
    assert_parity(*engines, q)


def test_fused_parity_offset(engines):
    assert_parity(*engines, "sum(rate(http_requests_total[5m] offset 5m))")


def test_fused_parity_jittered():
    """Per-series scrape jitter: per-shard blocks stage near-regular, the
    superblock runs the general fused kernel; the reference tree runs the
    per-shard jittered MXU path — results must still agree."""
    rng = np.random.default_rng(3)
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(4)))
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import METRIC_TAG, PROM_COUNTER, shard_for

    n, m = 24, 240
    base_ts = BASE + np.arange(m, dtype=np.int64) * 10_000
    for i in range(n):
        tags = {METRIC_TAG: "jit_total", "_ws_": "w", "_ns_": "n",
                "instance": f"h{i}"}
        dev = rng.integers(-400, 400, size=m)
        vals = np.cumsum(rng.uniform(0, 5, size=m)) + 1e6
        ms.shard("ds", shard_for(tags, spread=2, num_shards=4)).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, base_ts + dev, {"count": vals})
        )
    fused = QueryEngine(ms, "ds")
    ref = QueryEngine(ms, "ds", PlannerParams(fused_aggregate=False))
    start = (BASE + 400_000) / 1000
    assert_parity(fused, ref, "sum(rate(jit_total[5m]))", start, start + 900, 60)
    assert_parity(fused, ref, "max(rate(jit_total[5m]))", start, start + 900, 60)


def test_fused_partial_results_falls_back(engines):
    """allow_partial_results needs the merge tree's lost-child tolerance:
    the fused node must delegate to its reference fallback subtree (visible
    in the trace) and still return identical results."""
    fused, ref = engines
    q = "sum(rate(http_requests_total[5m]))"
    rf, _ = assert_parity(fused, ref, q, allow_partial_results=True)

    def names(sp, acc):
        acc.add(sp.name)
        for c in sp.children:
            names(c, acc)
        return acc

    seen = names(rf.trace, set())
    assert "FusedAggregateExec" in seen
    assert "ReduceAggregateExec" in seen  # the fallback subtree executed


def test_fused_plan_selection(engines):
    fused, ref = engines
    q = "sum(rate(http_requests_total[5m]))"
    assert type(_plan_root(fused, q)).__name__ == "FusedAggregateExec"
    assert type(_plan_root(ref, q)).__name__ == "ReduceAggregateExec"
    # epilogue ops fuse too: global topk/bottomk and (grouped) quantile
    for q in ("topk(3, rate(http_requests_total[5m]))",
              "bottomk(2, heap_usage0)",
              "quantile(0.9, rate(http_requests_total[5m]))"):
        assert type(_plan_root(fused, q)).__name__ == "FusedAggregateExec", q
    # non-fusable shapes keep the reference tree on the fused engine
    # (grouped topk keeps the per-shard candidate pre-reduction tree)
    for q in ("stddev(rate(http_requests_total[5m]))",
              "topk by (job) (3, rate(http_requests_total[5m]))",
              "sum(quantile_over_time(0.9, heap_usage0[3m]))"):
        assert type(_plan_root(fused, q)).__name__ != "FusedAggregateExec", q


def test_fused_sees_new_ingest(engines):
    """The superblock cache is shard-version-keyed: ingest invalidates it
    and the next query reflects the new samples."""
    fused, ref = engines
    ms = fused.memstore
    q = "sum(count_over_time(heap_usage0[10m]))"
    # range reaching past the staged head so appended samples land IN range
    end = (BASE + 330 * 10_000) / 1000
    before = _rows(fused.query_range(q, START, end, STEP))
    ms.ingest_routed(
        "ds",
        machine_metrics(n_series=48, n_samples=330, start_ms=BASE, seed=42),
        spread=3,
    )
    after = _rows(fused.query_range(q, START, end, STEP))
    assert any(
        np.nansum(after[k]) > np.nansum(before[k]) for k in before
    ), "new in-range samples must show up after ingest"
    assert_parity(fused, ref, q, START, end)


def test_fused_cached_superblock_respects_limits(engines):
    """Per-request limits (execute_plan narrows them) must be enforced on
    the superblock-cache HIT path too, not only on the build path."""
    from filodb_tpu.query.exec.transformers import QueryError
    from filodb_tpu.query.promql import query_range_to_logical_plan

    fused, _ = engines
    q = "sum(rate(http_requests_total[5m]))"
    fused.query_range(q, START, END, STEP)  # build + cache the superblock
    plan = query_range_to_logical_plan(q, START, END, STEP)
    with pytest.raises(QueryError, match="limit"):
        fused.execute_plan(plan, max_series=1)


# -- O(1) dispatch -----------------------------------------------------------


def _dispatch_total() -> int:
    from filodb_tpu.testkit import kernel_dispatch_total

    return kernel_dispatch_total()


def test_warm_sum_rate_is_single_dispatch(engines):
    fused, _ = engines
    q = "sum(rate(http_requests_total[5m]))"
    for _ in range(2):  # stage + compile + fill every cache
        fused.query_range(q, START, END, STEP)
    before = _dispatch_total()
    fused.query_range(q, START, END, STEP)
    assert _dispatch_total() - before == 1, (
        "warm fused sum(rate) must issue exactly ONE kernel dispatch"
    )


def test_reference_tree_dispatches_per_shard(engines):
    """Sanity for the counter itself: the reference tree dispatches O(shards)
    (range kernel + segment reduce per non-empty shard)."""
    _, ref = engines
    q = "sum(rate(http_requests_total[5m]))"
    for _ in range(2):
        ref.query_range(q, START, END, STEP)
    before = _dispatch_total()
    ref.query_range(q, START, END, STEP)
    assert _dispatch_total() - before > 1


# -- cache mechanics ---------------------------------------------------------


def test_superblock_cache_version_keying():
    from filodb_tpu.ops.staging import SuperblockCache

    c = SuperblockCache(max_entries=2)
    c.put("k", (1, 1), "v", 10)
    assert c.get("k", (1, 1)) == "v"
    # version moved: a stale entry never serves, but it is RETAINED so the
    # interval-aware refresh path can revalidate or extend it in place
    assert c.get("k", (1, 2)) is None
    assert c.peek("k") == ((1, 1), "v", 10)
    # revalidate = CAS on the stored version vector
    assert not c.revalidate("k", (9, 9), (1, 2))
    assert c.revalidate("k", (1, 1), (1, 2))
    assert c.get("k", (1, 2)) == "v"
    # drop removes outright (aborted in-place extension)
    c.drop("k")
    assert c.peek("k") is None


def test_superblock_cache_lru_on_hit():
    from filodb_tpu.ops.staging import SuperblockCache

    c = SuperblockCache(max_entries=2)
    c.put("a", (1,), "va", 1)
    c.put("b", (1,), "vb", 1)
    assert c.get("a", (1,)) == "va"  # refresh a
    c.put("c", (1,), "vc", 1)       # evicts b (LRU), not a
    assert c.get("a", (1,)) == "va"
    assert c.get("b", (1,)) is None


def test_get_wm_single_construction_under_race():
    """Two concurrent misses on one key must build ONCE (the loser used to
    build a duplicate device-resident matrix set and leak it)."""
    from filodb_tpu.parallel import exec as PX

    built = []
    gate = threading.Barrier(4)

    def ctor():
        built.append(1)
        import time

        time.sleep(0.05)  # hold the build window open for the racers
        return object()

    results = []

    def worker():
        gate.wait()
        results.append(PX._get_wm(("race-key",), ctor))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(built) == 1
    assert all(r is results[0] for r in results)
    PX._WM_CACHE.pop(("race-key",))


def test_get_wm_lru_on_hit():
    from filodb_tpu.parallel import exec as PX

    saved = [(k, PX._WM_CACHE.pop(k)) for k in PX._WM_CACHE.keys()]
    try:
        for i in range(PX._WM_CACHE.capacity):
            PX._get_wm(("lru", i), lambda i=i: i)
        PX._get_wm(("lru", 0), lambda: "rebuilt?")  # hit refreshes slot 0
        PX._get_wm(("lru", "new"), lambda: "new")    # evicts ("lru", 1)
        assert ("lru", 0) in PX._WM_CACHE
        assert ("lru", 1) not in PX._WM_CACHE
    finally:
        PX._WM_CACHE.clear()
        for k, v in saved:
            PX._get_wm(k, lambda v=v: v)


def test_memo_on_single_build_under_race():
    """The shared memo_on helper (window matrices / group ids): concurrent
    same-key misses on one object build once; different keys never clobber
    each other's attached memo dict."""
    from filodb_tpu.singleflight import memo_on

    class Obj:
        pass

    o = Obj()
    built = []
    gate = threading.Barrier(6)

    def worker(key):
        gate.wait()
        memo_on(o, "_memo", key, lambda: built.append(key) or key)

    threads = [
        threading.Thread(target=worker, args=(k,))
        for k in ("a", "a", "a", "b", "b", "c")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(built) == ["a", "b", "c"]  # one build per key
    assert o._memo == {"a": "a", "b": "b", "c": "c"}  # no dict clobbering


def test_keyed_single_flight_prunes_lock_table():
    from filodb_tpu.singleflight import KeyedSingleFlight

    sf = KeyedSingleFlight(max_keys=8, alive=lambda k: k == "keep")
    keep_lock = sf.lock("keep")
    for i in range(20):
        sf.lock(("k", i))
    assert len(sf) <= 9  # pruned down around the cap
    assert sf.lock("keep") is keep_lock  # alive keys survive pruning
