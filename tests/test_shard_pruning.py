"""Query-side shard pruning by shard-key hash + spread (reference
SingleClusterPlanner.scala:424 shardsFromFilters): a selector carrying
equality filters on every shard-key column fans out to only the 2^spread
shards ingest routing can have placed it on."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine, SingleClusterPlanner
from filodb_tpu.core.schemas import Dataset, shard_for
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.testkit import counter_batch

N_SHARDS = 128
SPREAD = 3
BASE = 1_600_000_000_000
Q = 'sum(rate(http_requests_total{_ws_="demo",_ns_="App-2"}[5m]))'


@pytest.fixture(scope="module")
def ms():
    m = TimeSeriesMemStore()
    m.setup(Dataset("prometheus"), range(N_SHARDS))
    m.ingest_routed("prometheus", counter_batch(n_series=64, n_samples=60, start_ms=BASE), spread=SPREAD)
    return m


def _materialize(ms, q):
    pl = SingleClusterPlanner(ms, "prometheus", params=PlannerParams(spread=SPREAD))
    start = (BASE + 400_000) / 1000
    end = (BASE + 580_000) / 1000
    return pl, pl.materialize(query_range_to_logical_plan(q, start, end, 60))


def _fanout(ep) -> int:
    """Shard fan-out of a materialized aggregate: the fused single-dispatch
    node carries its shard list; the reference tree fans out one leaf per
    shard."""
    if hasattr(ep, "shard_nums"):
        return len(ep.shard_nums)
    return ep.print_tree().count("SelectRawPartitionsExec")


def test_shardkey_filters_prune_to_2_pow_spread(ms):
    _, ep = _materialize(ms, Q)
    n_leaves = _fanout(ep)
    assert 1 <= n_leaves <= 2**SPREAD, ep.print_tree()
    assert n_leaves < N_SHARDS


def test_pruned_shards_cover_ingest_routing(ms):
    """The pruned set is exactly a superset of where ingest put the series."""
    pl, _ = _materialize(ms, Q)
    from filodb_tpu.core.filters import equals

    filters = [equals("_metric_", "http_requests_total"), equals("_ws_", "demo"), equals("_ns_", "App-2")]
    pruned = set(pl.shards_for(filters))
    for i in range(64):
        tags = {"_metric_": "http_requests_total", "_ws_": "demo", "_ns_": "App-2",
                "instance": f"host-{i}", "job": "api"}
        assert shard_for(tags, SPREAD, N_SHARDS) in pruned


def test_pruned_result_matches_scan_all(ms):
    """Engine result parity: pruned fan-out == scan-all fan-out."""
    eng = QueryEngine(ms, "prometheus", PlannerParams(spread=SPREAD))
    start = (BASE + 400_000) / 1000
    end = (BASE + 580_000) / 1000
    res_pruned = eng.query_range(Q, start, end, 60)
    # un-keyed query scans everything (no _ws_/_ns_ filters -> no pruning)
    res_all = eng.query_range("sum(rate(http_requests_total[5m]))", start, end, 60)
    a = res_pruned.grids[0].values_np()
    b = res_all.grids[0].values_np()
    np.testing.assert_allclose(a, b, rtol=1e-6)
    assert np.isfinite(a).any()


def test_missing_shardkey_filter_scans_all(ms):
    _, ep = _materialize(ms, "sum(rate(http_requests_total[5m]))")
    assert _fanout(ep) == N_SHARDS


def test_regex_on_shardkey_scans_all(ms):
    _, ep = _materialize(ms, 'sum(rate(http_requests_total{_ws_=~"de.*",_ns_="App-2"}[5m]))')
    assert _fanout(ep) == N_SHARDS


def test_mesh_path_packs_only_pruned_shards(ms):
    """VERDICT done-criterion: the mesh path packs only the pruned shards."""
    import jax

    pl = SingleClusterPlanner(
        ms, "prometheus",
        params=PlannerParams(spread=SPREAD, mesh=__import__("filodb_tpu.parallel.mesh", fromlist=["make_mesh"]).make_mesh(jax.devices("cpu")[:1])),
    )
    start = (BASE + 400_000) / 1000
    end = (BASE + 580_000) / 1000
    ep = pl.materialize(query_range_to_logical_plan(Q, start, end, 60))
    from filodb_tpu.parallel.exec import MeshAggregateExec

    assert isinstance(ep, MeshAggregateExec)
    assert len(ep.shard_nums) <= 2**SPREAD
