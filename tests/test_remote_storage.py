"""Prometheus remote write/read protocol tests (reference remote-read proto
support; wire format snappy+protobuf compatible with prometheus/prompb)."""

import json
import urllib.request

import numpy as np
import pytest

from filodb_tpu.api import snappy
from filodb_tpu.api.http import serve_background
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore

BASE = 1_600_000_000_000


class TestSnappy:
    def test_literal_roundtrip(self):
        for data in (b"", b"x", b"hello world" * 100, bytes(range(256)) * 300):
            assert snappy.decompress(snappy.compress(data)) == data

    def test_decompress_copy_tags(self):
        # hand-crafted stream with a 2-byte-offset copy: "abcd" + copy(len 8,
        # offset 4) -> "abcdabcdabcd"
        payload = bytes([12])  # uvarint 12
        payload += bytes([(4 - 1) << 2]) + b"abcd"  # literal "abcd"
        payload += bytes([((8 - 1) << 2) | 2, 4, 0])  # copy len 8 offset 4
        assert snappy.decompress(payload) == b"abcdabcdabcd"

    def test_decompress_one_byte_offset_copy(self):
        # literal "ab", copy kind-1: len 4, offset 2 -> "ababab"
        payload = bytes([6])
        payload += bytes([(2 - 1) << 2]) + b"ab"
        payload += bytes([((4 - 4) << 2) | 1 | (0 << 5), 2])
        assert snappy.decompress(payload) == b"ababab"

    def test_bad_offset_rejected(self):
        payload = bytes([4, (1 - 1) << 2, ord("a"), ((4 - 4) << 2) | 1, 9])
        with pytest.raises(ValueError):
            snappy.decompress(payload)


@pytest.fixture()
def api():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(2))
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine)
    yield f"http://127.0.0.1:{port}", ms
    srv.shutdown()


def make_write_body(n_series=3, n_samples=10):
    from filodb_tpu.api import remote_pb2 as pb

    w = pb.WriteRequest()
    for i in range(n_series):
        ts = w.timeseries.add()
        ts.labels.add(name="__name__", value="remote_metric")
        ts.labels.add(name="instance", value=f"h{i}")
        for k in range(n_samples):
            ts.samples.add(value=float(i * 100 + k), timestamp=BASE + k * 15_000)
    return snappy.compress(w.SerializeToString())


def test_remote_write_then_query(api):
    url, ms = api
    body = make_write_body()
    req = urllib.request.Request(f"{url}/api/v1/write", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.status == 204
    engine = QueryEngine(ms, "prometheus")
    res = engine.query_instant("remote_metric", (BASE + 200_000) / 1000)
    assert sum(g.n_series for g in res.grids) == 3


def test_remote_read_roundtrip(api):
    from filodb_tpu.api import remote_pb2 as pb

    url, ms = api
    # write first
    req = urllib.request.Request(f"{url}/api/v1/write", data=make_write_body(), method="POST")
    urllib.request.urlopen(req, timeout=60)
    # read back with a matcher
    rr = pb.ReadRequest()
    q = rr.queries.add()
    q.start_timestamp_ms = BASE
    q.end_timestamp_ms = BASE + 10_000_000
    q.matchers.add(type=0, name="__name__", value="remote_metric")
    q.matchers.add(type=2, name="instance", value="h[01]")
    body = snappy.compress(rr.SerializeToString())
    req = urllib.request.Request(f"{url}/api/v1/read", data=body, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        out = r.read()
    resp = pb.ReadResponse()
    resp.ParseFromString(snappy.decompress(out))
    assert len(resp.results) == 1
    series = resp.results[0].timeseries
    assert len(series) == 2  # h0, h1 via regex matcher
    names = {dict((l.name, l.value) for l in s.labels)["instance"] for s in series}
    assert names == {"h0", "h1"}
    assert len(series[0].samples) == 10


def test_rules_and_status_stubs(api):
    url, _ = api
    with urllib.request.urlopen(f"{url}/api/v1/rules", timeout=30) as r:
        assert json.loads(r.read())["data"] == {"groups": []}
    with urllib.request.urlopen(f"{url}/api/v1/status/flags", timeout=30) as r:
        assert json.loads(r.read())["status"] == "success"


class TestSnappyFuzz:
    @pytest.mark.parametrize("seed", range(5))
    def test_garbage_never_hangs_or_crashes(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(50):
            blob = rng.integers(0, 256, rng.integers(0, 200)).astype(np.uint8).tobytes()
            try:
                out = snappy.decompress(blob)
                assert isinstance(out, bytes)  # lucky valid stream
            except (ValueError, IndexError):
                pass  # clean rejection

    def test_roundtrip_fuzz(self):
        rng = np.random.default_rng(42)
        for _ in range(20):
            data = rng.integers(0, 256, rng.integers(0, 300_000)).astype(np.uint8).tobytes()
            assert snappy.decompress(snappy.compress(data)) == data
