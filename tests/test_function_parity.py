"""Parity tests for the remaining reference-specific functions
(LastOverTimeIsMadOutlier, OrVector, histogram_bucket, limit, optimize
markers, chunkmeta debug)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.lpopt import AggRuleProvider, IncludeAggRule, optimize_with_preagg
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.query import logical as L
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.testkit import histogram_batch, machine_metrics

BASE = 1_600_000_000_000
START_S = (BASE + 600_000) / 1000
END_S = (BASE + 1_500_000) / 1000


@pytest.fixture(scope="module")
def engine():
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("prometheus"), [0, 1])
    ms.ingest_routed("prometheus", machine_metrics(n_series=4, n_samples=200, start_ms=BASE), spread=1)
    ms.ingest_routed("prometheus", histogram_batch(n_series=2, n_samples=150, start_ms=BASE), spread=1)
    return QueryEngine(ms, "prometheus")


def test_mad_outlier_flags_anomaly(engine):
    # gauges are ~N(50,20): with tolerance 3 most windows are not outliers
    res = engine.query_range(
        "last_over_time_is_mad_outlier(1000, 1, heap_usage0[10m])", START_S, END_S, 60)
    assert not list(res.all_series())  # huge tolerance -> nothing flagged
    res2 = engine.query_range(
        "last_over_time_is_mad_outlier(0.001, 1, heap_usage0[10m])", START_S, END_S, 60)
    assert list(res2.all_series())  # tiny tolerance -> everything flagged


def test_or_vector_fills_nans(engine):
    # windows before data start are NaN; or_vector turns them into 7
    res = engine.query_range("or_vector(sum_over_time(heap_usage0[30s]), 7)", START_S, END_S, 120)
    for _, _, vals in res.all_series():
        assert not np.isnan(vals).any()


def test_histogram_bucket_selects_le(engine):
    res = engine.query_range(
        "histogram_bucket(0.5, rate(http_request_latency[5m]))", START_S, END_S, 60)
    series = list(res.all_series())
    assert len(series) == 2
    for lbls, _, vals in series:
        assert lbls["le"] == "0.5"
        assert (vals >= 0).all()


def test_limit_function(engine):
    res = engine.query_range("limit(2, heap_usage0)", START_S, END_S, 60)
    assert sum(g.n_series for g in res.grids) == 2


def test_no_optimize_marker_blocks_preagg():
    provider = AggRuleProvider([IncludeAggRule("m", frozenset({"job"}))])
    p1 = optimize_with_preagg(
        query_range_to_logical_plan("sum by (job) (m)", 1000, 2000, 15), provider)
    p2 = optimize_with_preagg(
        query_range_to_logical_plan("no_optimize(sum by (job) (m))", 1000, 2000, 15), provider)
    m1 = [f.value for rs in L.leaf_raw_series(p1) for f in rs.filters if f.column == "_metric_"]
    m2 = [f.value for rs in L.leaf_raw_series(p2) for f in rs.filters if f.column == "_metric_"]
    assert m1 == ["m:agg"] and m2 == ["m"]


def test_optimize_marker_executes_as_noop(engine):
    res = engine.query_range("no_optimize(sum(heap_usage0))", START_S, END_S, 60)
    assert sum(g.n_series for g in res.grids) == 1


def test_chunkmeta_debug_query(engine):
    res = engine.query_range("_filodb_chunkmeta_all(heap_usage0)", START_S, END_S, 60)
    assert res.metadata is not None
    assert len(res.metadata) == 4
    rec = res.metadata[0]
    assert rec["schema"] == "gauge" and rec["numChunks"] >= 2
    assert rec["chunks"][0]["numRows"] == 100
