"""Distributed batch downsampler (downsample/distributed.py): 2-process
jobs with atomic shard commits, claim heartbeats, stale-claim breaking, and
kill/resume (reference spark-jobs DownsamplerMain over executors +
CassandraColumnStore.getScanSplits:500 work splitting)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from filodb_tpu.core.schemas import Dataset
from filodb_tpu.downsample.distributed import (
    _claim_path,
    _job_dir,
    job_complete,
    member_ordered_shards,
    run_worker,
)
from filodb_tpu.downsample.downsampler import (
    ShardDownsampler,
    batch_downsample,
)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.columnstore import LocalColumnStore
from filodb_tpu.store.flush import FlushCoordinator, recover_shard
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000
PERIODS = (300_000,)  # 5m


def _seed_store(root, n_shards=4, n_series=6, n_samples=400):
    from filodb_tpu.memstore.shard import StoreConfig

    store = LocalColumnStore(str(root))
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("ds"), range(n_shards))
    for s in range(n_shards):
        ms.ingest("ds", s, machine_metrics(
            n_series=n_series, n_samples=n_samples, start_ms=BASE + s,
        ))
    fc = FlushCoordinator(ms, store)
    for s in range(n_shards):
        fc.flush_shard("ds", s)
    return store, ms


def _oracle_totals(store, ms, n_shards):
    """Single-process batch_downsample result: per-shard sample totals and
    value checksums of the 5m dataset."""
    dsm = TimeSeriesMemStore()
    d = ShardDownsampler(dsm, "ds", periods_ms=PERIODS)
    batch_downsample(store, ms, "ds", range(n_shards), dsm, d)
    out = {}
    for s in range(n_shards):
        sh = dsm.shard("ds_5m", s)
        tot = 0.0
        n = 0
        for pid in sh.lookup_partitions([], 0, 2**62):
            ts, vals = sh.partition(int(pid)).samples_in_range(0, 2**62, "avg")
            tot += float(np.nansum(vals))
            n += len(ts)
        out[s] = (n, round(tot, 6))
    return out


def _recovered_totals(root, n_shards):
    store = LocalColumnStore(str(root))
    dsm = TimeSeriesMemStore()
    dsm.setup(Dataset("ds_5m"), range(n_shards))
    out = {}
    for s in range(n_shards):
        recover_shard(dsm, store, "ds_5m", s)
        sh = dsm.shard("ds_5m", s)
        tot = 0.0
        n = 0
        for pid in sh.lookup_partitions([], 0, 2**62):
            ts, vals = sh.partition(int(pid)).samples_in_range(0, 2**62, "avg")
            tot += float(np.nansum(vals))
            n += len(ts)
        out[s] = (n, round(tot, 6))
    return out


def test_two_workers_split_the_job(tmp_path):
    store, ms = _seed_store(tmp_path)
    want = _oracle_totals(store, ms, 4)
    r1 = run_worker(str(tmp_path), "ds", range(4), PERIODS, worker_id="w1",
                    members=["w1", "w2"], self_url="w1")
    r2 = run_worker(str(tmp_path), "ds", range(4), PERIODS, worker_id="w2",
                    members=["w1", "w2"], self_url="w2")
    assert sorted(r1.shards_done + r2.shards_done) == [0, 1, 2, 3]
    assert job_complete(str(tmp_path), "ds", range(4))
    assert _recovered_totals(tmp_path, 4) == want


def test_rerun_skips_committed_shards(tmp_path):
    store, ms = _seed_store(tmp_path)
    r1 = run_worker(str(tmp_path), "ds", range(4), PERIODS, worker_id="w1")
    assert sorted(r1.shards_done) == [0, 1, 2, 3]
    r2 = run_worker(str(tmp_path), "ds", range(4), PERIODS, worker_id="w2")
    assert r2.shards_done == [] and sorted(r2.shards_skipped) == [0, 1, 2, 3]


def test_member_ordering_disjoint_start():
    a = member_ordered_shards(range(8), ["u1", "u2"], "u1")
    b = member_ordered_shards(range(8), ["u1", "u2"], "u2")
    assert set(a[:4]).isdisjoint(b[:4])
    assert sorted(a) == sorted(b) == list(range(8))


def test_stale_claim_broken_fresh_claim_respected(tmp_path):
    _seed_store(tmp_path, n_shards=1)
    job = _job_dir(str(tmp_path), "ds", "default")
    os.makedirs(job, exist_ok=True)
    # a fresh claim by a live worker blocks the shard
    with open(_claim_path(job, 0), "w") as f:
        json.dump({"worker": "alive"}, f)
    r = run_worker(str(tmp_path), "ds", [0], PERIODS, worker_id="w2",
                   stale_s=60.0)
    assert r.shards_done == [] and r.shards_skipped == [0]
    # backdate the claim beyond stale_s: the straggler gets reassigned
    old = os.path.getmtime(_claim_path(job, 0)) - 120
    os.utime(_claim_path(job, 0), (old, old))
    r = run_worker(str(tmp_path), "ds", [0], PERIODS, worker_id="w2",
                   stale_s=60.0)
    assert r.shards_done == [0] and r.claims_broken == [0]


def test_kill_and_resume_two_processes(tmp_path):
    """The done-criterion from the round verdict: worker 1 is KILLED while
    holding a claim (no commit); worker 2 breaks the stale claim, redoes
    the shard, and the final store equals the single-process oracle."""
    store, ms = _seed_store(tmp_path)
    want = _oracle_totals(store, ms, 4)
    env = dict(
        os.environ, FILODB_DS_CRASH_AFTER_CLAIM="2",
        JAX_PLATFORMS="cpu", FILODB_PLATFORM="cpu",
    )
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "from filodb_tpu.downsample.distributed import run_worker\n"
        f"run_worker({str(tmp_path)!r}, 'ds', range(4), (300000,), "
        "worker_id='victim')\n"
    )
    p = subprocess.run([sys.executable, "-c", code], env=env, timeout=300,
                       capture_output=True, text=True)
    assert p.returncode == 17, p.stderr[-500:]
    job = _job_dir(str(tmp_path), "ds", "default")
    assert os.path.exists(_claim_path(job, 2)), "victim died holding a claim"
    assert not os.path.exists(os.path.join(job, "shard-2.done"))
    # backdate the orphaned claim (stand-in for waiting out stale_s)
    old = os.path.getmtime(_claim_path(job, 2)) - 120
    os.utime(_claim_path(job, 2), (old, old))
    r2 = run_worker(str(tmp_path), "ds", range(4), PERIODS,
                    worker_id="rescuer", stale_s=60.0)
    assert 2 in r2.shards_done and 2 in r2.claims_broken
    assert job_complete(str(tmp_path), "ds", range(4))
    assert _recovered_totals(tmp_path, 4) == want
