"""Randomized cross-checking: random data shapes x random window/step
configs x every major range function vs the numpy oracle (the
property-style arm of the SURVEY §4(f) strategy)."""

import numpy as np
import pytest

import oracle
from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.staging import stage_series

BASE = 1_600_000_000_000

FUNCS_GAUGE = [
    "sum_over_time", "avg_over_time", "min_over_time", "max_over_time",
    "count_over_time", "last_over_time", "stddev_over_time", "changes",
    "idelta", "deriv",
]
FUNCS_COUNTER = ["rate", "increase", "irate"]


def random_case(rng):
    n_series = int(rng.integers(1, 9))
    n = int(rng.integers(5, 400))
    interval = int(rng.integers(1_000, 30_000))
    jitter = rng.random() < 0.5
    window_ms = int(rng.integers(2, 40)) * 15_000
    step_ms = int(rng.integers(1, 10)) * 30_000
    num_steps = int(rng.integers(3, 40))
    start = BASE + int(rng.integers(0, 2 * window_ms))
    series = []
    for _ in range(n_series):
        if jitter:
            gaps = rng.integers(max(interval // 2, 1), interval * 2, n)
            ts = BASE + np.cumsum(gaps).astype(np.int64)
        else:
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * interval
        series.append(ts)
    return series, window_ms, step_ms, num_steps, start


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_gauge_functions(seed):
    rng = np.random.default_rng(seed)
    tss, window, step, nsteps, start = random_case(rng)
    series = [(ts, 50 + 20 * rng.standard_normal(len(ts))) for ts in tss]
    func = FUNCS_GAUGE[seed % len(FUNCS_GAUGE)]
    block = stage_series(series, BASE)
    params = K.RangeParams(start, step, nsteps, window)
    got = np.asarray(K.run_range_function(func, block, params))[: len(series), :nsteps]
    want = np.stack([
        oracle.range_function(func, t, v, start, step, nsteps, window)
        for t, v in series
    ])
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want),
                                  err_msg=f"{func} seed={seed}")
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=5e-4, atol=5e-3,
                               err_msg=f"{func} seed={seed}")


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_counter_functions(seed):
    rng = np.random.default_rng(100 + seed)
    tss, window, step, nsteps, start = random_case(rng)
    series = []
    for ts in tss:
        vals = np.cumsum(rng.uniform(0, 10, len(ts))) + rng.uniform(0, 1e6)
        if rng.random() < 0.5 and len(ts) > 10:  # resets
            k = int(rng.integers(2, len(ts) - 1))
            vals[k:] -= vals[k] - rng.uniform(0, 3)
        series.append((ts, vals))
    func = FUNCS_COUNTER[seed % len(FUNCS_COUNTER)]
    block = stage_series(series, BASE, counter_corrected=True)
    params = K.RangeParams(start, step, nsteps, window)
    got = np.asarray(
        K.run_range_function(func, block, params, is_counter=True)
    )[: len(series), :nsteps]
    want = np.stack([
        oracle.range_function(func, t, v, start, step, nsteps, window, is_counter=True)
        for t, v in series
    ])
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want),
                                  err_msg=f"{func} seed={seed}")
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=2e-3, atol=1e-3,
                               err_msg=f"{func} seed={seed}")


def test_degenerate_shapes():
    # single sample, single series, single step
    block = stage_series([(np.array([BASE + 1000]), np.array([5.0]))], BASE)
    params = K.RangeParams(BASE + 2000, 1000, 1, 10_000)
    got = np.asarray(K.run_range_function("last_over_time", block, params))[0, 0]
    assert got == 5.0
    # empty series among real ones
    block = stage_series(
        [(np.array([], dtype=np.int64), np.array([])),
         (np.array([BASE + 1000]), np.array([7.0]))], BASE)
    got = np.asarray(K.run_range_function("sum_over_time", block, params))[:2, 0]
    assert np.isnan(got[0]) and got[1] == 7.0


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_regular_grid_mxu_path(seed):
    """Same fuzz harness pinned to regular grids: exercises the MXU matmul
    path across random window/step configs."""
    rng = np.random.default_rng(500 + seed)
    n_series = int(rng.integers(2, 7))
    n = int(rng.integers(20, 300))
    interval = int(rng.integers(5_000, 20_000))
    window_ms = int(rng.integers(2, 30)) * 15_000
    step_ms = int(rng.integers(1, 8)) * 30_000
    nsteps = int(rng.integers(3, 30))
    start = BASE + int(rng.integers(0, 2 * window_ms))
    ts = BASE + (1 + np.arange(n, dtype=np.int64)) * interval
    series = [(ts.copy(), 50 + 20 * rng.standard_normal(n)) for _ in range(n_series)]
    func = FUNCS_GAUGE[seed % len(FUNCS_GAUGE)]
    block = stage_series(series, BASE)
    assert block.regular_ts is not None
    params = K.RangeParams(start, step_ms, nsteps, window_ms)
    got = np.asarray(K.run_range_function(func, block, params))[:n_series, :nsteps]
    want = np.stack([
        oracle.range_function(func, t, v, start, step_ms, nsteps, window_ms)
        for t, v in series
    ])
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want), err_msg=f"{func} {seed}")
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=5e-4, atol=5e-3, err_msg=f"{func} {seed}")
