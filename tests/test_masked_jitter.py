"""Missing-scrape (masked-grid) fast path vs the general kernel path.

A dropped scrape breaks the equal-count near-regular detection, which used
to cost the ~40x general-path penalty for an 0.1% hole rate. The masked
sidecar (ops/staging.MaskedGrid + ops/mxu_jitter.jitter_masked_kernel) must
be semantically indistinguishable from the general path on the same data.
Window-semantics contract: reference PeriodicSamplesMapper.scala:256.
"""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.mxu_jitter import JITTER_FUNCS
from filodb_tpu.ops.staging import stage_series

BASE = 1_600_000_000_000
INTERVAL = 10_000


def holey_series(n_series=6, n=300, seed=0, counter=False, jitter=0.05,
                 hole_frac=0.01):
    """Jittered nominal grid with a fraction of scrapes dropped per series
    (different slots per series)."""
    rng = np.random.default_rng(seed)
    nominal = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL
    out = []
    for i in range(n_series):
        dev = rng.uniform(-jitter, jitter, n) * INTERVAL
        ts = nominal + np.rint(dev).astype(np.int64)
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            k = n // 2 + i
            vals[k:] -= vals[k] - rng.uniform(0, 5)  # a reset per series
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        keep = np.ones(n, bool)
        # never drop the endpoints (keeps the grid anchor deterministic) and
        # drop different interior slots per series
        n_drop = max(1, int(hole_frac * n))
        drop = rng.choice(np.arange(1, n - 1), size=n_drop, replace=False)
        keep[drop] = False
        out.append((ts[keep], vals[keep]))
    return out


def run_path(func, series, counter, force_general, window_ms=300_000,
             diff=False):
    block = stage_series(
        series, BASE, counter_corrected=counter and not diff, diff_encode=diff
    )
    assert block.regular_ts is None and block.nominal_ts is None
    assert block.mgrid is not None, "staging must detect the holey grid"
    if force_general:
        block.mgrid = None
    params = K.RangeParams(BASE + 400_000, 60_000, 20, window_ms)
    return np.asarray(
        K.run_range_function(
            func, block, params, is_counter=counter or diff
        )
    )[: len(series), :20]


GAUGE_FUNCS = sorted(JITTER_FUNCS - {"rate", "increase", "irate"})
COUNTER_FUNCS = ["rate", "increase", "irate"]


@pytest.mark.parametrize("hole_frac", [0.005, 0.01, 0.05])
@pytest.mark.parametrize("func", GAUGE_FUNCS)
def test_masked_matches_general_gauge(func, hole_frac):
    series = holey_series(seed=3, hole_frac=hole_frac)
    fast = run_path(func, series, False, False)
    slow = run_path(func, series, False, True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow), err_msg=func)
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=2e-4, atol=1e-3, err_msg=func)


@pytest.mark.parametrize("hole_frac", [0.005, 0.01, 0.05])
@pytest.mark.parametrize("func", COUNTER_FUNCS)
def test_masked_matches_general_counter(func, hole_frac):
    series = holey_series(seed=4, counter=True, hole_frac=hole_frac)
    fast = run_path(func, series, True, False)
    slow = run_path(func, series, True, True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow), err_msg=func)
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=1e-3, atol=1e-3, err_msg=func)


def test_masked_idelta_diff_encoded():
    series = holey_series(seed=5, counter=True)
    fast = run_path("idelta", series, True, False, diff=True)
    slow = run_path("idelta", series, True, True, diff=True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow))
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=1e-6, atol=1e-9)


@pytest.mark.parametrize("func", ["rate", "sum_over_time", "min_over_time"])
def test_masked_gather_matmul_parity(func, monkeypatch):
    """The masked kernel's TPU matmul fetch path executed on CPU must equal
    the gather path bit-for-bit."""
    counter = func == "rate"
    series = holey_series(seed=6, counter=counter, hole_frac=0.01)
    outs = {}
    for fetch in ("gather", "matmul"):
        monkeypatch.setenv("FILODB_MXU_FETCH", fetch)
        outs[fetch] = run_path(func, series, counter, False)
    np.testing.assert_array_equal(outs["gather"], outs["matmul"], err_msg=func)


def test_window_with_only_holes_is_empty():
    """Every series missing the same run of scrapes: windows covering only
    the gap must be NaN (absent), exactly like the general path."""
    rng = np.random.default_rng(8)
    n = 200
    nominal = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL
    out = []
    for i in range(4):
        ts = nominal + np.rint(rng.uniform(-0.05, 0.05, n) * INTERVAL).astype(np.int64)
        vals = 50 + 20 * rng.standard_normal(n)
        keep = np.ones(n, bool)
        keep[100:104] = False  # shared 40s gap
        keep[10 + i] = False  # plus per-series holes
        out.append((ts[keep], vals[keep]))
    block = stage_series(out, BASE)
    assert block.mgrid is not None
    # 30s windows stepping across the gap
    params = K.RangeParams(BASE + 980_000, 10_000, 16, 30_000)
    fast = np.asarray(K.run_range_function("count_over_time", block, params))[:4, :16]
    gen = stage_series(out, BASE)
    gen.mgrid = None
    slow = np.asarray(K.run_range_function("count_over_time", gen, params))[:4, :16]
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow))
    assert np.isnan(fast).any(), "gap windows must be absent"
    m = ~np.isnan(slow)
    np.testing.assert_array_equal(fast[m], slow[m])


def test_no_mgrid_for_irregular_data():
    rng = np.random.default_rng(11)
    out = []
    for i in range(4):
        ts = BASE + np.sort(rng.choice(np.arange(1, 3_000_000), 200, replace=False))
        out.append((ts.astype(np.int64), rng.standard_normal(200)))
    block = stage_series(out, BASE)
    assert block.mgrid is None


def test_too_many_holes_falls_back():
    series = holey_series(seed=12, hole_frac=0.2)  # 20% > MAX_HOLE_FRAC
    block = stage_series(series, BASE)
    assert block.mgrid is None


def test_harmonize_masked_common_grid():
    from filodb_tpu.ops.staging import harmonize_masked

    blocks = []
    for s in range(4):
        series = holey_series(n_series=3, seed=20 + s, hole_frac=0.01)
        if s == 1:  # one shard starts a scrape later (anchor offset)
            series = [(ts[1:], v[1:]) for ts, v in series]
        blocks.append(stage_series(series, BASE, counter_corrected=True))
    assert all(b.mgrid is not None for b in blocks)
    assert harmonize_masked(blocks)
    g0 = blocks[0].mgrid
    for b in blocks[1:]:
        assert b.mgrid.n_valid == g0.n_valid
        assert b.mgrid.maxdev_ms == g0.maxdev_ms
        np.testing.assert_array_equal(
            np.asarray(b.mgrid.nominal_ts)[: g0.n_valid],
            np.asarray(g0.nominal_ts)[: g0.n_valid],
        )


def test_mesh_engine_masked_is_fused_single_dispatch():
    """Holey jittered counters through the MESH engine: the default
    aggregate path DELEGATES to the sharded fused superblock program,
    which now covers masked grids (doc/perf.md "Jitter-tolerant fused
    path") — the warm query must be exactly ONE multi-device dispatch,
    matching the host engine. The explicit fused opt-out
    (fused_aggregate=False) still exercises the legacy masked MXU mesh
    kernel, also parity-checked (it remains the pre-fusion escape hatch)."""
    import jax

    import filodb_tpu.parallel.exec as PE
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import Dataset, METRIC_TAG, PROM_COUNTER, shard_for
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh
    from filodb_tpu.testkit import kernel_dispatch_total

    rng = np.random.default_rng(33)
    n = 150
    nominal = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    for i in range(48):
        tags = {METRIC_TAG: "rq_total", "_ws_": "w", "_ns_": "n",
                "inst": f"h{i}"}
        shard = shard_for(tags, spread=3, num_shards=8)
        ts = nominal + np.rint(
            rng.uniform(-0.05, 0.05, n) * INTERVAL).astype(np.int64)
        vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
        keep = np.ones(n, bool)
        keep[rng.choice(np.arange(1, n - 1), 2, replace=False)] = False
        ms.shard("prometheus", shard).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts[keep], {"count": vals[keep]})
        )
    host = QueryEngine(ms, "prometheus")
    legacy = QueryEngine(ms, "prometheus",
                         PlannerParams(mesh=make_mesh(jax.devices()[:1]),
                                       fused_aggregate=False))
    fused_mesh = QueryEngine(ms, "prometheus",
                             PlannerParams(mesh=make_mesh(jax.devices()[:1])))
    start, end = (BASE + 400_000) / 1000, (BASE + 1_400_000) / 1000
    q = "sum(rate(rq_total[5m]))"

    ran = {"masked": 0}
    orig = PE.MeshAggregateExec._run_masked

    def spy(self, *a, **k):
        r = orig(self, *a, **k)
        if r is not None:
            ran["masked"] += 1
        return r

    PE.MeshAggregateExec._run_masked = spy
    try:
        rh = host.query_range(q, start, end, 60)
        rm = legacy.query_range(q, start, end, 60)
        rf = fused_mesh.query_range(q, start, end, 60)
    finally:
        PE.MeshAggregateExec._run_masked = orig
    assert ran["masked"] == 1, "legacy mesh opt-out keeps its masked path"
    # the fused delegate covers masked grids: warm query = ONE dispatch
    before = kernel_dispatch_total()
    fused_mesh.query_range(q, start, end, 60)
    assert kernel_dispatch_total() - before == 1, (
        "warm mesh query over a masked grid must be ONE fused dispatch"
    )
    snap = ms._superblock_cache.snapshot()
    assert any(e.get("grid") == "holes" for e in snap), snap
    vh = np.asarray(rh.grids[0].values_np())
    for rv in (rm, rf):
        vm = np.asarray(rv.grids[0].values_np())
        np.testing.assert_array_equal(np.isnan(vh), np.isnan(vm))
        ok = ~np.isnan(vh)
        np.testing.assert_allclose(vm[ok], vh[ok], rtol=2e-3)
