"""Vectorized posting-bitmap part-key index suite (make test-index).

The bitmap index (memstore/index.py PartKeyIndex + memstore/postings.py)
must return IDENTICAL part-id sets to the retained set-arithmetic oracle
(SetBasedPartKeyIndex) — exact equality, not tolerance — across randomized
filter combinations (eq / in / literal-alternation / prefix regex / general
regex / negative / empty-matcher), interval overlap, and limits; stay
equal under incremental add / update_end_time / remove; survive concurrent
lookup-vs-ingest; and keep the opt-in device tier's ledger drift at zero.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, equals, regex
from filodb_tpu.memstore.index import PartKeyIndex, SetBasedPartKeyIndex

pytestmark = pytest.mark.index

BIG = 2**62


def make_universe(rng, n=600, sparse_ids=False):
    """Random tag universe: a high-card label, medium labels, an optional
    label (missing-tag semantics), and random [start, end] intervals."""
    parts = []
    used = set()
    for i in range(n):
        if sparse_ids:
            pid = int(rng.integers(0, n * 37))
            while pid in used:
                pid = int(rng.integers(0, n * 37))
        else:
            pid = i
        used.add(pid)
        tags = {
            "_metric_": f"metric_{rng.integers(6)}",
            "host": f"h{rng.integers(80)}",
            "dc": ["us-east", "us-west", "eu", "ap"][rng.integers(4)],
        }
        if rng.random() < 0.4:
            tags["extra"] = f"e{rng.integers(4)}"
        if rng.random() < 0.1:
            tags["rare"] = f"r{rng.integers(2)}"
        start = int(rng.integers(0, 10_000))
        end = int(start + rng.integers(50, 15_000))
        parts.append((pid, tags, start, end))
    return parts


def build_pair(parts):
    bm, oracle = PartKeyIndex(), SetBasedPartKeyIndex()
    for pid, tags, s, e in parts:
        bm.add_partkey(pid, tags, s, e)
        oracle.add_partkey(pid, tags, s, e)
    return bm, oracle


def random_filter(rng) -> ColumnFilter:
    col = ["_metric_", "host", "dc", "extra", "rare", "absent"][rng.integers(6)]
    kind = rng.integers(9)
    if kind == 0:
        return ColumnFilter(col, "=", f"metric_{rng.integers(6)}"
                            if col == "_metric_" else f"h{rng.integers(80)}")
    if kind == 1:  # empty-matcher equality (matches missing tag)
        return ColumnFilter(col, "=", "")
    if kind == 2:
        return ColumnFilter(col, "in", (f"h{rng.integers(80)}",
                                        f"h{rng.integers(80)}", "us-east"))
    if kind == 3:  # literal alternation
        return ColumnFilter(col, "=~", "|".join(
            f"h{rng.integers(80)}" for _ in range(int(rng.integers(1, 4)))))
    if kind == 4:  # prefix regex
        return ColumnFilter(col, "=~", ["h1.*", "us.*", "metric_.*",
                                        "e.*", ""][rng.integers(5)])
    if kind == 5:  # general anchored regex
        return ColumnFilter(col, "=~", ["h[0-7].*", "h1[0-9]", "metric_[0-3]",
                                        "us-(east|west)", ".*st",
                                        ".+"][rng.integers(6)])
    if kind == 6:
        return ColumnFilter(col, "!=", ["h3", "us-east", "e1",
                                        ""][rng.integers(4)])
    if kind == 7:
        return ColumnFilter(col, "!~", ["h1.*", "us.*", ".+", "",
                                        "h[0-4].*"][rng.integers(5)])
    return ColumnFilter(col, "not in", ("h1", "us-east"))


def assert_same_lookup(bm, oracle, filters, s, e, limit=None):
    got = bm.part_ids_from_filters(filters, s, e, limit).tolist()
    want = oracle.part_ids_from_filters(filters, s, e, limit).tolist()
    assert got == want, (filters, s, e, limit)


class TestPropertyEquivalence:
    @pytest.mark.parametrize("seed", range(10))
    def test_random_filter_combos(self, seed):
        rng = np.random.default_rng(seed)
        parts = make_universe(rng, sparse_ids=seed % 3 == 0)
        bm, oracle = build_pair(parts)
        for _ in range(40):
            filters = [random_filter(rng)
                       for _ in range(int(rng.integers(1, 4)))]
            s = int(rng.integers(0, 20_000))
            e = int(s + rng.integers(0, 20_000))
            lim = int(rng.integers(1, 50)) if rng.random() < 0.3 else None
            assert_same_lookup(bm, oracle, filters, s, e, lim)
        # no-filter scan + full-range + label introspection ride along
        assert_same_lookup(bm, oracle, [], 0, BIG)
        assert bm.label_names([], 0, BIG) == oracle.label_names([], 0, BIG)
        f = [equals("_metric_", "metric_1")]
        assert bm.label_names(f, 0, BIG) == oracle.label_names(f, 0, BIG)
        for lbl in ("host", "extra", "absent"):
            assert (bm.label_values([], lbl, 0, BIG)
                    == oracle.label_values([], lbl, 0, BIG))
            assert (bm.label_values(f, lbl, 0, BIG)
                    == oracle.label_values(f, lbl, 0, BIG))
            assert bm.cardinality(lbl) == oracle.cardinality(lbl)

    def test_dense_promotion_stays_equal(self):
        """A value covering most of the universe promotes its container to
        packed words; results must not change."""
        bm, oracle = PartKeyIndex(), SetBasedPartKeyIndex()
        for pid in range(5000):
            tags = {"_ws_": "demo", "host": f"h{pid % 7}"}
            bm.add_partkey(pid, tags, 0, 100)
            oracle.add_partkey(pid, tags, 0, 100)
        ws = bm._labels["_ws_"].containers["demo"]
        ws.finalize(bm._nbits)
        assert ws.words is not None, "expected dense promotion"
        for filters in ([equals("_ws_", "demo")],
                        [equals("_ws_", "demo"), equals("host", "h3")],
                        [ColumnFilter("_ws_", "!=", "other")],
                        [ColumnFilter("host", "=~", "h[0-2]")]):
            assert_same_lookup(bm, oracle, filters, 0, BIG)
            assert_same_lookup(bm, oracle, filters, 0, BIG, limit=17)

    def test_mixed_width_dense_ops(self):
        """Two containers promoted dense at DIFFERENT universe capacities
        (bitmap widths differ) must still AND/OR/ANDNOT correctly — the
        algebra aligns to the widest operand."""
        bm, oracle = PartKeyIndex(), SetBasedPartKeyIndex()
        pid = 0
        for _ in range(3000):  # value A promotes at a small universe
            for idx in (bm, oracle):
                idx.add_partkey(pid, {"grp": "A", "host": f"h{pid % 5}"}, 0)
            pid += 1
        # force A's finalize (and dense promotion) at the SMALL capacity
        assert_same_lookup(bm, oracle, [equals("grp", "A")], 0, BIG)
        for _ in range(30000):  # universe grows ~10x; B promotes wider
            for idx in (bm, oracle):
                idx.add_partkey(pid, {"grp": "B", "host": f"h{pid % 5}"}, 0)
            pid += 1
        ca = bm._labels["grp"].containers["A"]
        cb = bm._labels["grp"].containers["B"]
        ca.finalize(bm._nbits)
        cb.finalize(bm._nbits)
        assert ca.words is not None and cb.words is not None
        assert len(ca.words) != len(cb.words)
        for filters in (
            [ColumnFilter("grp", "=~", "A|B")],        # dense OR dense
            [equals("grp", "A"), equals("grp", "B")],  # dense AND dense
            [ColumnFilter("grp", "!=", "A")],          # tagged ANDNOT dense
            [equals("grp", "B"), equals("host", "h2")],
        ):
            assert_same_lookup(bm, oracle, filters, 0, BIG)

    def test_missing_tag_semantics(self):
        """f.matches(None) rule: {k!=\"v\"}, {k=~\".*\"}, {k=\"\"} match
        series missing k entirely — one `all &~ tagged` bitmap op."""
        bm, oracle = build_pair([
            (0, {"a": "x"}, 0, 100),
            (1, {"a": "y", "b": "q"}, 0, 100),
            (2, {"b": "q"}, 0, 100),
        ])
        for f in (ColumnFilter("a", "!=", "x"),
                  ColumnFilter("a", "=~", ".*"),
                  ColumnFilter("a", "=~", "x*"),
                  ColumnFilter("a", "=", ""),
                  ColumnFilter("a", "!~", "x"),
                  ColumnFilter("a", "!~", ".+"),
                  ColumnFilter("c", "=~", ".*"),
                  ColumnFilter("c", "!=", "anything")):
            assert_same_lookup(bm, oracle, [f], 0, BIG)


class TestIncrementalParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_add_update_remove_script(self, seed):
        """Random interleaving of add_partkey / update_end_time / remove,
        equality re-checked after every mutation burst."""
        rng = np.random.default_rng(100 + seed)
        bm, oracle = PartKeyIndex(), SetBasedPartKeyIndex()
        live: list[int] = []
        next_pid = 0
        probes = [
            [equals("_metric_", "metric_2")],
            [regex("host", "h1.*")],
            [ColumnFilter("host", "!~", "h[0-3].*")],
            [ColumnFilter("extra", "=", "")],
            [equals("_metric_", "metric_1"), regex("dc", "us.*")],
        ]
        for _ in range(30):
            op = rng.random()
            if op < 0.55 or not live:
                for _ in range(int(rng.integers(1, 40))):
                    tags = {
                        "_metric_": f"metric_{rng.integers(4)}",
                        "host": f"h{rng.integers(30)}",
                        "dc": ["us-east", "us-west", "eu"][rng.integers(3)],
                    }
                    if rng.random() < 0.3:
                        tags["extra"] = f"e{rng.integers(3)}"
                    s = int(rng.integers(0, 5000))
                    bm.add_partkey(next_pid, tags, s)
                    oracle.add_partkey(next_pid, tags, s)
                    live.append(next_pid)
                    next_pid += 1
            elif op < 0.8:
                for pid in rng.choice(live, size=min(len(live), 10),
                                      replace=False):
                    end = int(rng.integers(1000, 9000))
                    bm.update_end_time(int(pid), end)
                    oracle.update_end_time(int(pid), end)
            else:
                drop = [int(p) for p in rng.choice(
                    live, size=min(len(live), int(rng.integers(1, 20))),
                    replace=False)]
                bm.remove(drop)
                oracle.remove(drop)
                live = [p for p in live if p not in set(drop)]
            for filters in probes:
                s = int(rng.integers(0, 8000))
                assert_same_lookup(bm, oracle, filters, s, s + 3000)
                assert_same_lookup(bm, oracle, filters, 0, BIG)
            assert len(bm) == len(oracle)
            # label introspection stays in lockstep through removals too
            assert bm.label_names([], 0, BIG) == oracle.label_names([], 0, BIG)
            assert (bm.label_values([], "extra", 0, BIG)
                    == oracle.label_values([], "extra", 0, BIG))

    def test_remove_then_readd_same_id(self):
        bm, oracle = build_pair([(7, {"a": "x", "b": "y"}, 0, 50)])
        for idx in (bm, oracle):
            idx.remove([7])
            idx.add_partkey(7, {"a": "z"}, 10, 60)
        assert_same_lookup(bm, oracle, [equals("a", "z")], 0, BIG)
        assert_same_lookup(bm, oracle, [equals("a", "x")], 0, BIG)
        assert_same_lookup(bm, oracle, [ColumnFilter("b", "=", "")], 0, BIG)


class TestConcurrentSoak:
    def test_lookup_vs_ingest(self):
        """Lookup threads hammer the index while an ingest thread keeps
        adding (and occasionally removing) parts: no exceptions, every
        result sorted-unique, and the final state equals the oracle."""
        bm = PartKeyIndex()
        oracle = SetBasedPartKeyIndex()
        stop = threading.Event()
        errors: list = []
        filters_pool = [
            [equals("_metric_", "metric_1")],
            [regex("host", "h2.*")],
            [ColumnFilter("host", "!~", "h[0-4].*")],
            [equals("_metric_", "metric_0"), regex("host", "h1|h2|h3")],
        ]

        def looker(k):
            i = 0
            try:
                while not stop.is_set():
                    f = filters_pool[(i + k) % len(filters_pool)]
                    out = bm.part_ids_from_filters(f, 0, BIG)
                    arr = out.tolist()
                    assert arr == sorted(set(arr))
                    bm.label_values([], "host", 0, BIG)
                    i += 1
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=looker, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        rng = np.random.default_rng(0)
        added = []
        try:
            for pid in range(4000):
                tags = {"_metric_": f"metric_{pid % 3}",
                        "host": f"h{rng.integers(50)}"}
                bm.add_partkey(pid, tags, 0)
                oracle.add_partkey(pid, tags, 0)
                added.append((pid, tags))
                if pid % 500 == 499:
                    drop = [p for p, _ in added[:20]]
                    bm.remove(drop)
                    oracle.remove(drop)
                    added = added[20:]
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:1]
        for f in filters_pool:
            assert_same_lookup(bm, oracle, f, 0, BIG)


class TestDeviceTierLedger:
    def _hot_pair(self):
        from filodb_tpu.memstore.index_device import DevicePostingsTier

        bm = PartKeyIndex()
        oracle = SetBasedPartKeyIndex()
        for pid in range(3000):
            tags = {"_ws_": "demo", "_ns_": f"ns{pid % 4}",
                    "host": f"h{pid % 100}"}
            bm.add_partkey(pid, tags, 0)
            oracle.add_partkey(pid, tags, 0)
        tier = DevicePostingsTier(bm, min_hits=2, name="test-tier")
        bm.device_tier = tier
        return bm, oracle, tier

    def _drift(self):
        from filodb_tpu.ledger import LEDGER

        slot = LEDGER.verify()["kinds"].get("index_postings")
        return slot["drift"] if slot else 0

    def test_device_intersection_matches_and_drift_zero(self):
        bm, oracle, tier = self._hot_pair()
        f = [equals("_ws_", "demo"), equals("_ns_", "ns1")]
        for _ in range(3):  # build traffic
            bm.part_ids_from_filters(f, 0, BIG)
        assert tier.maintain() > 0
        assert self._drift() == 0
        before = tier.stats["intersections"]
        assert_same_lookup(bm, oracle, f, 0, BIG)
        assert tier.stats["intersections"] > before, \
            "device path must actually resolve the staged selector"
        # interval + limit still vectorize on top of the device result
        assert_same_lookup(bm, oracle, f, 0, BIG, limit=5)

    def test_postings_change_invalidates_staged_copy(self):
        bm, oracle, tier = self._hot_pair()
        f = [equals("_ns_", "ns2")]
        for _ in range(3):
            bm.part_ids_from_filters(f, 0, BIG)
        assert tier.maintain() > 0
        # a new series under the staged label must force the host path and
        # drop the stale device copy — with zero ledger drift throughout
        bm.add_partkey(9000, {"_ws_": "demo", "_ns_": "ns2", "host": "hX"}, 0)
        oracle.add_partkey(9000, {"_ws_": "demo", "_ns_": "ns2",
                                  "host": "hX"}, 0)
        assert_same_lookup(bm, oracle, f, 0, BIG)
        assert self._drift() == 0
        assert tier.maintain() > 0  # restage picks the fresh postings
        assert_same_lookup(bm, oracle, f, 0, BIG)
        assert self._drift() == 0
        tier.clear()
        assert self._drift() == 0
        assert tier.ledger.bytes == 0

    def test_empty_value_equality_never_uses_device_path(self):
        """{k=\"\"} equality also matches series MISSING the tag — a staged
        posting bitmap alone cannot answer it, so the tier must neither
        count it as traffic nor resolve it, even if a bitmap for the empty
        value exists."""
        from filodb_tpu.memstore.index_device import DevicePostingsTier

        bm = PartKeyIndex()
        oracle = SetBasedPartKeyIndex()
        for pid in range(200):
            tags = {"m": "x"}
            if pid % 2:
                tags["a"] = ""  # explicitly tagged with the EMPTY value
            # even pids lack the tag entirely
            bm.add_partkey(pid, tags, 0)
            oracle.add_partkey(pid, tags, 0)
        tier = DevicePostingsTier(bm, min_hits=1, name="empty-val-tier")
        bm.device_tier = tier
        f = [equals("a", "")]
        for _ in range(5):
            assert_same_lookup(bm, oracle, f, 0, BIG)  # all 200 ids
        assert ("a", "") not in bm.traffic
        assert tier.maintain() == 0
        # belt and braces: force-stage the empty-value bitmap anyway — the
        # lookup must still refuse the device path and stay correct
        bm.traffic[("a", "")] = 100
        tier.maintain()
        before = tier.stats["intersections"]
        assert_same_lookup(bm, oracle, f, 0, BIG)
        assert tier.stats["intersections"] == before

    def test_shard_opt_in_wiring(self):
        from filodb_tpu.memstore.shard import StoreConfig, TimeSeriesShard

        sh = TimeSeriesShard("d", 0, StoreConfig(index_device_postings=True))
        assert sh.index.device_tier is not None
        st = sh.index_stats()
        assert st["device"] is not None
        sh2 = TimeSeriesShard("d", 1, StoreConfig())
        assert sh2.index.device_tier is None
