"""Property-style index fuzzing: random tag universes + random filter
combinations, both index backends vs brute-force filtering (model:
reference PartKeyIndexRawSpec exhaustive matcher cases)."""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.memstore.index import PartKeyIndex

try:
    from filodb_tpu.memstore.index_native import (
        NativePartKeyIndex,
        native_index_available,
    )

    IMPLS = [PartKeyIndex] + ([NativePartKeyIndex] if native_index_available() else [])
except Exception:  # pragma: no cover
    IMPLS = [PartKeyIndex]


def build_universe(rng, n=500):
    metrics = [f"metric_{i}" for i in range(8)]
    hosts = [f"host-{i}" for i in range(25)]
    dcs = ["us-east", "us-west", "eu", "ap"]
    parts = []
    for pid in range(n):
        tags = {
            "_metric_": metrics[rng.integers(len(metrics))],
            "host": hosts[rng.integers(len(hosts))],
            "dc": dcs[rng.integers(len(dcs))],
        }
        if rng.random() < 0.3:
            tags["extra"] = f"e{rng.integers(3)}"
        start = int(rng.integers(0, 10_000))
        end = int(start + rng.integers(100, 20_000))
        parts.append((pid, tags, start, end))
    return parts


def random_filters(rng):
    out = []
    for _ in range(rng.integers(1, 4)):
        col = ["_metric_", "host", "dc", "extra"][rng.integers(4)]
        op = ["=", "!=", "=~", "!~"][rng.integers(4)]
        if op in ("=", "!="):
            val = [f"metric_{rng.integers(8)}", f"host-{rng.integers(25)}",
                   "us-east", f"e{rng.integers(3)}"][rng.integers(4)]
        else:
            val = ["metric_[0-3]", "host-1.*", "us.*", "e1|e2", ""][rng.integers(5)]
        out.append(ColumnFilter(col, op, val))
    return out


def brute_force(parts, filters, start, end):
    out = []
    for pid, tags, s, e in parts:
        if s > end or e < start:
            continue
        if all(f.matches(tags.get(f.column)) for f in filters):
            out.append(pid)
    return sorted(out)


@pytest.mark.parametrize("impl", IMPLS, ids=lambda c: c.__name__)
@pytest.mark.parametrize("seed", range(8))
def test_random_filters_match_brute_force(impl, seed):
    rng = np.random.default_rng(seed)
    parts = build_universe(rng)
    idx = impl()
    for pid, tags, s, e in parts:
        idx.add_partkey(pid, tags, s, e)
    for _ in range(25):
        filters = random_filters(rng)
        start = int(rng.integers(0, 15_000))
        end = int(start + rng.integers(0, 15_000))
        got = sorted(idx.part_ids_from_filters(filters, start, end).tolist())
        want = brute_force(parts, filters, start, end)
        assert got == want, (filters, start, end)


@pytest.mark.parametrize("impl", IMPLS, ids=lambda c: c.__name__)
def test_removal_consistency(impl):
    rng = np.random.default_rng(99)
    parts = build_universe(rng, n=200)
    idx = impl()
    for pid, tags, s, e in parts:
        idx.add_partkey(pid, tags, s, e)
    removed = set(range(0, 200, 3))
    idx.remove(removed)
    kept = [p for p in parts if p[0] not in removed]
    for _ in range(10):
        filters = random_filters(rng)
        got = sorted(idx.part_ids_from_filters(filters, 0, 10**9).tolist())
        want = brute_force(kept, filters, 0, 10**9)
        assert got == want
