"""Seed bootstrap + membership (reference akka-bootstrapper:
ClusterSeedDiscovery whitelist flow + the /__members HTTP contract)."""

import json
import urllib.request

import pytest

from filodb_tpu.coordinator.bootstrap import (
    BootstrapError,
    MemberRegistry,
    SeedBootstrapper,
)

A, B, C = "http://a:9090", "http://b:9090", "http://c:9090"


class TestMemberRegistry:
    def test_self_always_member_never_pruned(self):
        r = MemberRegistry(A)
        r.prune(now=1e12)
        assert r.members() == [A]
        assert r.peers() == ()

    def test_learn_vs_touch_liveness(self):
        """Hearsay (learn) must not refresh a dead member's liveness —
        only direct contact (touch) does."""
        r = MemberRegistry(A, prune_after_s=60)
        r.touch([B], now=1000)
        r.learn([B], now=2000)  # hearsay about an already-known member
        assert r.prune(now=1070) == [B]  # still aged out from t=1000

    def test_learn_adds_unknown(self):
        r = MemberRegistry(A)
        assert r.learn([B, C, B], now=10) == [B, C]
        assert r.learn([B], now=20) == []
        assert r.peers() == (B, C)

    def test_snapshot_contract(self):
        r = MemberRegistry(A)
        r.touch([B], now=1)
        snap = r.snapshot()
        assert snap["self"] == A
        assert set(snap["members"]) == {A, B}

    def test_trailing_slash_normalized(self):
        r = MemberRegistry(A + "/")
        r.touch([B + "/"])
        assert r.members() == [A, B]


def _fake_cluster(members_by_url, ids=None):
    """fetch stub: POSTing {"url": u} to x/__members registers u with x and
    returns x's member list (the live /__members handler contract)."""

    def fetch(url, auth_token=None, data=None, **kw):
        base = url.removesuffix("/__members")
        if base not in members_by_url:
            raise ConnectionError(f"{base} down")
        if data and data.get("url"):
            members_by_url[base].add(data["url"])
        return {"self": base, "id": (ids or {}).get(base, f"id-{base}"),
                "members": sorted(members_by_url[base])}

    return fetch


class TestSeedBootstrapper:
    def test_join_existing_cluster(self):
        cluster = {A: {A, B}, B: {A, B}}
        changes = []
        reg = MemberRegistry(C)
        boot = SeedBootstrapper(reg, [A], fetch=_fake_cluster(cluster),
                                on_change=changes.append)
        members = boot.bootstrap()
        assert set(members) == {A, B, C}
        assert changes and set(changes[-1]) == {A, B}
        assert C in cluster[A]  # the join announced us to the seed

    def test_head_self_seeds_when_alone(self):
        reg = MemberRegistry(A)
        boot = SeedBootstrapper(reg, [A, B], fetch=_fake_cluster({}))
        assert boot.bootstrap(retries=2, backoff_s=0.01) == [A]

    def test_non_head_refuses_to_split_brain(self):
        reg = MemberRegistry(B)
        boot = SeedBootstrapper(reg, [A, B], fetch=_fake_cluster({}))
        with pytest.raises(BootstrapError):
            boot.bootstrap(retries=2, backoff_s=0.01)

    def test_gossip_propagates_joins(self):
        """A knows only seed B; C joins via B; A learns C on refresh."""
        cluster = {B: {B}}
        reg_a = MemberRegistry(A)
        boot_a = SeedBootstrapper(reg_a, [B], fetch=_fake_cluster(cluster))
        boot_a.bootstrap()
        assert reg_a.peers() == (B,)
        cluster[B].add(C)  # C announced itself to B meanwhile
        boot_a.refresh_once()
        assert set(reg_a.peers()) == {B, C}

    def test_self_alias_detected_and_excluded(self):
        """A node whose seed list names ITSELF under another hostname must
        not join itself as a peer (URL equality can't see it; node id can)."""
        alias = "http://hostA:9090"
        reg = MemberRegistry(A)  # self_url is the loopback form
        cluster = {alias: {alias}}
        boot = SeedBootstrapper(reg, [alias],
                                fetch=_fake_cluster(cluster, ids={alias: reg.node_id}))
        # the only seed is our own alias -> effectively alone -> self-seed
        assert boot.bootstrap(retries=2, backoff_s=0.01) == [A]
        assert reg.peers() == ()
        # hearsay mentioning the alias later must NOT re-add it
        assert reg.learn([alias]) == []
        reg.touch([alias])
        assert reg.peers() == ()

    def test_poll_uses_short_timeout(self):
        seen = {}

        def fetch(url, auth_token=None, data=None, timeout=None, **kw):
            seen["timeout"] = timeout
            return {"self": B, "id": "id-b", "members": [B]}

        reg = MemberRegistry(A)
        SeedBootstrapper(reg, [B], fetch=fetch, poll_timeout_s=5.0).bootstrap()
        assert seen["timeout"] == 5.0

    def test_refresh_repolls_seeds_after_failed_bootstrap(self):
        """A node isolated at startup (seed down, bootstrap failed) must
        rejoin when the seed comes back — the refresh loop re-polls the
        configured seeds, not just known members."""
        cluster = {}
        reg = MemberRegistry(B)
        boot = SeedBootstrapper(reg, [A], fetch=_fake_cluster(cluster))
        with pytest.raises(BootstrapError):
            boot.bootstrap(retries=1, backoff_s=0.01)
        assert reg.peers() == ()
        cluster[A] = {A}  # seed comes back up
        boot.refresh_once()
        assert reg.peers() == (A,)
        assert B in cluster[A]  # and we announced ourselves to it

    def test_refresh_prunes_dead_members(self):
        cluster = {B: {B}}
        reg = MemberRegistry(A, prune_after_s=0.0)  # immediate aging
        boot = SeedBootstrapper(reg, [B], fetch=_fake_cluster(cluster))
        boot.bootstrap()
        del cluster[B]  # B dies
        import time

        time.sleep(0.01)
        boot.refresh_once()
        assert reg.peers() == ()


class TestLiveSeedBootstrap:
    def test_two_servers_discover_each_other(self):
        """Server A self-seeds; B lists A as its seed. After B joins, BOTH
        planners scatter to each other — no static peer list anywhere."""
        from filodb_tpu.server import FiloServer

        a = b = None
        try:
            a = FiloServer({
                "dataset": "prometheus", "shards": 8,
                "distributed": {"owned_shards": [0, 1, 2, 3],
                                "seeds": ["placeholder"]},
            })
            # self-seed: A is the head (and only) seed — set after the port
            # is known since test ports are ephemeral
            pa = None
            a.seeds = ()
            pa = a.start(port=0)
            url_a = f"http://127.0.0.1:{pa}"
            from filodb_tpu.coordinator.bootstrap import MemberRegistry as MR
            from filodb_tpu.coordinator.bootstrap import SeedBootstrapper as SB

            a.registry = MR(url_a)

            def on_change_a(peers):
                a.engine.planner.params.peer_endpoints = peers

            a.bootstrapper = SB(a.registry, [url_a], on_change=on_change_a)
            a._http.RequestHandlerClass.members_hook = staticmethod(a.registry.snapshot)

            def on_join_a(url, node_id=None):
                if node_id and node_id == a.registry.node_id:
                    a.registry.mark_self_alias(url)
                    return
                new = a.registry.learn([url])
                a.registry.touch([url])
                if new:
                    on_change_a(a.registry.peers())

            a._http.RequestHandlerClass.join_hook = staticmethod(on_join_a)
            a.bootstrapper.bootstrap()  # alone: self-seeds

            b = FiloServer({
                "dataset": "prometheus", "shards": 8,
                "distributed": {"owned_shards": [4, 5, 6, 7],
                                "seeds": [url_a]},
            })
            pb = b.start(port=0)
            url_b = f"http://127.0.0.1:{pb}"
            b.advertise_url = url_b
            # b.start spawned the join thread with a default advertise URL of
            # 127.0.0.1:<port>, which IS reachable here — wait for the join
            import time

            for _ in range(100):
                if a.engine.planner.params.peer_endpoints and \
                        b.engine.planner.params.peer_endpoints:
                    break
                time.sleep(0.05)
            assert b.engine.planner.params.peer_endpoints == (url_a,)
            assert a.engine.planner.params.peer_endpoints  # learned B via join POST

            # the /__members contract over real HTTP
            with urllib.request.urlopen(f"{url_a}/__members", timeout=10) as r:
                snap = json.loads(r.read())["data"]
            assert url_a == snap["self"]
            assert len(snap["members"]) == 2
        finally:
            for srv in (a, b):
                if srv is not None:
                    srv.stop()
