"""Concurrent identical-query coalescing (coordinator.scheduler.SingleFlight)
— the dashboard fan-out path: N copies of the same panel query must cost one
plan+stage+kernel execution (reference: shared QueryScheduler pool,
QueryScheduler.scala:29-73)."""

import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.coordinator.scheduler import SingleFlight
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec.transformers import QueryError
from filodb_tpu.testkit import counter_batch

START = 1_600_000_000_000


class TestSingleFlight:
    def test_concurrent_callers_share_one_execution(self):
        sf = SingleFlight()
        calls = []
        gate = threading.Event()

        def slow():
            calls.append(1)
            gate.wait(5)
            return "answer"

        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(sf.run("k", slow, timeout_s=10))
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # everyone joined the flight
        gate.set()
        for t in threads:
            t.join()
        assert len(calls) == 1
        assert results == ["answer"] * 8

    def test_exception_propagates_to_followers(self):
        sf = SingleFlight()
        gate = threading.Event()

        def boom():
            gate.wait(5)
            raise QueryError("nope")

        errs = []

        def follow():
            try:
                sf.run("k", boom, timeout_s=10)
            except QueryError as e:
                errs.append(str(e))

        threads = [threading.Thread(target=follow) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        gate.set()
        for t in threads:
            t.join()
        assert errs == ["nope"] * 4

    def test_sequential_calls_never_share(self):
        sf = SingleFlight()
        calls = []
        sf.run("k", lambda: calls.append(1), timeout_s=5)
        sf.run("k", lambda: calls.append(1), timeout_s=5)
        assert len(calls) == 2

    def test_distinct_keys_run_independently(self):
        sf = SingleFlight()
        assert sf.run("a", lambda: 1, timeout_s=5) == 1
        assert sf.run("b", lambda: 2, timeout_s=5) == 2


@pytest.fixture(scope="module")
def engine():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed(
        "prometheus",
        counter_batch(n_series=32, n_samples=120, start_ms=START),
        spread=2,
    )
    return QueryEngine(ms, "prometheus", PlannerParams(deadline_s=120))


def test_engine_coalesces_identical_queries(engine, monkeypatch):
    import filodb_tpu.coordinator.planner as P

    executions = []
    orig = QueryEngine._query_range_uncoalesced

    def spy(self, *a, **k):
        executions.append(a)
        time.sleep(0.2)  # hold the flight open so followers join
        return orig(self, *a, **k)

    monkeypatch.setattr(QueryEngine, "_query_range_uncoalesced", spy)
    s, e = START / 1000 + 400, START / 1000 + 1100
    q = "sum(rate(http_requests_total[5m]))"
    engine.query_range(q, s, e, 60)  # warm (1 execution)
    results = []
    threads = [
        threading.Thread(
            target=lambda: results.append(engine.query_range(q, s, e, 60))
        )
        for _ in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 6
    v0 = results[0].grids[0].values_np()
    for r in results[1:]:
        np.testing.assert_array_equal(r.grids[0].values_np(), v0)
    # 1 warm + far fewer than 6 concurrent executions (usually 1)
    assert len(executions) - 1 <= 2


def test_engine_distinct_queries_not_coalesced(engine, monkeypatch):
    executions = []
    orig = QueryEngine._query_range_uncoalesced

    def spy(self, *a, **k):
        executions.append(a[0])
        return orig(self, *a, **k)

    monkeypatch.setattr(QueryEngine, "_query_range_uncoalesced", spy)
    s, e = START / 1000 + 400, START / 1000 + 1100
    engine.query_range("sum(rate(http_requests_total[5m]))", s, e, 60)
    engine.query_range("count(rate(http_requests_total[5m]))", s, e, 60)
    assert len(executions) == 2


def test_coalescing_can_be_disabled(monkeypatch):
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(2))
    eng = QueryEngine(ms, "prometheus",
                      PlannerParams(coalesce_identical=False, deadline_s=30))
    called = []
    monkeypatch.setattr(
        SingleFlight, "run",
        lambda self, *a, **k: called.append(1),
    )
    s, e = START / 1000 + 400, START / 1000 + 500
    eng.query_range("up", s, e, 60)
    assert not called
