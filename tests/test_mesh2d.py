"""2D mesh (series x time) execution: psum aggregation composed with the
ring halo — verified against the single-device pipeline on a 2x4 and 4x2
virtual mesh."""

import numpy as np
import pytest

import jax

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.staging import stage_series
from filodb_tpu.parallel import mesh2d as M2

BASE = 1_600_000_000_000


def make_blocks(n_blocks=2, series_per_block=5, n=400, seed=0, counter=True):
    rng = np.random.default_rng(seed)
    blocks, gids, all_series = [], [], []
    for b in range(n_blocks):
        series = []
        for i in range(series_per_block):
            ts = BASE + np.cumsum(rng.integers(5_000, 15_000, n)).astype(np.int64)
            if counter:
                vals = np.cumsum(rng.uniform(0, 10, n)) + 1e8
            else:
                vals = 50 + 20 * rng.standard_normal(n)
            series.append((ts, vals))
            all_series.append((ts, vals, i % 2))
        blocks.append(stage_series(series, BASE, counter_corrected=counter))
        gids.append((np.arange(series_per_block) % 2).astype(np.int32))
    return blocks, gids, all_series


PARAMS = K.RangeParams(BASE + 400_000, 30_000, 96, 300_000)


@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("op", ["sum", "avg", "count"])
def test_mesh2d_matches_oracle(shape, op):
    import oracle

    mesh = M2.make_mesh2d(*shape)
    blocks, gids, all_series = make_blocks()
    got = np.asarray(
        M2.run_mesh2d(mesh, "rate", op, blocks, gids, 2, PARAMS, is_counter=True)
    )
    rates = {}
    for ts, vals, g in all_series:
        r = oracle.range_function(
            "rate", ts, vals, PARAMS.start_ms, PARAMS.step_ms, PARAMS.num_steps,
            PARAMS.window_ms, is_counter=True)
        rates.setdefault(g, []).append(r)
    for g in (0, 1):
        rows = np.stack(rates[g])
        if op == "sum":
            want = np.nansum(rows, axis=0)
        elif op == "avg":
            want = np.nanmean(rows, axis=0)
        else:
            want = (~np.isnan(rows)).sum(axis=0).astype(float)
        np.testing.assert_allclose(got[g], want, rtol=2e-3, err_msg=f"{shape} {op} g{g}")


def test_mesh2d_gauge_sum():
    mesh = M2.make_mesh2d(2, 4)
    blocks, gids, all_series = make_blocks(counter=False, seed=5)
    got = np.asarray(
        M2.run_mesh2d(mesh, "sum_over_time", "sum", blocks, gids, 2, PARAMS)
    )
    import oracle

    sums = {}
    for ts, vals, g in all_series:
        r = oracle.range_function(
            "sum_over_time", ts, vals, PARAMS.start_ms, PARAMS.step_ms,
            PARAMS.num_steps, PARAMS.window_ms)
        sums.setdefault(g, []).append(r)
    for g in (0, 1):
        want = np.nansum(np.stack(sums[g]), axis=0)
        np.testing.assert_allclose(got[g], want, rtol=1e-3)


def test_mesh2d_through_engine():
    """Planner selects the 2D exec for a (shard x time) mesh and results
    match the host path."""
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.exec import Mesh2DAggregateExec
    from filodb_tpu.query.promql import query_range_to_logical_plan
    from filodb_tpu.testkit import counter_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus", counter_batch(n_series=24, n_samples=200, start_ms=BASE), spread=2)
    host = QueryEngine(ms, "prometheus")
    mesh2 = QueryEngine(ms, "prometheus", PlannerParams(mesh=M2.make_mesh2d(2, 4)))
    start_s, end_s = (BASE + 600_000) / 1000, (BASE + 1_800_000) / 1000
    q = "sum by (instance) (rate(http_requests_total[5m]))"
    plan = query_range_to_logical_plan(q, start_s, end_s, 60)
    ep = mesh2.planner.materialize(plan)
    assert isinstance(ep, Mesh2DAggregateExec)
    r2 = ep.execute(mesh2.context())
    r1 = host.query_range(q, start_s, end_s, 60)
    m1 = {tuple(sorted(l.items())): v for l, _, v in r1.all_series()}
    m2_ = {tuple(sorted(l.items())): v for l, _, v in r2.all_series()}
    assert m1.keys() == m2_.keys()
    for k in m1:
        np.testing.assert_allclose(m2_[k], m1[k], rtol=2e-3)
