"""Simulated consumer-group rebalance over the JSONL tail transport
(gateway/tail.py + IngestionPipeline): the Kafka-shaped handoff contract
(reference doc/ingestion.md:24,:87-97, KafkaIngestionStream.scala:26 manual
commits) — a shard revoked from one node and assigned to another must
resume from the committed offset with exactly-once net effect.

See doc/ingestion.md "Kafka-shaped transport semantics" for the mapping."""

import json

import numpy as np
import pytest

from filodb_tpu.core.schemas import Dataset
from filodb_tpu.gateway.stream import IngestionPipeline
from filodb_tpu.gateway.tail import JsonlTailStream
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.store.columnstore import LocalColumnStore
from filodb_tpu.store.flush import FlushCoordinator
from filodb_tpu.coordinator.planner import QueryEngine

BASE = 1_600_000_000_000


def _write_log(path, n_rows, n_series=4, start_i=0):
    with open(path, "a") as f:
        for i in range(start_i, start_i + n_rows):
            rec = {
                "metric": "cpu_usage",
                "tags": {"host": f"h{i % n_series}"},
                "ts_ms": BASE + (i // n_series) * 10_000,
                "value": float(i),
            }
            f.write(json.dumps(rec) + "\n")


def _totals(ms):
    sh = ms.shard("ds", 0)
    out = {}
    for pid in sh.lookup_partitions([], 0, 2**62):
        part = sh.partition(int(pid))
        ts, vals = part.samples_in_range(0, 2**62, "value")
        out[part.tags["host"]] = (len(ts), round(float(np.nansum(vals)), 3))
    return out


def _fresh(store_root=None):
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=50))
    ms.setup(Dataset("ds"), [0])
    return ms


def test_rebalance_resumes_from_committed_offset(tmp_path):
    """Node A consumes with periodic commits, 'dies' with an unflushed
    tail; node B takes the shard over and must equal the single-consumer
    oracle (no loss, no double count)."""
    log = tmp_path / "shard-0.jsonl"
    _write_log(log, 400)
    store = LocalColumnStore(str(tmp_path / "store"))

    # oracle: one consumer, no failure
    oracle = _fresh()
    IngestionPipeline(oracle, "ds", 0, JsonlTailStream(str(log))).run()
    want = _totals(oracle)

    # node A: commit every batch (batch_lines=64), then the partition is
    # revoked mid-log — simulate by consuming only a prefix file
    prefix = tmp_path / "prefix.jsonl"
    with open(log) as f:
        lines = f.readlines()
    with open(prefix, "w") as f:
        f.writelines(lines[:250])
    a = _fresh()
    fc = FlushCoordinator(a, store)
    IngestionPipeline(a, "ds", 0, JsonlTailStream(str(prefix), batch_lines=64),
                      flush_coordinator=fc, flush_every=1).run()
    # A ingested 250 rows but its LAST partial batch (rows past the final
    # commit) represents the unflushed tail a real crash would lose

    # rebalance: node B gets the shard, recovers from the store, replays
    # the FULL log from the committed offset
    b = _fresh()
    pipeline_b = IngestionPipeline(b, "ds", 0, JsonlTailStream(str(log)),
                                   flush_coordinator=FlushCoordinator(b, store))
    replayed = pipeline_b.recover_and_run(store)
    assert replayed > 0, "B must replay the uncommitted suffix"
    assert _totals(b) == want


def test_multi_generation_handoff(tmp_path):
    """A -> B -> C: each generation consumes a longer prefix, commits, and
    hands off; the final state equals the oracle."""
    log = tmp_path / "shard-0.jsonl"
    store = LocalColumnStore(str(tmp_path / "store"))
    _write_log(log, 600)
    oracle = _fresh()
    IngestionPipeline(oracle, "ds", 0, JsonlTailStream(str(log))).run()
    want = _totals(oracle)

    with open(log) as f:
        lines = f.readlines()
    node = None
    for gen, upto in enumerate((200, 450, 600)):
        prefix = tmp_path / f"gen{gen}.jsonl"
        with open(prefix, "w") as f:
            f.writelines(lines[:upto])
        node = _fresh()
        p = IngestionPipeline(node, "ds", 0,
                              JsonlTailStream(str(prefix), batch_lines=64),
                              flush_coordinator=FlushCoordinator(node, store),
                              flush_every=1)
        p.recover_and_run(store)
    assert _totals(node) == want


def test_handoff_preserves_query_results(tmp_path):
    """The contract a user sees: rate() over the handed-off shard equals
    the single-consumer run."""
    log = tmp_path / "shard-0.jsonl"
    store = LocalColumnStore(str(tmp_path / "store"))
    _write_log(log, 480)
    oracle = _fresh()
    IngestionPipeline(oracle, "ds", 0, JsonlTailStream(str(log))).run()

    with open(log) as f:
        lines = f.readlines()
    prefix = tmp_path / "prefix.jsonl"
    with open(prefix, "w") as f:
        f.writelines(lines[:300])
    a = _fresh()
    IngestionPipeline(a, "ds", 0, JsonlTailStream(str(prefix), batch_lines=50),
                      flush_coordinator=FlushCoordinator(a, store),
                      flush_every=1).run()
    b = _fresh()
    IngestionPipeline(b, "ds", 0, JsonlTailStream(str(log)),
                      flush_coordinator=FlushCoordinator(b, store)
                      ).recover_and_run(store)
    s, e = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = "sum(cpu_usage)"
    want = QueryEngine(oracle, "ds").query_range(q, s, e, 60)
    got = QueryEngine(b, "ds").query_range(q, s, e, 60)
    np.testing.assert_allclose(
        got.grids[0].values_np(), want.grids[0].values_np(),
        rtol=1e-6, equal_nan=True,
    )
