"""Result-plane tests (ISSUE 19): renderer byte-equality goldens across the
native / numpy / pure-python encode tiers, streamed-vs-buffered body
identity, the chunked mid-stream abort marker, the Arrow columnar peer
exchange (bit-equal round-trip + version-negotiation fallback to JSON),
and standing-query serve_range on the ordinary query_range path."""

import gzip
import http.client
import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu import native as N
from filodb_tpu.api import promjson as J
from filodb_tpu.query.rangevector import Grid, QueryResult, QueryStats, ScalarResult

BASE = 1_600_000_000_000

# exponent edges, subnormals, ties, specials — every formatting regime the
# repr grammar has: fixed with ".0", fixed fractional, scientific e±NN,
# shortest-round-trip torture values, signed zeros, non-finites
TORTURE = [
    0.0, -0.0, 1.0, -1.0, 42.0, -273.15, 0.1, 0.2, 0.3, 1 / 3,
    1e-5, 9.999e-5, 1e-4, 1.5e-5, 1e15, 1e16 - 2, 1e16, 1.1e16, 1e17,
    5e-324, 2.5e-323, 2.2250738585072014e-308, 1.7976931348623157e308,
    -1.7976931348623157e308, 9007199254740993.0, 2.0 ** 53, 2.0 ** 53 + 2,
    0.5, 2.0 ** -10, 123456789.123456789, 1.000000000000001,
    9.999999999999999e22, 123e-20, 7.038531e-26,
    float("nan"), float("inf"), float("-inf"),
]


def _fmt_oracle(v: float) -> str:
    if np.isnan(v):
        return "NaN"
    if np.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def _fragment_oracle(ts_s: np.ndarray, row: np.ndarray) -> bytes:
    """Pure-python fragment oracle: [[t,"v"],...] with NaN samples skipped
    — the golden byte format every encode tier must reproduce exactly."""
    parts = [
        f'[{J._ts3(float(t))},"{_fmt_oracle(float(v))}"]'
        for t, v in zip(ts_s, row) if not np.isnan(v)
    ]
    return ("[" + ",".join(parts) + "]").encode()


def _torture_matrix(dtype):
    rng = np.random.default_rng(3)
    rows = [np.array(TORTURE, dtype=np.float64)]
    rows.append(rng.standard_normal(len(TORTURE)) * 10.0 ** rng.integers(
        -20, 20, len(TORTURE)))
    rows.append(np.floor(rng.uniform(0, 1e9, len(TORTURE))))
    rows.append(np.full(len(TORTURE), np.nan))  # all-NaN row -> "[]"
    vals = np.stack(rows)
    if dtype == np.float32:
        with np.errstate(over="ignore"):  # huge doubles -> inf, intended
            vals = vals.astype(np.float32)
    return vals


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_render_rows_golden_all_tiers(dtype):
    """Byte-equality goldens: whatever encode tier serves (native when
    libfilodbrender is built, the vectorized numpy tier always), row
    fragments are byte-identical to the pure-python _fmt oracle."""
    vals = _torture_matrix(dtype)
    # f32 values widen to double exactly as python float(v) does
    wide = vals.astype(np.float64)
    ts = (BASE + np.arange(vals.shape[1]) * 60_123) / 1000.0
    expected = [_fragment_oracle(ts, wide[i]) for i in range(len(wide))]

    got = J.render_rows(ts, vals)
    assert [bytes(r) for r in got] == expected

    # numpy tier explicitly (native disabled)
    orig = N.render_matrix_rows
    N.render_matrix_rows = lambda t, v: None
    try:
        got_np = J.render_rows(ts, vals)
    finally:
        N.render_matrix_rows = orig
    assert [bytes(r) for r in got_np] == expected

    # per-row serving fragment (raw-series path) agrees too
    for i in range(len(wide)):
        assert J._values_fragment(ts, vals[i]) == expected[i]


def test_native_format_double_matches_repr():
    lib = N.render_lib()
    if lib is None:
        pytest.skip("libfilodbrender not built")
    rng = np.random.default_rng(11)
    cases = list(TORTURE)
    cases += list(rng.standard_normal(5000) * 10.0 ** rng.integers(-300, 300, 5000))
    cases += list(rng.standard_normal(5000).astype(np.float32).astype(np.float64))
    for v in cases:
        v = float(v)
        got = N.format_double(v)
        if np.isnan(v):
            assert got == "nan"
        elif np.isinf(v):
            assert got == ("inf" if v > 0 else "-inf")
        else:
            assert got == repr(v), f"{v!r}: native {got!r} != repr {repr(v)!r}"


def test_histogram_matrix_golden():
    """Histogram-kind grids: the le-expanded bucket rows render through the
    same tiers, byte-identical to the oracle."""
    rng = np.random.default_rng(5)
    les = np.array([0.1, 1.0, np.inf])
    hist = np.cumsum(rng.random((2, 4, 3)).astype(np.float32), axis=2)
    hist[0, 1, :] = np.nan
    g = Grid([{"_metric_": "lat", "i": "0"}, {"_metric_": "lat", "i": "1"}],
             BASE, 60_000, 4,
             np.zeros((2, 4), np.float32), hist=hist, les=les)
    res = QueryResult(grids=[g])
    body = b"".join(J.stream_matrix(res))
    out = json.loads(body)
    assert out["status"] == "success"
    ts = (BASE + np.arange(4) * 60_000) / 1000.0
    wide = hist.astype(np.float64)
    for s in out["data"]["result"]:
        le = s["metric"].get("le")
        if le is None:
            continue
        i = int(s["metric"]["i"])
        b = [0.1, 1.0, float("inf")].index(float(le))
        frag = json.dumps(s["values"], separators=(",", ":")).encode()
        assert frag == _fragment_oracle(ts, wide[i, :, b])


# -- streamed vs buffered ----------------------------------------------------


def _grid(n_series=8, num_steps=40, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    vals = rng.standard_normal((n_series, num_steps)).astype(dtype)
    vals[rng.random((n_series, num_steps)) < 0.1] = np.nan
    return Grid([{"_metric_": "m", "i": str(i)} for i in range(n_series)],
                BASE, 60_000, num_steps, vals)


def test_streamed_body_byte_identical_to_buffered():
    res = QueryResult(grids=[_grid(), _grid(seed=2, num_steps=17)],
                      warnings=[{"w": "x"}], partial=True)
    stats = {"seriesScanned": 16}
    buffered = b"".join(J.stream_matrix(res, stats, warnings=res.warnings,
                                        partial=True))
    phases: dict = {}
    streamed = b"".join(J.stream_matrix(res, stats, warnings=res.warnings,
                                        partial=True, block_rows=3,
                                        phases=phases))
    assert streamed == buffered
    assert phases["transfer"] >= 0.0


def test_http_streamed_equals_buffered_payload(api_server):
    srv, base, engine = api_server
    q = urllib.parse.quote("heap_usage0")
    url = (f"{base}/api/v1/query_range?query={q}"
           f"&start={(BASE + 600_000) / 1000}&end={(BASE + 3_000_000) / 1000}&step=60")
    handler = srv.RequestHandlerClass
    old = handler.STREAM_MIN_SAMPLES
    try:
        handler.STREAM_MIN_SAMPLES = 10 ** 9  # force buffered
        with urllib.request.urlopen(url) as r:
            buffered = json.loads(r.read())
            assert r.headers.get("Transfer-Encoding") != "chunked"
        handler.STREAM_MIN_SAMPLES = 1  # force streaming
        with urllib.request.urlopen(url) as r:
            body = r.read()
            if r.headers.get("Content-Encoding") == "gzip":
                body = gzip.decompress(body)
            assert r.headers.get("Transfer-Encoding") == "chunked"
            streamed = json.loads(body)
    finally:
        handler.STREAM_MIN_SAMPLES = old
    # stats carry per-execution timings; the payload must be identical
    buffered["data"].pop("stats", None)
    streamed["data"].pop("stats", None)
    assert streamed == buffered


def test_stream_abort_emits_error_marker(api_server):
    srv, base, engine = api_server
    from filodb_tpu.api import http as H
    from filodb_tpu.metrics import REGISTRY

    def count():
        total = 0.0
        with REGISTRY._lock:
            for (name, lbls), m in REGISTRY._metrics.items():
                if name == "filodb_http_responses" and dict(lbls).get(
                        "class") == "stream_abort":
                    total += m.value
        return total

    handler = srv.RequestHandlerClass
    old_min = handler.STREAM_MIN_SAMPLES
    orig = H.J.stream_matrix

    def exploding(*a, **k):
        gen = orig(*a, **k)
        yield next(gen)
        raise RuntimeError("device fell off mid-body")

    before = count()
    try:
        handler.STREAM_MIN_SAMPLES = 1
        H.J.stream_matrix = exploding
        q = urllib.parse.quote("heap_usage0")
        url = (f"{base}/api/v1/query_range?query={q}"
               f"&start={(BASE + 600_000) / 1000}"
               f"&end={(BASE + 3_000_000) / 1000}&step=60")
        with urllib.request.urlopen(url) as r:
            body = r.read()
        if body[:2] == b"\x1f\x8b":
            body = gzip.decompress(body)
    finally:
        H.J.stream_matrix = orig
        handler.STREAM_MIN_SAMPLES = old_min
    # the stream terminated CLEANLY (chunked terminator reached — read()
    # returned) with a trailing structured error marker, not a cut socket
    tail = body.rsplit(b"\n", 2)
    marker = json.loads(tail[-2])
    assert marker["status"] == "error"
    assert marker["errorType"] == "stream_aborted"
    assert "RuntimeError" in marker["error"]
    assert count() == before + 1


# -- Arrow columnar peer exchange -------------------------------------------


def test_arrow_envelope_full_round_trip():
    AE = pytest.importorskip("filodb_tpu.api.arrow_edge")
    g64 = _grid(seed=4, dtype=np.float64)
    les = np.array([0.5, np.inf])
    hist = np.random.default_rng(9).random((3, 6, 2)).astype(np.float32)
    gh = Grid([{"h": str(i)} for i in range(3)], BASE, 30_000, 6,
              np.zeros((3, 6), np.float32), hist=hist, les=les, stale=True)
    res = QueryResult(grids=[_grid(), g64, gh], warnings=[{"w": "lost"}],
                      partial=True)
    res.stats = QueryStats(series_scanned=7, kernel_ns=42, cache_hits=1)
    res.scalar = ScalarResult(BASE, 1000, 2, np.array([1.25, np.nan]))
    res.raw = [({"r": "a"}, np.array([1, 5], np.int64), np.array([2.5, np.nan])),
               ({"r": "b"}, np.array([9], np.int64), np.array([[1.0, 2.0]]))]
    res.trace = {"span": "root"}
    back = AE.ipc_to_result(AE.result_to_ipc(res))
    assert len(back.grids) == 3
    for a, b in zip(res.grids, back.grids):
        assert (a.labels, a.start_ms, a.step_ms, a.num_steps, a.stale) == (
            b.labels, b.start_ms, b.step_ms, b.num_steps, b.stale)
        va, vb = a.values_np(), b.values_np()
        assert va.dtype == vb.dtype  # f64 grids stay f64 on the wire
        assert va.tobytes() == vb.tobytes()  # bit-equal, not just close
    assert np.asarray(back.grids[2].hist).tobytes() == hist.tobytes()
    assert np.array_equal(np.asarray(back.grids[2].les), les)
    assert back.warnings == res.warnings and back.partial
    assert (back.stats.series_scanned, back.stats.kernel_ns,
            back.stats.cache_hits) == (7, 42, 1)
    assert back.trace == {"span": "root"}
    assert back.scalar.values[0] == 1.25 and np.isnan(back.scalar.values[1])
    assert len(back.raw) == 2
    for (la, ta, va), (lb, tb, vb) in zip(res.raw, back.raw):
        assert la == lb and np.array_equal(ta, tb)
        assert np.asarray(va, np.float64).tobytes() == vb.tobytes()
    # empty result round-trips
    assert AE.ipc_to_result(AE.result_to_ipc(QueryResult())).grids == []


def test_arrow_negotiation_and_json_fallback(api_server):
    AE = pytest.importorskip("filodb_tpu.api.arrow_edge")
    from filodb_tpu.coordinator import planners as P

    srv, base, engine = api_server
    q = urllib.parse.quote("heap_usage0")
    url = (f"{base}/api/v1/query_range?query={q}"
           f"&start={(BASE + 600_000) / 1000}&end={(BASE + 3_000_000) / 1000}&step=60")
    # peer hop: columnar by default
    out = P.fetch_result(url)
    assert isinstance(out, QueryResult)
    assert sum(g.n_series for g in out.grids) == 10
    # bit-equality vs the JSON decimal leg: repr round-trips exactly
    env = P.fetch_json(url, want_envelope=True)
    by_lbl = {}
    for g in out.grids:
        vals, times = g.values_np(), g.step_times_ms()
        t2i = {int(t): j for j, t in enumerate(times)}
        for i, lb in enumerate(g.labels):
            pub = {("__name__" if k == "_metric_" else k): v
                   for k, v in lb.items()}
            by_lbl[json.dumps(pub, sort_keys=True)] = (vals[i], t2i)
    checked = 0
    for s in env["data"]["result"]:
        row, t2i = by_lbl[json.dumps(s["metric"], sort_keys=True)]
        for t, v in s["values"]:
            assert np.float32(float(v)) == row[t2i[round(float(t) * 1000)]]
            checked += 1
    assert checked > 50
    # JSON stays the answer without the Accept header (user edge)
    with urllib.request.urlopen(url) as r:
        assert r.headers.get("Content-Type") == "application/json"
    # old-peer negotiation: a server without the columnar edge answers
    # JSON and fetch_result falls back to the envelope
    handler = srv.RequestHandlerClass
    try:
        handler.ARROW_EDGE = False
        out2 = P.fetch_result(url)
    finally:
        handler.ARROW_EDGE = True
    assert isinstance(out2, dict) and out2["status"] == "success"
    # peer_exchange=json config: this node stops advertising Arrow
    old = P.PEER_EXCHANGE
    try:
        P.PEER_EXCHANGE = "json"
        out3 = P.fetch_result(url)
    finally:
        P.PEER_EXCHANGE = old
    assert isinstance(out3, dict)


def test_remote_exec_leg_columnar_bit_equal(api_server):
    pytest.importorskip("filodb_tpu.api.arrow_edge")
    from filodb_tpu.coordinator import planners as P

    srv, base, engine = api_server

    class Ctx:
        allow_partial_results = False

        @staticmethod
        def remaining_deadline_s():
            return 30.0

    start_ms, end_ms = BASE + 600_000, BASE + 3_000_000
    plan = P.PromQlRemoteExec(base, "heap_usage0", start_ms, end_ms, 60_000)
    arrow_res = plan.do_execute(Ctx())
    old = P.PEER_EXCHANGE
    try:
        P.PEER_EXCHANGE = "json"
        json_res = P.PromQlRemoteExec(base, "heap_usage0", start_ms, end_ms,
                                      60_000).do_execute(Ctx())
    finally:
        P.PEER_EXCHANGE = old

    def flat(res):
        out = {}
        for g in res.grids:
            vals, times = g.values_np(), g.step_times_ms()
            for i, lb in enumerate(g.labels):
                row = {int(t): v for t, v in zip(times, vals[i])
                       if not np.isnan(v)}
                out[json.dumps(lb, sort_keys=True)] = row
        return out

    a, b = flat(arrow_res), flat(json_res)
    assert a.keys() == b.keys() and len(a) == 10
    for k in a:
        assert a[k].keys() == b[k].keys()
        for t in a[k]:
            assert np.float32(a[k][t]) == np.float32(b[k][t])


def test_client_columnar_matches_json(api_server):
    pytest.importorskip("filodb_tpu.api.arrow_edge")
    from filodb_tpu.client import FiloClient

    srv, base, engine = api_server
    start_s, end_s = (BASE + 600_000) / 1000, (BASE + 3_000_000) / 1000
    t1, s1 = FiloClient(base).query_range("heap_usage0", start_s, end_s, 60)
    t2, s2 = FiloClient(base, columnar=False).query_range(
        "heap_usage0", start_s, end_s, 60)
    assert np.array_equal(t1, t2) and len(s1) == len(s2) == 10
    key = lambda s: json.dumps(s["metric"], sort_keys=True)  # noqa: E731
    m1 = {key(s): s["values"] for s in s1}
    m2 = {key(s): s["values"] for s in s2}
    assert m1.keys() == m2.keys()
    for k in m1:
        a, b = m1[k], m2[k]
        mask = ~np.isnan(a)
        assert np.array_equal(mask, ~np.isnan(b))
        assert np.array_equal(a[mask], b[mask])


# -- standing serve ----------------------------------------------------------


def test_standing_serves_ordinary_query_range(api_server_standing):
    srv, base, engine, se, q, start_s, end_s, step_s = api_server_standing
    from filodb_tpu.obs.querylog import QUERY_LOG

    url = (f"{base}/api/v1/query_range?query={urllib.parse.quote(q)}"
           f"&start={start_s}&end={end_s}&step={step_s:g}")
    with urllib.request.urlopen(url) as r:
        out = json.loads(r.read())
    assert out["status"] == "success"
    assert out["data"]["stats"]["servedFrom"] == "standing"
    recs = [e for e in QUERY_LOG.entries(50)
            if e.get("path") == "standing:serve"]
    assert recs, "standing:serve never logged"
    # the served matrix is bit-equal to what the standing engine retains
    # (a fresh evaluation can differ by 1 ulp: incremental vs batch sums)
    direct = se.serve_range(q, start_s, end_s, step_s)
    fresh = engine.query_range(q, start_s, end_s, step_s)
    assert np.allclose(direct.grids[0].values_np(),
                       fresh.grids[0].values_np(), rtol=1e-5, equal_nan=True)
    want = {}
    for g in direct.grids:
        vals, times = g.values_np(), g.step_times_ms()
        for i, lb in enumerate(g.labels):
            pub = {("__name__" if k == "_metric_" else k): v
                   for k, v in lb.items()}
            want[json.dumps(pub, sort_keys=True)] = {
                int(t): np.float32(v) for t, v in zip(times, vals[i])
                if not np.isnan(v)}
    got = {}
    for s in out["data"]["result"]:
        got[json.dumps(s["metric"], sort_keys=True)] = {
            round(float(t) * 1000): np.float32(float(v))
            for t, v in s["values"]}
    assert got == want


# -- fixtures ----------------------------------------------------------------


@pytest.fixture(scope="module")
def api_server():
    from filodb_tpu.api.http import serve_background
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import machine_metrics

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus",
                     machine_metrics(n_series=10, n_samples=360, start_ms=BASE),
                     spread=2)
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine)
    yield srv, f"http://127.0.0.1:{port}", engine
    srv.shutdown()


@pytest.fixture(scope="module")
def api_server_standing():
    from filodb_tpu.api.http import serve_background
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.standing import StandingEngine
    from filodb_tpu.testkit import counter_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    n_samples = 360
    ms.ingest_routed("prometheus",
                     counter_batch(n_series=8, n_samples=n_samples, start_ms=BASE),
                     spread=2)
    engine = QueryEngine(ms, "prometheus")
    edge_ms = BASE + n_samples * 10_000
    se = StandingEngine(engine, {"default_span_ms": 3_600_000},
                        clock=lambda: (edge_ms + 5_000) / 1e3)
    q = "sum(rate(http_requests_total[5m]))"
    step_ms = 60_000
    sq = se.register(q, step_ms)
    se.refresh(sq)
    assert sq.retained is not None
    # a phase-aligned sub-window of the retained grid
    start_ms = sq.grid_start_ms + 5 * step_ms
    end_ms = sq.grid_start_ms + 25 * step_ms
    assert end_ms <= sq.grid_end_ms
    srv, port = serve_background(engine, standing=se)
    yield (srv, f"http://127.0.0.1:{port}", engine, se, q,
           start_ms / 1000, end_ms / 1000, step_ms / 1000)
    srv.shutdown()
