"""Bounded query scheduler (reference QueryScheduler.scala:29-73): shared
pool with a concurrency cap, fail-fast admission, and cooperative deadline
cancellation."""

import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.coordinator.scheduler import QueryRejected, QueryScheduler
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec.transformers import QueryError
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


class TestSchedulerUnit:
    def test_concurrency_bounded(self):
        sched = QueryScheduler(parallelism=3, max_queued=50)
        seen = []

        def job():
            seen.append(sched.in_flight)
            time.sleep(0.02)
            return 1

        threads = [
            threading.Thread(target=lambda: sched.run(job, deadline_s=10))
            for _ in range(30)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sched.peak_in_flight <= 3
        assert len(seen) == 30  # every job ran

    def test_rejects_when_saturated(self):
        sched = QueryScheduler(parallelism=1, max_queued=1)
        release = threading.Event()
        started = threading.Event()

        def slow():
            started.set()
            release.wait(5)

        t1 = threading.Thread(target=lambda: sched.run(slow, deadline_s=10))
        t1.start()
        started.wait(2)
        t2 = threading.Thread(target=lambda: sched.run(lambda: None, deadline_s=10))
        t2.start()  # occupies the single queue slot
        time.sleep(0.05)
        with pytest.raises(QueryRejected):
            sched.run(lambda: None, deadline_s=10)
        release.set()
        t1.join()
        t2.join()

    def test_deadline_abort_frees_slot(self):
        sched = QueryScheduler(parallelism=1, max_queued=0)
        release = threading.Event()

        def hang():
            release.wait(5)

        with pytest.raises(QueryError, match="deadline"):
            sched.run(hang, deadline_s=0.1)
        release.set()
        # worker finishes and frees the slot; next run succeeds
        time.sleep(0.2)
        assert sched.run(lambda: 42, deadline_s=5) == 42

    def test_cancel_of_queued_job_frees_slot(self):
        sched = QueryScheduler(parallelism=1, max_queued=2)
        release = threading.Event()
        threading.Thread(target=lambda: sched.run(lambda: release.wait(5), deadline_s=10)).start()
        time.sleep(0.05)
        # queued (never starts) then deadline-cancelled
        with pytest.raises(QueryError):
            sched.run(lambda: None, deadline_s=0.05)
        release.set()
        time.sleep(0.2)
        # both slots must be free again
        assert sched.run(lambda: 1, deadline_s=5) == 1
        assert sched.run(lambda: 2, deadline_s=5) == 2


class TestSchedulerEngine:
    def test_50_concurrent_queries_bounded_and_correct(self):
        """VERDICT done-criterion: 50 concurrent query_ranges, bounded
        in-flight execution, correct results."""
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0, 1])
        ms.ingest("ds", 0, machine_metrics(n_series=8, n_samples=120, start_ms=BASE))
        sched = QueryScheduler(parallelism=4, max_queued=60)
        eng = QueryEngine(ms, "ds", PlannerParams(scheduler=sched))
        start_s, end_s = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
        want = eng.query_range("avg(heap_usage0)", start_s, end_s, 60).grids[0].values_np().copy()
        results, errors = [], []

        def one():
            try:
                r = eng.query_range("avg(heap_usage0)", start_s, end_s, 60)
                results.append(r.grids[0].values_np())
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=one) for _ in range(50)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(results) == 50
        for r in results:
            np.testing.assert_allclose(r, want, rtol=1e-6, equal_nan=True)
        assert sched.peak_in_flight <= 4

    def test_deadline_aborts_through_engine(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=60, start_ms=BASE))
        sched = QueryScheduler(parallelism=1, max_queued=0)
        eng = QueryEngine(ms, "ds", PlannerParams(scheduler=sched, deadline_s=0.0))
        with pytest.raises(QueryError, match="deadline"):
            eng.query_range("avg(heap_usage0)", (BASE + 400_000) / 1000, (BASE + 500_000) / 1000, 60)
