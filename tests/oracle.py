"""Pure-numpy f64 oracle for PromQL range functions — deliberately written
per-series/per-window (the way Prometheus' promql/functions.go computes them)
so it shares no code with the vectorized TPU kernels it cross-checks."""

import numpy as np


def windows(ts, start, step, num_steps, window):
    """Yield (out_t, sample_indices) — window = (out_t - w, out_t]."""
    for j in range(num_steps):
        t = start + j * step
        sel = np.nonzero((ts > t - window) & (ts <= t))[0]
        yield t, sel


def correct_counter(vals):
    out = vals.astype(np.float64).copy()
    corr = 0.0
    for i in range(1, len(out)):
        if vals[i] < vals[i - 1]:
            corr += vals[i - 1]
        out[i] = vals[i] + corr
    return out


def extrapolated(ts, raw, corrected, sel, t, window, is_counter, as_rate):
    if len(sel) < 2:
        return np.nan
    tf, tl = ts[sel[0]], ts[sel[-1]]
    delta = corrected[sel[-1]] - corrected[sel[0]]
    range_start, range_end = (t - window) / 1e3, t / 1e3
    tf_s, tl_s = tf / 1e3, tl / 1e3
    sampled = tl_s - tf_s
    dur_start = tf_s - range_start
    dur_end = range_end - tl_s
    avg_dur = sampled / (len(sel) - 1)
    if is_counter and delta > 0 and raw[sel[0]] >= 0:
        dur_zero = sampled * (raw[sel[0]] / delta)
        if dur_zero < dur_start:
            dur_start = dur_zero
    thresh = avg_dur * 1.1
    if dur_start >= thresh:
        dur_start = avg_dur / 2
    if dur_end >= thresh:
        dur_end = avg_dur / 2
    factor = (sampled + dur_start + dur_end) / sampled
    res = delta * factor
    if as_rate:
        res /= window / 1e3
    return res


def range_function(func, ts, vals, start, step, num_steps, window,
                   is_counter=False, is_delta=False, args=()):
    """ts int64 ms, vals f64 (one series) -> [num_steps] f64 with NaN absents."""
    ts = np.asarray(ts)
    vals = np.asarray(vals, dtype=np.float64)
    keep = ~np.isnan(vals)
    ts, vals = ts[keep], vals[keep]
    corrected = correct_counter(vals) if (is_counter and not is_delta) else vals
    out = np.full(num_steps, np.nan)
    for j, (t, sel) in enumerate(windows(ts, start, step, num_steps, window)):
        n = len(sel)
        if n == 0:
            if func == "absent_over_time":
                out[j] = 1.0
            continue
        w = vals[sel]
        if func == "sum_over_time":
            out[j] = w.sum()
        elif func == "count_over_time":
            out[j] = n
        elif func == "avg_over_time":
            out[j] = w.mean()
        elif func == "min_over_time":
            out[j] = w.min()
        elif func == "max_over_time":
            out[j] = w.max()
        elif func in ("last", "last_over_time"):
            out[j] = w[-1]
        elif func == "first_over_time":
            out[j] = w[0]
        elif func == "present_over_time":
            out[j] = 1.0
        elif func == "stddev_over_time":
            out[j] = w.std()
        elif func == "stdvar_over_time":
            out[j] = w.var()
        elif func == "z_score":
            sd = w.std()
            out[j] = (w[-1] - w.mean()) / sd if sd > 0 else np.nan
        elif func == "changes":
            out[j] = int((w[1:] != w[:-1]).sum())
        elif func == "resets":
            out[j] = int((w[1:] < w[:-1]).sum())
        elif func == "quantile_over_time":
            out[j] = np.quantile(w, args[0])
        elif func == "median_absolute_deviation_over_time":
            med = np.quantile(w, 0.5)
            out[j] = np.quantile(np.abs(w - med), 0.5)
        elif func in ("rate", "increase"):
            if is_delta:
                s = w.sum()
                out[j] = s / (window / 1e3) if func == "rate" else s
            else:
                out[j] = extrapolated(ts, vals, corrected, sel, t, window,
                                      is_counter, as_rate=(func == "rate"))
        elif func == "delta":
            out[j] = extrapolated(ts, vals, vals, sel, t, window, False, False)
        elif func == "idelta":
            if n >= 2:
                out[j] = w[-1] - w[-2]
        elif func == "irate":
            if n >= 2:
                dv = w[-1] - w[-2]
                if is_counter and not is_delta and dv < 0:
                    dv = w[-1]
                out[j] = dv / ((ts[sel[-1]] - ts[sel[-2]]) / 1e3)
        elif func == "deriv" or func == "predict_linear":
            if n >= 2:
                tc = (ts[sel] - t) / 1e3
                A = np.vstack([tc, np.ones(n)]).T
                slope, intercept = np.linalg.lstsq(A, w, rcond=None)[0]
                out[j] = slope if func == "deriv" else intercept + slope * args[0]
        elif func == "double_exponential_smoothing":
            if n >= 2:
                sf, tf_ = args
                level, trend = w[0], w[1] - w[0]
                for i in range(1, n):
                    prev = level
                    level = sf * w[i] + (1 - sf) * (level + trend)
                    trend = tf_ * (level - prev) + (1 - tf_) * trend
                out[j] = level
        else:
            raise ValueError(func)
    return out
