"""Kernel & compile observatory (doc/observability.md "Kernel & compile
observatory"): the process-global executable registry, recompile-storm
detection, the querylog -> /debug/kernels join, compile-cache provenance
reconciliation, and the one-command attestation artifact.

Contracts pinned here:

- the warm canonical query with the observatory enabled (capture is
  always on) stays exactly ONE kernel dispatch and records ZERO new
  compiles, and its registry key is STABLE across warm dispatches;
- a shape-varying dispatch loop triggers a recompile storm whose
  annotation names the unstable key dimension;
- query-log records carry ``executable_key`` + ``compile_miss`` that join
  to the registry's /debug/kernels table (engine-level and over HTTP);
- standing-query refreshes publish querylog records under
  ``path=standing:delta|standing:full`` (the maintainer used to bypass
  the querylog entirely);
- compile-cache hit/miss counters split by tier reconcile with the
  registry's per-executable provenance (both fed from classify_dispatch);
- ``tools/attest.py`` on the CPU backend emits a schema-valid
  ATTEST json with floors evaluated and the fused path proven served.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.metrics import REGISTRY
from filodb_tpu.obs.kernels import KERNELS, executable_key
from filodb_tpu.obs.querylog import QUERY_LOG
from filodb_tpu.ops import aggregations as AGG
from filodb_tpu.testkit import counter_batch, kernel_dispatch_total

pytestmark = pytest.mark.observability

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE = 1_600_000_000_000
N_SAMPLES = 240
START_S = (BASE + 600_000) / 1000
END_S = (BASE + 1_800_000) / 1000
Q = "sum(rate(http_requests_total[5m]))"


def _make_engine(n_shards=4, n_series=16, **params):
    ms = TimeSeriesMemStore(StoreConfig())
    ms.setup(Dataset("ds"), list(range(n_shards)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=n_series, n_samples=N_SAMPLES,
                            start_ms=BASE),
        spread=3,
    )
    return ms, QueryEngine(ms, "ds", PlannerParams(**params))


def _counter_value(name: str, **labels) -> float:
    key = (name, tuple(sorted(labels.items())))
    with REGISTRY._lock:
        m = REGISTRY._metrics.get(key)
        return m.value if m is not None else 0.0


def _record_for(snap: dict, key: str) -> dict | None:
    for e in snap["executables"]:
        if e["key"] == key:
            return e
    return None


# ---------------------------------------------------------------------------
# executable registry


class TestExecutableRegistry:
    def test_warm_canonical_query_one_dispatch_zero_compiles_stable_key(self):
        _ms, eng = _make_engine()
        eng.query_range(Q, START_S, END_S, 60)  # stage + compile
        eng.query_range(Q, START_S, END_S, 60)  # warm
        rec = QUERY_LOG.entries(1)[0]
        assert rec["path"] == "fused"
        key = rec["executable_key"]
        assert key, "warm fused query must carry its executable key"
        before_snap = _record_for(KERNELS.snapshot(), key)
        assert before_snap is not None, "querylog key must be in the registry"
        before_disp = kernel_dispatch_total()

        eng.query_range(Q, START_S, END_S, 60)

        assert kernel_dispatch_total() - before_disp == 1
        rec2 = QUERY_LOG.entries(1)[0]
        # key STABLE across warm dispatches, and the warm launch did not
        # compile — the observatory must never perturb the steady state
        assert rec2["executable_key"] == key
        assert rec2["compile_miss"] is False
        after_snap = _record_for(KERNELS.snapshot(), key)
        assert after_snap["compiles"] == before_snap["compiles"], \
            "warm dispatch recorded a new compile"
        assert after_snap["dispatches"] == before_snap["dispatches"] + 1
        # key anatomy: every canonical dimension is present in order
        assert key.startswith("family=")
        for dim in ("variant=", "epilogue=", "shapes=", "mesh=", "batch="):
            assert f"|{dim}" in key

    def test_dispatch_metrics_and_provenance(self):
        _ms, eng = _make_engine(n_series=8)
        eng.query_range(Q, START_S, END_S, 60)
        eng.query_range(Q, START_S, END_S, 60)
        key = QUERY_LOG.entries(1)[0]["executable_key"]
        rec = _record_for(KERNELS.snapshot(), key)
        # warm dispatches classify as in-process compile-cache hits; the
        # per-family dispatch counter moved
        assert rec["cache"]["in_process"] >= 1
        fam = rec["family"]
        assert _counter_value("filodb_kernel_exec_dispatches",
                              family=fam) >= rec["dispatches"]

    def test_unknown_key_dimension_rejected(self):
        with pytest.raises(ValueError, match="unknown executable-key"):
            KERNELS.observe_dispatch("x", 0.001, compiled=False,
                                     parts={"bogus": "1"})

    def test_device_timing_opt_in(self):
        vals = np.ones((4, 3), np.float32)
        gids = np.zeros(4, np.int32)
        AGG.segment_aggregate("sum", vals, gids, 1)  # compile outside timing
        key = executable_key({"family": "segment_sum", "variant": "general",
                              "epilogue": "agg:sum", "shapes": "S4xJ3xG1"})
        before = _record_for(KERNELS.snapshot(), key)["device_total_ms"]
        KERNELS.configure(device_timing=True)
        try:
            AGG.segment_aggregate("sum", vals, gids, 1)
        finally:
            KERNELS.configure(device_timing=False)
        after = _record_for(KERNELS.snapshot(), key)
        assert after["device_total_ms"] > before
        assert after["dispatches"] >= 2

    def test_capacity_eviction_drops_stale_entries_not_the_new_one(self):
        from filodb_tpu.obs.kernels import ExecutableRegistry

        reg = ExecutableRegistry(max_entries=16)
        for i in range(16):
            reg.observe_dispatch(f"evict_fam{i}", 0.001,
                                 parts={"shapes": f"S{i}"})
        # a 17th family past capacity must displace a stale entry and
        # then accumulate normally — never self-evict on insert
        for _ in range(3):
            reg.observe_dispatch("evict_fresh", 0.001,
                                 parts={"shapes": "S99"})
        snap = reg.snapshot()
        assert len(snap["executables"]) == 16
        by_fam = {e["family"]: e for e in snap["executables"]}
        assert "evict_fresh" in by_fam, "new record was self-evicted"
        assert by_fam["evict_fresh"]["dispatches"] == 3
        assert "evict_fam0" not in by_fam  # the stale one paid

    def test_registered_jits_report_cache_sizes(self):
        jits = KERNELS.registered_jits()
        # the fused scalar wrappers registered at import and have compiled
        # at least once by now (the engine tests above dispatched them)
        assert "ops.aggregations._segment_aggregate_jit" in jits
        assert jits["ops.aggregations._segment_aggregate_jit"]["cache_size"] >= 1
        assert any(k.startswith("ops.kernels.") for k in jits)
        assert any(k.startswith("ops.hist_kernels.") for k in jits)


# ---------------------------------------------------------------------------
# recompile-storm detection


class TestRecompileStorm:
    def test_shape_varying_loop_triggers_storm_naming_dimension(self):
        fam = "segment_stdvar"
        # drop accounting state (compile rings included): the widened
        # window must not re-interpret compiles other suites paid
        KERNELS.clear()
        before = _counter_value("filodb_xla_recompile_storms", family=fam)
        KERNELS.configure(storm_threshold=3, storm_window_s=300.0)
        try:
            vals = np.ones((6, 4), np.float32)
            gids = np.zeros(6, np.int32)
            # 5 distinct static group counts -> 5 fresh lowerings of one
            # family inside the window: the shape-churn storm
            for g in (811, 821, 823, 827, 829):
                AGG.segment_aggregate("stdvar", vals, gids, g)
        finally:
            KERNELS.configure(storm_threshold=5, storm_window_s=60.0)
        storms = KERNELS.snapshot()["storms"]
        assert fam in storms, f"no storm recorded for {fam}: {storms}"
        assert storms[fam]["unstable_dims"] == ["shapes"], \
            "the storm annotation must name the churning key dimension"
        assert storms[fam]["compiles_in_window"] >= 4
        assert _counter_value("filodb_xla_recompile_storms",
                              family=fam) == before + 1, \
            "one storm event, not one count per compile past threshold"

    def test_stable_shapes_do_not_storm(self):
        fam = "segment_group"
        KERNELS.clear()  # isolate from other suites' segment_group compiles
        KERNELS.configure(storm_threshold=3, storm_window_s=300.0)
        try:
            vals = np.ones((5, 4), np.float32)
            gids = np.zeros(5, np.int32)
            for _ in range(8):  # one compile then warm: no churn
                AGG.segment_aggregate("group", vals, gids, 739)
        finally:
            KERNELS.configure(storm_threshold=5, storm_window_s=60.0)
        assert fam not in KERNELS.snapshot()["storms"]


# ---------------------------------------------------------------------------
# querylog join + HTTP surface


class TestDebugKernels:
    @pytest.fixture()
    def server(self):
        from filodb_tpu.api.http import serve_background

        _ms, eng = _make_engine()
        srv, port = serve_background(eng, port=0)
        yield eng, port
        srv.shutdown()

    def test_querylog_key_joins_debug_kernels_over_http(self, server):
        eng, port = server
        base = f"http://127.0.0.1:{port}"
        q = urllib.parse.urlencode({
            "query": Q, "start": START_S, "end": END_S, "step": 60,
        })
        for _ in range(2):
            with urllib.request.urlopen(f"{base}/api/v1/query_range?{q}") as r:
                assert json.loads(r.read())["status"] == "success"
        with urllib.request.urlopen(f"{base}/debug/querylog?limit=1") as r:
            rec = json.loads(r.read())["data"][0]
        assert rec["executable_key"]
        assert rec["compile_miss"] is False  # second call was warm
        with urllib.request.urlopen(f"{base}/debug/kernels") as r:
            kern = json.loads(r.read())["data"]
        keys = {e["key"] for e in kern["executables"]}
        assert rec["executable_key"] in keys, \
            "querylog record must join the /debug/kernels table by key"
        assert "storms" in kern and "config" in kern
        assert kern["jits"], "registered wrappers must be listed"
        # ?limit= pages the table
        with urllib.request.urlopen(f"{base}/debug/kernels?limit=1") as r:
            assert len(json.loads(r.read())["data"]["executables"]) == 1


# ---------------------------------------------------------------------------
# standing refreshes in the querylog (the maintainer used to bypass it)


class TestStandingQuerylog:
    def test_refresh_publishes_standing_path_records(self):
        from filodb_tpu.standing import StandingEngine

        base = int(time.time() * 1000) - 3_600_000
        ms = TimeSeriesMemStore(StoreConfig())
        ms.setup(Dataset("ds"), range(2))
        ms.ingest_routed(
            "ds", counter_batch(n_series=8, n_samples=300, start_ms=base),
            spread=1,
        )
        eng = QueryEngine(ms, "ds", PlannerParams())
        st = StandingEngine(eng, {"enabled": True})
        sq = st.register(Q, step_ms=60_000, span_ms=1_800_000)
        try:
            assert st.refresh(sq) is not None  # cold: full evaluation
            st.refresh(sq)  # nothing changed: retained (delta plane)
            recs = [e for e in QUERY_LOG.entries(8)
                    if e["path"].startswith("standing:")]
            assert len(recs) >= 2
            assert recs[0]["path"] == "standing:delta"  # retained serve
            assert recs[1]["path"] == "standing:full"
            assert recs[0]["id"] != recs[1]["id"], \
                "each refresh must ring its own record"
            assert recs[1]["executable_key"], \
                "the full refresh's fused dispatch must carry its key"
            assert all(r["status"] == "ok" for r in recs[:2])
            assert recs[1]["stats"]["kernel_ms"] >= 0
        finally:
            st.unregister(sq.qid)


# ---------------------------------------------------------------------------
# compile-cache provenance reconciliation (satellite: tiered counters)


class TestCompileCacheProvenance:
    def test_tiers_reconcile_with_registry_provenance(self):
        from filodb_tpu.ops import compile_cache as CC

        cache_dir = tempfile.mkdtemp(prefix="filodb-cc-")
        prev_dir = CC._enabled_dir
        assert CC.enable_compile_cache(cache_dir) == cache_dir
        try:
            h_ip0 = _counter_value("filodb_compile_cache_hits",
                                   tier="in_process")
            m_ip0 = _counter_value("filodb_compile_cache_misses",
                                   tier="in_process")
            m_p0 = _counter_value("filodb_compile_cache_misses",
                                  tier="persistent")
            vals = np.ones((3, 5), np.float32)
            gids = np.zeros(3, np.int32)
            AGG.segment_aggregate("min", vals, gids, 677)  # fresh trace
            AGG.segment_aggregate("min", vals, gids, 677)  # warm
            assert _counter_value("filodb_compile_cache_misses",
                                  tier="in_process") == m_ip0 + 1
            assert _counter_value("filodb_compile_cache_hits",
                                  tier="in_process") >= h_ip0 + 1
            # the fresh trace wrote a persistent entry (thresholds are
            # forced to zero) -> a persistent-tier miss, and the registry's
            # record carries the same classification + the entry bytes
            assert _counter_value("filodb_compile_cache_misses",
                                  tier="persistent") == m_p0 + 1
            key = executable_key({
                "family": "segment_min", "variant": "general",
                "epilogue": "agg:min", "shapes": "S3xJ5xG677",
            })
            rec = _record_for(KERNELS.snapshot(), key)
            assert rec["cache"]["fresh"] == 1
            assert rec["cache"]["in_process"] == 1
            assert rec["executable_bytes"] and rec["executable_bytes"] > 0
        finally:
            # restore the previous cache dir (enable is idempotent per dir)
            CC._enabled_dir = None
            if prev_dir:
                CC.enable_compile_cache(prev_dir)

    def test_dir_walk_memoized_on_mtime(self):
        from filodb_tpu.ops.compile_cache import _CompileCacheProbe

        d = tempfile.mkdtemp(prefix="filodb-cc2-")
        with open(os.path.join(d, "entry-a"), "wb") as f:
            f.write(b"x" * 100)
        probe = _CompileCacheProbe(d)
        probe.WALK_TTL_S = 0.0  # isolate the mtime memo from the TTL
        assert probe.walk_bytes() == 100
        walked_mtime = probe._mtime_ns
        # nothing changed: the memo serves without re-walking
        os.unlink(os.path.join(d, "entry-a"))
        os.rmdir(d)  # even a VANISHED dir serves the memo until mtime moves
        probe._mtime_ns = walked_mtime
        # re-create with different content + a bumped mtime -> re-walk
        os.makedirs(d)
        with open(os.path.join(d, "entry-b"), "wb") as f:
            f.write(b"x" * 250)
        os.utime(d, ns=(walked_mtime + 10**9, walked_mtime + 10**9))
        assert probe.walk_bytes() == 250


# ---------------------------------------------------------------------------
# attestation (make attest)


class TestAttestation:
    def test_attest_cpu_emits_schema_valid_artifact(self, tmp_path):
        floor_file = tmp_path / "floors.json"
        floor_file.write_text(json.dumps({"entries": [{
            "metric": "sum_rate_100k_series_range_query_p50",
            "series": 256, "runs": 1, "p50_ms_floor": 1e9, "env": {},
        }]}))
        out = tmp_path / "ATTEST_cpu.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "attest.py"),
             "--floor-file", str(floor_file), "--no-multichip",
             "--out", str(out)],
            capture_output=True, text=True, cwd=REPO, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(out.read_text())
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import attest

            assert attest.validate_attestation(doc) == []
        finally:
            sys.path.pop(0)
        assert doc["backend"] == "cpu"
        assert doc["verdict"] == "pass"
        # floors evaluated: the gate verdict and measurement are embedded
        fl = doc["floors"][0]
        assert fl["metric"] == "sum_rate_100k_series_range_query_p50"
        assert fl["ok"] is True and "OK" in fl["verdict"]
        assert fl["measurement"]["match"] is True
        # the kernel snapshot PROVES the fused path served the workload
        assert doc["kernels"]["proof"]["fused_path_served"] is True
        assert any("fused" in f for f in
                   doc["kernels"]["proof"]["fused_families_dispatched"])
        assert fl["kernels"]["totals"]["dispatches"] >= 1
        assert doc["platform"].get("devices"), "device inventory missing"
