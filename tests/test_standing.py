"""Standing-query engine (doc/operations.md "Standing queries & recording
rules"): delta-maintained dashboards with push fan-out and recording rules.

The load-bearing property: a standing query's delta-maintained ``[G, J]``
partials are BIT-EQUAL to a full re-evaluation of the same grid over the
same (aligned) superblock — across regular, jittered and holey scrape
grids, across live-edge appends riding the in-place superblock extension
path, across forced restages (``FILODB_SUPERBLOCK_EXTEND=0`` covered by
the ingest-chaos suite; here the extension path is live), and under
concurrent ingest. Plus the serving contract: a warm refresh with provably
disjoint ingest performs ZERO kernel dispatches, a live-edge refresh
dispatches exactly ONCE for only the touched step suffix, one refresh
materialization serves N concurrent SSE subscribers, promotion/demotion is
hysteretic over the scheduler's retained recurrence ring, and recording
rules write real queryable series back.
"""

import json
import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import (
    METRIC_TAG, PROM_COUNTER, Dataset, shard_for,
)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.standing import StandingEngine, SubscriptionHub, SubscriptionLimit
from filodb_tpu.testkit import counter_batch, kernel_dispatch_total

pytestmark = pytest.mark.standing

BASE = 1_600_000_000_000
INTERVAL = 10_000
N_SHARDS = 4
STEP_MS = 15_000
SPAN_MS = 1_200_000


def _series_data(metric, n_series, total, jitter=0.0, hole_frac=0.0, seed=7):
    """Full per-series (tags, ts, vals) counter arrays: callers ingest a
    prefix by time, then append later slices — values stay monotone so
    appends continue each series exactly like live scrapes."""
    rng = np.random.default_rng(seed)
    # half-interval phase shift, as in test_fused_jitter: keeps the grid
    # class deterministic against 5m-aligned staging boundaries
    nominal = (BASE + INTERVAL // 2
               + (1 + np.arange(total, dtype=np.int64)) * INTERVAL)
    out = []
    for i in range(n_series):
        tags = {METRIC_TAG: metric, "_ws_": "w", "_ns_": "n",
                "instance": f"h{i}", "job": f"j{i % 4}"}
        dev = (np.rint(rng.uniform(-jitter, jitter, total) * INTERVAL)
               .astype(np.int64) if jitter > 0 else 0)
        ts = nominal + dev
        vals = np.cumsum(rng.uniform(0, 10, total)) + 1e9
        keep = np.ones(total, bool)
        if hole_frac > 0:
            drop = rng.choice(np.arange(1, total - 1),
                              max(1, int(hole_frac * total)), replace=False)
            keep[drop] = False
        out.append((tags, ts[keep], vals[keep]))
    return out


def _ingest_window(ms, dataset, data, lo_ms, hi_ms):
    """Ingest every sample with lo_ms <= ts < hi_ms (one live batch)."""
    n = 0
    for tags, ts, vals in data:
        m = (ts >= lo_ms) & (ts < hi_ms)
        if not m.any():
            continue
        shard = shard_for(tags, spread=3, num_shards=N_SHARDS)
        n += ms.shard(dataset, shard).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts[m], {"count": vals[m]})
        )
    return n


def _fresh(metric="rq", n_series=24, total=260, jitter=0.0, hole_frac=0.0,
           seed=7, prefix=200):
    """(memstore, engine, data, edge_ms): prefix samples ingested, the rest
    held back for live appends."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    data = _series_data(metric, n_series, total, jitter, hole_frac, seed)
    edge = BASE + prefix * INTERVAL
    _ingest_window(ms, "ds", data, 0, edge)
    return ms, QueryEngine(ms, "ds"), data, edge


def _standing(engine, edge_ms, **cfg):
    cfg = {"default_span_ms": SPAN_MS, **cfg}
    return StandingEngine(engine, cfg, clock=lambda: (edge_ms + 5_000) / 1e3)


# -- registration & modes ----------------------------------------------------


def test_register_modes_and_unregister():
    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)
    sq = se.register("sum by (job) (rate(rq[5m]))", STEP_MS)
    assert sq.mode == "delta" and sq.mode_reason is None
    top = se.register("topk(3, rate(rq[5m]))", STEP_MS)
    assert top.mode == "full"
    assert top.mode_reason == "standing_nondecomposable"
    qt = se.register("quantile(0.9, rate(rq[5m]))", STEP_MS)
    assert qt.mode == "full"
    assert se.registry.get(sq.qid) is sq
    assert len(se.registry.list()) == 3
    se.unregister(sq.qid)
    assert se.registry.get(sq.qid) is None
    with pytest.raises(Exception):
        se.register("not a promql ((", STEP_MS)


def test_registry_bounded():
    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge, max_standing=2)
    se.register("sum(rate(rq[5m]))", STEP_MS)
    se.register("avg(rate(rq[5m]))", STEP_MS)
    with pytest.raises(ValueError):
        se.register("count(rate(rq[5m]))", STEP_MS)


# -- delta maintenance: bit-equality property --------------------------------


GRIDS = {
    "regular": dict(jitter=0.0, hole_frac=0.0),
    "jitter": dict(jitter=0.05, hole_frac=0.0),
    "holes": dict(jitter=0.05, hole_frac=0.01),
}

QUERIES = [
    "sum by (instance) (rate(rq[5m]))",
    "avg by (job) (increase(rq[5m]))",
    "count(sum_over_time(rq[2m]))",
]


@pytest.mark.parametrize("grid", list(GRIDS))
@pytest.mark.parametrize("q", QUERIES)
def test_delta_biteq_vs_full_reevaluation(grid, q):
    """THE acceptance property: across live-edge append rounds, the delta
    path's spliced partials are bit-equal to a forced full re-evaluation
    of the same grid (same aligned superblock), for every grid class."""
    ms, eng, data, edge = _fresh(seed=11, **GRIDS[grid])
    se = _standing(eng, edge)
    sq = se.register(q, STEP_MS)
    twin = se.register(q, STEP_MS)
    se.refresh(sq)
    for rnd in range(3):
        lo, hi = edge + rnd * 50_000, edge + (rnd + 1) * 50_000
        assert _ingest_window(ms, "ds", data, lo, hi) > 0
        se.clock = lambda e=hi: (e + 5_000) / 1e3
        se.refresh(sq)
        se.refresh(twin, force_full=True)
        assert sq.grid_start_ms == twin.grid_start_ms
        assert sq.labels == twin.labels
        assert sq.retained.tobytes() == twin.retained.tobytes(), (
            f"{grid} {q} round {rnd}: delta partials diverge from full "
            f"re-evaluation"
        )
    assert sq.stats["delta"] >= 1, "the delta path never ran"
    assert sq.stats["steps_retained"] > 0


def test_delta_refresh_is_suffix_only_single_dispatch():
    """A live-edge append refresh re-dispatches exactly ONCE, computing
    only the touched step suffix — no full re-dispatch (the acceptance
    criterion's 'runs the delta path')."""
    ms, eng, data, edge = _fresh()
    se = _standing(eng, edge)
    sq = se.register("sum by (instance) (rate(rq[5m]))", STEP_MS)
    se.refresh(sq)
    # priming round: if the aligned staging range happens to roll right
    # here (it rolls once per align_ms of wall time), pay the reset now
    _ingest_window(ms, "ds", data, edge, edge + 30_000)
    se.clock = lambda: (edge + 35_000) / 1e3
    se.refresh(sq)
    J = sq.num_steps()
    computed0 = sq.stats["steps_computed"]
    _ingest_window(ms, "ds", data, edge + 30_000, edge + 60_000)
    se.clock = lambda: (edge + 65_000) / 1e3
    before = kernel_dispatch_total()
    se.refresh(sq)
    assert kernel_dispatch_total() - before == 1, (
        "delta refresh must be exactly ONE kernel dispatch"
    )
    delta_steps = sq.stats["steps_computed"] - computed0
    assert 0 < delta_steps < J / 2, (
        f"delta refresh computed {delta_steps} of {J} steps — not a suffix"
    )
    assert sq.stats["delta"] >= 1


def test_disjoint_ingest_serves_retained_zero_dispatch():
    """Nothing new in range → the refresh serves retained partials with
    ZERO kernel dispatches, and — since the content is byte-identical —
    skips the render/publish too (no redundant fan-out per wake)."""
    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)
    sq = se.register("sum by (instance) (rate(rq[5m]))", STEP_MS)
    first = se.refresh(sq)
    assert first is not None
    before = kernel_dispatch_total()
    renders0 = sq.stats["renders"]
    payload = se.refresh(sq)
    assert payload is None  # unchanged content: nothing re-rendered/pushed
    assert sq.last_payload == first  # subscribers' snapshot frame intact
    assert kernel_dispatch_total() - before == 0
    assert sq.stats["retained"] == 1
    assert sq.stats["renders"] == renders0


def test_concurrent_extension_soak():
    """Refreshes racing live ingest: no errors, every refresh serves a
    well-formed grid, and the quiesced final state is bit-equal to a full
    re-evaluation."""
    ms, eng, data, edge = _fresh(total=300, prefix=200)
    se = _standing(eng, edge)
    q = "sum by (job) (rate(rq[5m]))"
    sq = se.register(q, STEP_MS)
    twin = se.register(q, STEP_MS)
    se.refresh(sq)
    stop = threading.Event()
    state = {"hi": edge}

    def ingester():
        hi = edge
        while not stop.is_set() and hi < edge + 90_000:
            _ingest_window(ms, "ds", data, hi, hi + 10_000)
            hi += 10_000
            state["hi"] = hi
            time.sleep(0.01)

    t = threading.Thread(target=ingester)
    t.start()
    try:
        for _ in range(12):
            se.clock = lambda e=state["hi"]: (e + 5_000) / 1e3
            se.refresh(sq)
            assert sq.last_error is None, sq.last_error
            assert sq.retained.shape[1] == sq.num_steps()
            time.sleep(0.005)
    finally:
        stop.set()
        t.join()
    se.clock = lambda e=state["hi"]: (e + 5_000) / 1e3
    se.refresh(sq)
    se.refresh(twin, force_full=True)
    assert sq.labels == twin.labels
    assert sq.retained.tobytes() == twin.retained.tobytes()
    assert sq.stats["errors"] == 0


def test_new_series_resets_cleanly():
    """A NEW series appearing (full-clear effect) resets the retained
    state instead of splicing a mismatched group axis."""
    ms, eng, data, edge = _fresh(n_series=12)
    se = _standing(eng, edge)
    sq = se.register("sum by (instance) (rate(rq[5m]))", STEP_MS)
    se.refresh(sq)
    g0 = len(sq.labels)
    extra = _series_data("rq", 16, 260, seed=99)[12:]  # 4 unseen series
    _ingest_window(ms, "ds", extra, 0, edge + 40_000)
    se.clock = lambda: (edge + 45_000) / 1e3
    se.refresh(sq)
    assert sq.stats["reset"] >= 2  # first refresh + the new-series reset
    assert len(sq.labels) > g0
    twin = se.register("sum by (instance) (rate(rq[5m]))", STEP_MS)
    se.refresh(twin, force_full=True)
    assert sq.retained.tobytes() == twin.retained.tobytes()


# -- nondecomposable demotion ------------------------------------------------


def _fallback_count(reason):
    from filodb_tpu.metrics import REGISTRY

    return REGISTRY.counter("filodb_fused_fallback", reason=reason).value


def test_nondecomposable_full_refresh_counted():
    """topk standing queries demote cleanly: refreshes run the full
    re-dispatch, counted in the fused-fallback taxonomy, and still serve
    correct pushed payloads."""
    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)
    sq = se.register("topk(3, rate(rq[5m]))", STEP_MS)
    before = _fallback_count("standing_nondecomposable")
    payload = se.refresh(sq)
    assert payload is not None
    assert _fallback_count("standing_nondecomposable") == before + 1
    body = json.loads(payload)
    assert body["resultType"] == "matrix"
    assert body["result"], "topk standing refresh returned no rows"
    assert sq.stats["full"] == 1 and sq.stats["delta"] == 0


# -- promotion / demotion over the scheduler's recurrence ring ---------------


def test_key_ring_retained_across_batch_close():
    """The satellite fix: per-key recurrence survives batch-group close —
    repeated queries accumulate in the scheduler's ring instead of
    vanishing with each closed window."""
    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)  # injects a scheduler with the ring
    q = "sum by (instance) (rate(rq[5m]))"
    for _ in range(4):
        eng.query_range(q, (edge - SPAN_MS) / 1e3, edge / 1e3, STEP_MS / 1e3)
    ring = se.scheduler.key_ring
    assert len(ring) >= 1
    entries = ring.entries()
    (key, e) = next((k, v) for k, v in entries
                    if (v.get("desc") or {}).get("promql") == q)
    assert e["count"] == 4
    assert e["desc"]["dataset"] == "ds"
    assert e["desc"]["step_ms"] == STEP_MS
    snap = se.scheduler.snapshot()
    assert snap["standing_keys"] >= 1


def test_observe_key_without_trace_root():
    """Direct exec.execute (no engine trace root → no promql) must still
    observe safely: the fallback key normalizes by/without to hashable
    tuples instead of crashing the dispatch path."""
    from filodb_tpu.query.promql import query_range_to_logical_plan

    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)
    plan = query_range_to_logical_plan(
        "sum by (job) (rate(rq[5m]))", (edge - SPAN_MS) / 1e3, edge / 1e3, 15
    )
    ex = eng.planner.materialize(plan)
    res = ex.execute(eng.context())
    assert res.grids
    assert len(se.scheduler.key_ring) >= 1
    # promql-less keys never promote (nothing to re-register from)
    assert se.promote_tick() == 0


def test_key_ring_bounded():
    from filodb_tpu.query.scheduler import KeyStatsRing

    ring = KeyStatsRing(max_entries=8)
    for i in range(50):
        ring.observe(("k", i))
    assert len(ring) == 8
    # LRU: the most recently observed keys survive
    kept = {k for k, _ in ring.entries()}
    assert ("k", 49) in kept and ("k", 0) not in kept


def test_promotion_hysteresis():
    """A bursting live-edge key promotes; demotion needs long idle AND no
    subscribers; nondecomposable keys are remembered, never flapped on."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    now_ms = int(time.time() * 1000)
    ms.ingest_routed(
        "ds", counter_batch(n_series=12, n_samples=120,
                            start_ms=now_ms - 1_200_000), spread=3,
    )
    eng = QueryEngine(ms, "ds")
    se = StandingEngine(eng, {
        "promote_min_count": 3, "promote_window_s": 300.0,
        "demote_idle_s": 600.0, "default_span_ms": 600_000,
    })
    q = "sum by (instance) (rate(http_requests_total[5m]))"
    for _ in range(3):
        eng.query_range(q, (now_ms - 600_000) / 1e3, now_ms / 1e3, 15)
    assert se.promote_tick() == 1
    sqs = se.registry.list()
    assert len(sqs) == 1 and sqs[0].source == "promoted"
    assert sqs[0].promql == q and sqs[0].mode == "delta"
    assert se.promote_tick() == 0  # already registered: no re-promotion
    # nondecomposable keys are declined and remembered
    qt = "topk(2, rate(http_requests_total[5m]))"
    for _ in range(3):
        eng.query_range(qt, (now_ms - 600_000) / 1e3, now_ms / 1e3, 15)
    assert se.promote_tick() == 0
    reasons = {d["reason"] for d in se.registry.snapshot()["demoted"]}
    assert "standing_nondecomposable" in reasons
    # demotion: not before the idle bound...
    assert se.demote_tick(time.time() + 60) == 0
    # ...not while a subscriber holds the query...
    sub = se.hub.subscribe(sqs[0].qid)
    assert se.demote_tick(time.time() + 10_000) == 0
    se.hub.unsubscribe(sub)
    # ...then idle + unsubscribed demotes, and the key is remembered
    assert se.demote_tick(time.time() + 10_000) == 1
    assert not se.registry.list()
    assert se.registry.demoted_reason(sqs[0].key) == "idle"
    # hysteresis: the demoted key does not immediately re-promote
    assert se.promote_tick() == 0


def test_historical_scan_never_promotes():
    _ms, eng, _data, edge = _fresh()  # data far in the past vs wall clock
    se = _standing(eng, edge, promote_min_count=2)
    q = "sum(rate(rq[5m]))"
    for _ in range(3):
        eng.query_range(q, (edge - SPAN_MS) / 1e3, edge / 1e3, 15)
    assert se.promote_tick() == 0  # end lags wall clock by years


# -- shard effect intervals (the classification feed) ------------------------


def test_ingest_effects_interval_since():
    from filodb_tpu.memstore.shard import TimeSeriesShard

    sh = TimeSeriesShard("ds", 0)
    data = _series_data("m", 2, 40)
    for tags, ts, vals in data:
        sh.ingest_series(SeriesBatch(PROM_COUNTER, tags, ts[:20],
                                     {"count": vals[:20]}))
    v0 = sh.version
    assert sh.ingest_effects_interval_since(v0, 0, 2**62) == (None, None, None)
    tags, ts, vals = data[0]
    sh.ingest_series(SeriesBatch(PROM_COUNTER, tags, ts[20:25],
                                 {"count": vals[20:25]}))
    reason, lo, hi = sh.ingest_effects_interval_since(v0, 0, 2**62)
    assert reason == "overlap"
    assert lo <= int(ts[20]) and hi == int(ts[24])
    # disjoint probe range: proves untouched
    assert sh.ingest_effects_interval_since(
        v0, 0, int(ts[19]) - 600_000
    ) == (None, None, None)
    # a NEW series is a full clear
    v1 = sh.version
    sh.ingest_series(SeriesBatch(
        PROM_COUNTER, {METRIC_TAG: "m", "instance": "new"},
        ts[:5] + 1, {"count": vals[:5]},
    ))
    assert sh.ingest_effects_interval_since(v1, 0, 2**62)[0] == "full_clear"


def test_append_listener_fires_outside_lock():
    from filodb_tpu.memstore.shard import TimeSeriesShard

    sh = TimeSeriesShard("ds", 0)
    seen = []

    def cb(dataset, shard, lo, hi, full):
        # re-entering shard APIs must not deadlock (fired outside the lock)
        sh.ingest_effects_since(0, 0, 1)
        seen.append((dataset, shard, lo, hi, full))

    sh.add_append_listener(cb)
    tags, ts, vals = _series_data("m", 1, 10)[0]
    sh.ingest_series(SeriesBatch(PROM_COUNTER, tags, ts, {"count": vals}))
    assert len(seen) == 1
    assert seen[0][0] == "ds" and seen[0][4] is True  # new series = full
    sh.remove_append_listener(cb)
    sh.ingest_series(SeriesBatch(PROM_COUNTER, tags, ts + 200_000,
                                 {"count": vals + 1}))
    assert len(seen) == 1


# -- subscription hub --------------------------------------------------------


def test_hub_limit_and_newest_wins():
    hub = SubscriptionHub(max_subscribers=2, queue_depth=2)
    a = hub.subscribe("q1")
    _b = hub.subscribe("q1")
    with pytest.raises(SubscriptionLimit):
        hub.subscribe("q1")
    for i in range(4):
        hub.publish("q1", b"payload-%d" % i)
    # bounded queue keeps the NEWEST frames
    got = [a.get(timeout=1), a.get(timeout=1)]
    assert got == [b"payload-2", b"payload-3"]
    hub.close("q1")
    assert hub.total() == 0


# -- push fan-out over live SSE ----------------------------------------------


def _sse_events(resp, n, timeout_s=15.0):
    """Read n SSE data events from an open response."""
    out = []
    deadline = time.time() + timeout_s
    buf = b""
    while len(out) < n and time.time() < deadline:
        line = resp.fp.readline()
        if not line:
            break
        line = line.rstrip(b"\r\n")
        if line.startswith(b"data: "):
            buf += line[6:]
        elif not line and buf:
            out.append(json.loads(buf))
            buf = b""
    return out


def test_sse_fanout_one_materialization():
    """N >= 8 concurrent SSE subscribers each receive the SAME refresh
    payload from ONE materialization (renders == refreshes, not
    refreshes x N); past max_subscribers the subscription sheds 429."""
    import http.client

    from filodb_tpu.api.http import serve_background

    ms, eng, data, edge = _fresh()
    se = _standing(eng, edge, max_subscribers=8)
    sq = se.register("sum by (job) (rate(rq[5m]))", STEP_MS)
    se.refresh(sq)
    srv, port = serve_background(eng, standing=se)
    conns = []
    try:
        for _ in range(8):
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            c.request("GET", f"/api/v1/standing/subscribe?id={sq.qid}")
            r = c.getresponse()
            assert r.status == 200
            assert r.getheader("Content-Type") == "text/event-stream"
            conns.append((c, r))
        # the 9th subscriber sheds with the overload contract
        c9 = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c9.request("GET", f"/api/v1/standing/subscribe?id={sq.qid}")
        r9 = c9.getresponse()
        assert r9.status == 429
        assert r9.getheader("Retry-After")
        c9.close()
        # one refresh -> one render -> every subscriber gets the same frame
        renders0 = sq.stats["renders"]
        _ingest_window(ms, "ds", data, edge, edge + 20_000)
        se.clock = lambda: (edge + 25_000) / 1e3
        se.refresh(sq)
        assert sq.stats["renders"] == renders0 + 1
        frames = []
        for _c, r in conns:
            evs = _sse_events(r, 2)  # initial snapshot + the refresh
            assert len(evs) == 2
            frames.append(evs[1])
        assert all(f == frames[0] for f in frames)
        assert frames[0]["seq"] == sq.seq
        assert frames[0]["result"]
    finally:
        for c, _r in conns:
            c.close()
        srv.shutdown()


def test_standing_http_api_and_debug():
    import urllib.request

    from filodb_tpu.api.http import serve_background

    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)
    srv, port = serve_background(eng, standing=se)
    url = f"http://127.0.0.1:{port}"
    try:
        req = urllib.request.Request(
            f"{url}/api/v1/standing/register",
            data=json.dumps({"query": "sum(rate(rq[5m]))",
                             "step": "15s", "range": "20m"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["status"] == "success"
        qid = out["data"]["id"]
        assert out["data"]["mode"] == "delta"
        with urllib.request.urlopen(f"{url}/api/v1/standing", timeout=30) as r:
            lst = json.loads(r.read())["data"]
        assert lst["count"] == 1
        with urllib.request.urlopen(f"{url}/debug/standing", timeout=30) as r:
            dbg = json.loads(r.read())["data"]
        assert dbg["count"] == 1 and "key_ring" in dbg
        req = urllib.request.Request(
            f"{url}/api/v1/standing/unregister",
            data=json.dumps({"id": qid}).encode(), method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["status"] == "success"
        with urllib.request.urlopen(f"{url}/api/v1/standing", timeout=30) as r:
            assert json.loads(r.read())["data"]["count"] == 0
    finally:
        srv.shutdown()


# -- recording rules ---------------------------------------------------------


def test_recording_rule_writes_back_series():
    """A recording rule's refresh writes its newest closed steps back as a
    real series, queryable through the standard path, and the rule lists
    at /api/v1/rules."""
    ms, eng, data, edge = _fresh()
    se = _standing(eng, edge)
    sq = se.register(
        "sum by (job) (rate(rq[5m]))", STEP_MS, span_ms=4 * STEP_MS,
        source="rule", rule_name="job_rq_rate5m", eval_interval_s=15.0,
    )
    se.refresh(sq)
    end1 = sq.grid_end_ms
    # the written sample equals the rule's own newest partial
    res = eng.query_range("job_rq_rate5m", end1 / 1e3, end1 / 1e3, 15)
    rows = {tuple(sorted(g_lbl.items())): v
            for g in res.grids
            for g_lbl, v in zip(g.labels, g.values_np())}
    assert rows, "rule wrote no series"
    mine = {tuple(sorted({**dict(l), METRIC_TAG: "job_rq_rate5m"}.items())):
            sq.retained[i, -1] for i, l in enumerate(sq.labels)}
    for k, v in rows.items():
        assert k in mine
        assert np.float32(v[-1]) == np.float32(mine[k])
    # a later eval appends the NEW closed steps only (no rewrite storm)
    _ingest_window(ms, "ds", data, edge, edge + 30_000)
    se.clock = lambda: (edge + 35_000) / 1e3
    se.refresh(sq)
    assert sq.last_rule_write_ms == sq.grid_end_ms > end1
    payload = se.rules_payload()
    assert payload["groups"][0]["rules"][0]["name"] == "job_rq_rate5m"
    assert payload["groups"][0]["rules"][0]["type"] == "recording"


# -- lifecycle: append-wake loop ---------------------------------------------


def test_append_wake_refreshes_via_loop():
    """start() subscribes to shard appends: a live ingest wakes the loop
    and the registered query refreshes without anyone polling."""
    ms, eng, data, edge = _fresh()
    se = _standing(eng, edge, refresh_debounce_ms=0, tick_s=0.05)
    sq = se.register("sum(rate(rq[5m]))", STEP_MS)
    se.refresh(sq)
    seq0 = sq.seq
    se.start()
    try:
        _ingest_window(ms, "ds", data, edge, edge + 20_000)
        deadline = time.time() + 10
        while sq.seq == seq0 and time.time() < deadline:
            time.sleep(0.02)
        assert sq.seq > seq0, "append never woke the maintainer loop"
    finally:
        se.stop()


# -- resource attribution ----------------------------------------------------


def test_ledger_and_tenant_attribution():
    from filodb_tpu.ledger import LEDGER

    _ms, eng, _data, edge = _fresh()
    se = _standing(eng, edge)
    sq = se.register(
        'sum by (instance) (rate(rq{_ws_="w",_ns_="n"}[5m]))', STEP_MS
    )
    se.refresh(sq)
    assert sq.ws == "w" and sq.ns == "n"
    verify = LEDGER.verify()
    kind = verify["kinds"].get("standing_state")
    assert kind is not None
    assert kind["ledger"] == kind["actual"] > 0
    assert kind["drift"] == 0
    se.unregister(sq.qid)
    verify = LEDGER.verify()
    # this registry's account drained (other tests' registries may live)
    acct = [a for a in verify["accounts"]
            if a["kind"] == "standing_state" and a["actual"] == 0]
    assert acct
    assert all(a["drift"] == 0 if "drift" in a else True for a in acct)
    # a refresh racing the unregister bails instead of re-growing state
    # the ledger already credited back (the drift hazard)
    assert se.refresh(sq) is None
    assert sq.retained is None
    this = [a for a in verify["accounts"]
            if a["kind"] == "standing_state" and a["actual"] == 0]
    assert all(a["bytes"] == a["actual"] for a in this)
