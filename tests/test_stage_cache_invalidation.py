"""Selective staging-cache invalidation (memstore/shard.py
_invalidate_stage_range): live scrapes landing BEYOND a cached query range
must not evict it (the dashboard-historical-panel-under-ingest cost), while
anything that can change the cached block's content must."""

import numpy as np
import pytest

import filodb_tpu.ops.staging as ST
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import Dataset, GAUGE, METRIC_TAG
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


@pytest.fixture
def setup():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=6, n_samples=200, start_ms=BASE))
    engine = QueryEngine(ms, "ds")
    return ms, engine, ms.shard("ds", 0)


def _stage_calls(monkeypatch):
    calls = []
    orig = ST.stage_from_shard

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ST, "stage_from_shard", spy)
    return calls


def _append(ms, tags, ts, vals):
    ms.shard("ds", 0).ingest_series(
        SeriesBatch(GAUGE, dict(tags), np.asarray(ts, np.int64),
                    {"value": np.asarray(vals, np.float64)})
    )


def _existing_tags(shard):
    pid = int(shard.lookup_partitions([], 0, 2**62)[0])
    return dict(shard.partition(pid).tags)


def _new_series_tags(tags):
    return dict(tags, instance="brand-new-host")


def test_append_beyond_range_keeps_cache(setup, monkeypatch):
    ms, engine, shard = setup
    s, e = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = "sum(heap_usage0)"
    want = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    calls = _stage_calls(monkeypatch)
    tags = _existing_tags(shard)
    # new samples strictly beyond the staged range (raw end = e)
    _append(ms, tags, [BASE + 5_000_000], [1.0])
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls == [], "historical range must stay cached"
    np.testing.assert_array_equal(got, want)


def test_append_into_range_invalidates(setup, monkeypatch):
    """A live-edge panel (range end past the newest sample) must re-stage
    when a fresh scrape lands inside its range."""
    ms, engine, shard = setup
    s, e = (BASE + 400_000) / 1000, (BASE + 2_500_000) / 1000
    q = "sum(heap_usage0)"
    before = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    calls = _stage_calls(monkeypatch)
    tags = _existing_tags(shard)
    # newer than the series head (not out-of-order) AND inside [s, e]
    _append(ms, tags, [BASE + 2_200_000], [1000.0])
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls, "in-range sample must re-stage"
    assert not np.array_equal(got, before), "new in-range data must show up"


def test_new_series_invalidates_even_beyond_range(setup, monkeypatch):
    ms, engine, shard = setup
    s, e = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = "sum(heap_usage0)"
    engine.query_range(q, s, e, 60)
    calls = _stage_calls(monkeypatch)
    # a NEW series could match any cached filter set: conservative clear
    _append(ms, _new_series_tags(_existing_tags(shard)),
            [BASE + 5_000_000], [1.0])
    engine.query_range(q, s, e, 60)
    assert calls, "new series must invalidate"


def _counter_setup():
    from filodb_tpu.core.schemas import PROM_COUNTER

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    rng = np.random.default_rng(7)
    n = 200
    ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
    for i in range(6):
        vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
        k = 120 + i
        vals[k:] -= vals[k] - rng.uniform(0, 5)  # one reset per series
        tags = {"_metric_": "rq_total", "_ws_": "w", "_ns_": "n",
                "inst": f"h{i}"}
        ms.shard("ds", 0).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts, {"count": vals})
        )
    return ms, QueryEngine(ms, "ds"), ms.shard("ds", 0), ts


@pytest.mark.parametrize("q,with_reset", [
    ("sum(rate(rq_total[5m]))", False),
    ("sum(rate(rq_total[5m]))", True),
    ("sum(increase(rq_total[5m]))", False),
])
def test_live_edge_append_repair_matches_fresh_engine(monkeypatch, q, with_reset):
    """Repeated live-edge queries with samples appended between them must
    take the incremental append-repair path (no full re-stage) and stay
    equal to a fresh engine over identical data — counters included (exact
    f64 correction continuation, resets in the appended region too)."""
    from filodb_tpu.core.schemas import PROM_COUNTER

    ms, engine, shard, ts0 = _counter_setup()
    s = (BASE + 400_000) / 1000
    n0 = len(ts0)
    rng = np.random.default_rng(9)
    restages = []
    orig = ST.stage_from_shard

    def spy(*a, **k):
        restages.append(1)
        return orig(*a, **k)

    appended = {i: ([], []) for i in range(6)}
    for step in range(4):
        # live-edge range: covers everything ingested so far + the future
        e = (BASE + (n0 + 40) * 10_000) / 1000
        engine.query_range(q, s, e, 60)
        if step == 0:
            monkeypatch.setattr(ST, "stage_from_shard", spy)
        # append 2 fresh scrapes per series (same shared grid)
        new_ts = BASE + (n0 + 1 + 2 * step + np.arange(2, dtype=np.int64)) * 10_000
        for i in range(6):
            base_v = 1e6 * (step + 1)
            v = np.array([base_v, 1.0 if (with_reset and i == 0 and step == 2)
                          else base_v + rng.uniform(1, 5)])
            tags = {"_metric_": "rq_total", "_ws_": "w", "_ns_": "n",
                    "inst": f"h{i}"}
            ms.shard("ds", 0).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, new_ts, {"count": v})
            )
            appended[i][0].extend(new_ts.tolist())
            appended[i][1].extend(v.tolist())
    e = (BASE + (n0 + 40) * 10_000) / 1000
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert restages == [], "live-edge appends must repair, never re-stage"

    # oracle: a FRESH memstore with the identical final data
    ms2 = TimeSeriesMemStore()
    ms2.setup(Dataset("ds"), [0])
    rng2 = np.random.default_rng(7)
    n = 200
    ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
    for i in range(6):
        vals = np.cumsum(rng2.uniform(0, 10, n)) + 1e9
        k = 120 + i
        vals[k:] -= vals[k] - rng2.uniform(0, 5)
        full_ts = np.concatenate([ts, np.array(appended[i][0], np.int64)])
        full_v = np.concatenate([vals, np.array(appended[i][1])])
        tags = {"_metric_": "rq_total", "_ws_": "w", "_ns_": "n",
                "inst": f"h{i}"}
        ms2.shard("ds", 0).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, full_ts, {"count": full_v})
        )
    want = QueryEngine(ms2, "ds").query_range(q, s, e, 60).grids[0].values_np()
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    ok = ~np.isnan(want)
    np.testing.assert_allclose(got[ok], want[ok], rtol=1e-4)


def test_append_repair_gauge_exact(monkeypatch):
    """Gauge (raw-mode) repair must be bit-exact vs a fresh stage."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=100, start_ms=BASE))
    engine = QueryEngine(ms, "ds")
    s, e = (BASE + 400_000) / 1000, (BASE + 1_500_000) / 1000
    q = "sum(sum_over_time(heap_usage0[5m]))"
    engine.query_range(q, s, e, 60)
    restages = []
    orig = ST.stage_from_shard
    monkeypatch.setattr(
        ST, "stage_from_shard",
        lambda *a, **k: (restages.append(1), orig(*a, **k))[1],
    )
    ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=2,
                                       start_ms=BASE + 1_010_000))
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert restages == []
    ms2 = TimeSeriesMemStore()
    ms2.setup(Dataset("ds"), [0])
    ms2.ingest("ds", 0, machine_metrics(n_series=4, n_samples=100, start_ms=BASE))
    ms2.ingest("ds", 0, machine_metrics(n_series=4, n_samples=2,
                                        start_ms=BASE + 1_010_000))
    want = QueryEngine(ms2, "ds").query_range(q, s, e, 60).grids[0].values_np()
    np.testing.assert_array_equal(got, want)


def test_live_edge_jittered_append_repair_matches_fresh_engine(monkeypatch):
    """Jittered (near-regular) live scrapes — the realistic production
    shape — must ALSO take the append-repair path: nominal grid extended
    by per-column midranges, deviations re-checked against the jitter
    bound, results equal to a fresh engine."""
    from filodb_tpu.core.schemas import PROM_COUNTER

    rng = np.random.default_rng(21)
    n0, nseries = 120, 5
    nominal = BASE + (1 + np.arange(n0, dtype=np.int64)) * 10_000
    data = {}
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    for i in range(nseries):
        ts = nominal + np.rint(rng.uniform(-0.05, 0.05, n0) * 10_000).astype(np.int64)
        v = np.cumsum(rng.uniform(0, 10, n0)) + 1e9
        data[i] = (list(ts), list(v))
        tags = {"_metric_": "rq_total", "_ws_": "w", "_ns_": "n", "inst": f"h{i}"}
        ms.shard("ds", 0).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts, {"count": v}))
    engine = QueryEngine(ms, "ds")
    s = (BASE + 400_000) / 1000
    e = (BASE + (n0 + 30) * 10_000) / 1000
    q = "sum(rate(rq_total[5m]))"
    head = n0
    restages = []
    for step in range(4):
        engine.query_range(q, s, e, 60)
        if step == 0:
            calls = _stage_calls(monkeypatch)
        new_nom = BASE + (1 + head + np.arange(2, dtype=np.int64)) * 10_000
        for i in range(nseries):
            nts = new_nom + np.rint(
                rng.uniform(-0.05, 0.05, 2) * 10_000).astype(np.int64)
            nv = np.cumsum(rng.uniform(0, 10, 2)) + data[i][1][-1]
            data[i][0].extend(nts.tolist())
            data[i][1].extend(nv.tolist())
            tags = {"_metric_": "rq_total", "_ws_": "w", "_ns_": "n",
                    "inst": f"h{i}"}
            ms.shard("ds", 0).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, nts, {"count": nv}))
        head += 2
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls == [], "jittered live-edge appends must repair, not restage"
    ms2 = TimeSeriesMemStore()
    ms2.setup(Dataset("ds"), [0])
    for i in range(nseries):
        tags = {"_metric_": "rq_total", "_ws_": "w", "_ns_": "n", "inst": f"h{i}"}
        ms2.shard("ds", 0).ingest_series(SeriesBatch(
            PROM_COUNTER, tags, np.asarray(data[i][0], np.int64),
            {"count": np.asarray(data[i][1])}))
    want = QueryEngine(ms2, "ds").query_range(q, s, e, 60).grids[0].values_np()
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    ok = ~np.isnan(want)
    np.testing.assert_allclose(got[ok], want[ok], rtol=1e-3, atol=1e-3)


def test_jittered_gap_sample_is_never_silently_dropped(monkeypatch):
    """Reviewer-found hazard: on a jittered grid a series with negative
    head deviation can accept an in-order sample BELOW last_nom + maxdev;
    the repair must not skip it (per-series read starts make it a
    non-uniform batch -> restage fallback includes it)."""
    from filodb_tpu.core.schemas import GAUGE as G

    rng = np.random.default_rng(31)
    n0, nseries = 80, 4
    nominal = BASE + (1 + np.arange(n0, dtype=np.int64)) * 10_000
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    all_tags = []
    for i in range(nseries):
        dev = np.rint(rng.uniform(-0.04, 0.04, n0) * 10_000).astype(np.int64)
        if i == 0:
            dev[-1] = -350  # series 0's head trails the last nominal slot
        ts = nominal + dev
        tags = {"_metric_": "g", "_ws_": "w", "_ns_": "n", "inst": f"h{i}"}
        all_tags.append(tags)
        ms.shard("ds", 0).ingest_series(SeriesBatch(
            G, tags, ts, {"value": 50 + rng.standard_normal(n0)}))
    engine = QueryEngine(ms, "ds")
    s = (BASE + 400_000) / 1000
    e = (BASE + (n0 + 20) * 10_000) / 1000
    q = "sum(count_over_time(g[5m]))"
    before = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    blk = next(iter(ms.shard("ds", 0).stage_cache.values())).block
    assert blk.nominal_ts is not None, "setup must stage a jittered block"
    md = blk.maxdev_ms
    # in-order for series 0 (after its head at last_nom-350) but BELOW
    # last_nom + maxdev — the skipped-gap shape
    gap_ts = nominal[-1] - 100
    assert nominal[-1] - 350 < gap_ts <= nominal[-1] + md
    ms.shard("ds", 0).ingest_series(SeriesBatch(
        G, all_tags[0], np.array([gap_ts], np.int64), {"value": np.array([99.0])}))
    after = engine.query_range(q, s, e, 60).grids[0].values_np()
    # every 5m window covering gap_ts must count one more sample
    assert np.nansum(after) > np.nansum(before), \
        "the gap sample must be visible in cached query results"


def test_append_repair_falls_back_when_grid_diverges(setup, monkeypatch):
    """Series appending DIFFERENT timestamps break the shared grid: repair
    must decline and a full re-stage must produce correct results."""
    ms, engine, shard = setup
    tags = _existing_tags(shard)
    s, e = (BASE + 400_000) / 1000, (BASE + 2_600_000) / 1000
    q = "count(heap_usage0)"
    engine.query_range(q, s, e, 60)
    restages = []
    orig = ST.stage_from_shard
    monkeypatch.setattr(
        ST, "stage_from_shard",
        lambda *a, **k: (restages.append(1), orig(*a, **k))[1],
    )
    # only ONE series gets a new sample: per-series counts now differ
    _append(ms, tags, [BASE + 2_150_000], [1.0])
    got = engine.query_range(q, s, e, 60)
    assert restages, "divergent append must fall back to a full re-stage"
    assert got.grids[0].n_series >= 1


def test_gap_series_span_extension_invalidates(setup, monkeypatch):
    """Reviewer-found hazard: a sample BEYOND the cached range can extend a
    gap series' index span so it newly overlaps the range — the cached
    block's row set would then disagree with a fresh partition lookup. The
    effect interval must start at the series' PREVIOUS newest sample."""
    ms, engine, shard = setup
    tags = _existing_tags(shard)
    # gap series: one old sample long before the queried range
    gap = dict(tags, instance="gap-host")
    _append(ms, gap, [BASE + 100_000], [1.0])
    s, e = (BASE + 2_600_000) / 1000, (BASE + 3_200_000) / 1000
    q = "count(last_over_time(heap_usage0[40m]))"
    r1 = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    calls = _stage_calls(monkeypatch)
    # new sample BEYOND the cached range extends gap-host's span across it
    _append(ms, gap, [BASE + 5_000_000], [2.0])
    r2 = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls, "span-crossing append must re-stage"
    # and the fresh result must be consistent (same or more series counted,
    # never a row/label mismatch crash)
    assert r2.shape == r1.shape


def test_results_track_in_range_ingest_for_existing_series(setup, monkeypatch):
    ms, engine, shard = setup
    tags = _existing_tags(shard)
    s, e = (BASE + 400_000) / 1000, (BASE + 2_500_000) / 1000
    q = f'sum(heap_usage0{{instance="{tags["instance"]}"}})'
    before = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    # append within the (wide) cached range for the EXISTING series
    _append(ms, tags, [BASE + 2_200_000, BASE + 2_300_000], [500.0, 500.0])
    after = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert not np.array_equal(after, before), \
        "in-range append to an existing series must be visible immediately"
