"""Selective staging-cache invalidation (memstore/shard.py
_invalidate_stage_range): live scrapes landing BEYOND a cached query range
must not evict it (the dashboard-historical-panel-under-ingest cost), while
anything that can change the cached block's content must."""

import numpy as np
import pytest

import filodb_tpu.ops.staging as ST
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import Dataset, GAUGE, METRIC_TAG
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


@pytest.fixture
def setup():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=6, n_samples=200, start_ms=BASE))
    engine = QueryEngine(ms, "ds")
    return ms, engine, ms.shard("ds", 0)


def _stage_calls(monkeypatch):
    calls = []
    orig = ST.stage_from_shard

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ST, "stage_from_shard", spy)
    return calls


def _append(ms, tags, ts, vals):
    ms.shard("ds", 0).ingest_series(
        SeriesBatch(GAUGE, dict(tags), np.asarray(ts, np.int64),
                    {"value": np.asarray(vals, np.float64)})
    )


def _existing_tags(shard):
    pid = int(shard.lookup_partitions([], 0, 2**62)[0])
    return dict(shard.partition(pid).tags)


def _new_series_tags(tags):
    return dict(tags, instance="brand-new-host")


def test_append_beyond_range_keeps_cache(setup, monkeypatch):
    ms, engine, shard = setup
    s, e = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = "sum(heap_usage0)"
    want = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    calls = _stage_calls(monkeypatch)
    tags = _existing_tags(shard)
    # new samples strictly beyond the staged range (raw end = e)
    _append(ms, tags, [BASE + 5_000_000], [1.0])
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls == [], "historical range must stay cached"
    np.testing.assert_array_equal(got, want)


def test_append_into_range_invalidates(setup, monkeypatch):
    """A live-edge panel (range end past the newest sample) must re-stage
    when a fresh scrape lands inside its range."""
    ms, engine, shard = setup
    s, e = (BASE + 400_000) / 1000, (BASE + 2_500_000) / 1000
    q = "sum(heap_usage0)"
    before = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    calls = _stage_calls(monkeypatch)
    tags = _existing_tags(shard)
    # newer than the series head (not out-of-order) AND inside [s, e]
    _append(ms, tags, [BASE + 2_200_000], [1000.0])
    got = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls, "in-range sample must re-stage"
    assert not np.array_equal(got, before), "new in-range data must show up"


def test_new_series_invalidates_even_beyond_range(setup, monkeypatch):
    ms, engine, shard = setup
    s, e = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = "sum(heap_usage0)"
    engine.query_range(q, s, e, 60)
    calls = _stage_calls(monkeypatch)
    # a NEW series could match any cached filter set: conservative clear
    _append(ms, _new_series_tags(_existing_tags(shard)),
            [BASE + 5_000_000], [1.0])
    engine.query_range(q, s, e, 60)
    assert calls, "new series must invalidate"


def test_gap_series_span_extension_invalidates(setup, monkeypatch):
    """Reviewer-found hazard: a sample BEYOND the cached range can extend a
    gap series' index span so it newly overlaps the range — the cached
    block's row set would then disagree with a fresh partition lookup. The
    effect interval must start at the series' PREVIOUS newest sample."""
    ms, engine, shard = setup
    tags = _existing_tags(shard)
    # gap series: one old sample long before the queried range
    gap = dict(tags, instance="gap-host")
    _append(ms, gap, [BASE + 100_000], [1.0])
    s, e = (BASE + 2_600_000) / 1000, (BASE + 3_200_000) / 1000
    q = "count(last_over_time(heap_usage0[40m]))"
    r1 = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    calls = _stage_calls(monkeypatch)
    # new sample BEYOND the cached range extends gap-host's span across it
    _append(ms, gap, [BASE + 5_000_000], [2.0])
    r2 = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert calls, "span-crossing append must re-stage"
    # and the fresh result must be consistent (same or more series counted,
    # never a row/label mismatch crash)
    assert r2.shape == r1.shape


def test_results_track_in_range_ingest_for_existing_series(setup, monkeypatch):
    ms, engine, shard = setup
    tags = _existing_tags(shard)
    s, e = (BASE + 400_000) / 1000, (BASE + 2_500_000) / 1000
    q = f'sum(heap_usage0{{instance="{tags["instance"]}"}})'
    before = engine.query_range(q, s, e, 60).grids[0].values_np().copy()
    # append within the (wide) cached range for the EXISTING series
    _append(ms, tags, [BASE + 2_200_000, BASE + 2_300_000], [500.0, 500.0])
    after = engine.query_range(q, s, e, 60).grids[0].values_np()
    assert not np.array_equal(after, before), \
        "in-range append to an existing series must be visible immediately"
