"""Cost-model scheduling (query/costmodel.py; doc/perf.md "Cost-model
scheduling").

The scheduling plane prices work in device-seconds: the predictor joins
querylog fingerprints to realized kernel time (EWMA per fingerprint +
family, flat prior for the truly cold), admission drains per-tenant
buckets by the prediction (Retry-After = the bucket's actual drain time —
shed, wait the advertised seconds, admit, by construction), and the
dispatch scheduler widens its batch window under predicted queue cost,
collapses it when idle, and pre-warms recurrence-ring executables off the
serving path.

Rides the scheduler marker (make test-scheduler). All bucket/window tests
use an injected clock — deterministic by construction. The min/max fused
minmax tests assert BIT-equality (min/max are exact reduces: no
accumulation-order ulps) and a zero grid_jitter/grid_holes fallback delta.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import (
    Dataset,
    METRIC_TAG,
    PROM_COUNTER,
    shard_for,
)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.obs.kernels import KERNELS
from filodb_tpu.query.costmodel import CostModel, family_of
from filodb_tpu.query.scheduler import (
    AdmissionController,
    AdmissionRejected,
    DispatchScheduler,
)
from filodb_tpu.testkit import counter_batch, kernel_dispatch_total

pytestmark = pytest.mark.scheduler

BASE = 1_600_000_000_000
INTERVAL = 10_000
N_SHARDS = 8
N_SAMPLES = 240
START = (BASE + 600_000) / 1000
END = START + 900
STEP = 60


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _ingest_gauges(ms, metric, n_series, jitter=0.05, hole_frac=0.0,
                   seed=5):
    """Near-regular (jitter) or holey (masked) gauge fixtures — the grid
    classes whose min/max used to degrade to the general kernel."""
    rng = np.random.default_rng(seed)
    # half-interval phase shift keeps the jittered fixture out of the
    # "holes" classification (see tests/test_fused_jitter.py)
    nominal = (BASE + INTERVAL // 2
               + (1 + np.arange(N_SAMPLES, dtype=np.int64)) * INTERVAL)
    for i in range(n_series):
        tags = {METRIC_TAG: metric, "_ws_": "w", "_ns_": "n",
                "instance": f"h{i}", "job": f"j{i % 4}"}
        shard = shard_for(tags, spread=3, num_shards=N_SHARDS)
        dev = np.rint(
            rng.uniform(-jitter, jitter, N_SAMPLES) * INTERVAL
        ).astype(np.int64)
        ts = nominal + dev
        vals = 50 + 20 * rng.standard_normal(N_SAMPLES)
        keep = np.ones(N_SAMPLES, bool)
        if hole_frac > 0:
            drop = rng.choice(np.arange(1, N_SAMPLES - 1),
                              max(1, int(hole_frac * N_SAMPLES)),
                              replace=False)
            keep[drop] = False
        ms.shard("ds", shard).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts[keep], {"count": vals[keep]})
        )


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=48, n_samples=N_SAMPLES, start_ms=BASE),
        spread=3,
    )
    _ingest_gauges(ms, "gauge_jit", 24, jitter=0.05, seed=5)
    _ingest_gauges(ms, "gauge_holes", 24, jitter=0.05, hole_frac=0.01,
                   seed=9)
    return ms


def _rows(res):
    out = {}
    for g in res.grids:
        for lbls, vals in zip(g.labels, g.values_np()):
            out[tuple(sorted(lbls.items()))] = np.asarray(vals)
    return out


def _fallback_count(reason: str) -> int:
    from filodb_tpu.metrics import REGISTRY

    for line in REGISTRY.expose().splitlines():
        if line.startswith(
            f'filodb_fused_fallback_total{{reason="{reason}"}}'
        ):
            return int(float(line.rsplit(" ", 1)[1]))
    return 0


def _record(fp, promql, predicted, realized, steps=16, series=48,
            status="ok"):
    """A synthetic completed querylog record in the shape
    QueryLog.publish emits (the predictor's only input)."""
    return {
        "fingerprint": fp, "promql": promql, "status": status,
        "predicted_cost_s": predicted, "realized_cost_s": realized,
        "grid": {"steps": steps}, "stats": {"series_scanned": series},
    }


# ---------------------------------------------------------------------------
# the predictor
# ---------------------------------------------------------------------------


class TestFamilyOf:
    def test_range_functions_and_instant(self):
        assert family_of("sum by (job) (rate(http[5m]))") == "rate"
        assert family_of("min(min_over_time(g[3m]))") == "min_over_time"
        assert family_of("quantile_over_time(0.9, g[30m])") == (
            "quantile_over_time")
        assert family_of("sum(up)") == "instant"
        assert family_of("") == "instant"


class TestPredictor:
    def test_cold_prior_then_convergence(self):
        """The acceptance loop: cold -> flat prior; after N observations
        of realized cost the fingerprint EWMA prices within 2x."""
        cm = CostModel(prior_cost_s=0.05)
        fp, q = "f" * 16, "sum(rate(http_requests_total[5m]))"
        cost, src = cm.predict(fp, steps=16, family=family_of(q))
        assert (cost, src) == (0.05, "prior")
        realized = 0.4  # 8x the prior: convergence must actually move
        for _ in range(8):
            pred, _src = cm.predict(fp, steps=16, family=family_of(q))
            cm.observe(_record(fp, q, pred, realized))
        pred, src = cm.predict(fp, steps=16, family=family_of(q))
        assert src == "fingerprint"
        assert max(pred / realized, realized / pred) < 2.0
        assert cm.error_ratio(fp) is not None
        assert cm.error_ratio(fp) < 2.0

    def test_cold_fingerprint_priced_by_family_prior(self):
        """A never-seen fingerprint with family evidence is priced at the
        family unit cost x its own grid work x the conservative cold
        multiplier — and scales with the work, so a 10x-larger grid of
        the same family predicts 10x the cost."""
        cm = CostModel(prior_cost_s=0.05, cold_multiplier=2.0)
        q = "sum(rate(http_requests_total[5m]))"
        for i in range(4):
            cm.observe(_record(f"warm{i}", q, None, 0.2, steps=16,
                               series=48))
        small, src = cm.predict("cold-a", steps=16, series=48,
                                family="rate")
        assert src == "family"
        big, _ = cm.predict("cold-b", steps=160, series=48, family="rate")
        assert big == pytest.approx(10 * small, rel=1e-6)
        # cold multiplier: over-pricing an unknown is the cheap mistake
        assert small == pytest.approx(2.0 * 0.2, rel=1e-6)
        # no family evidence either -> the flat prior
        cost, src = cm.predict("cold-c", family="quantile_over_time")
        assert (cost, src) == (0.05, "prior")

    def test_observe_skips_shed_and_unrealized(self):
        cm = CostModel()
        cm.observe(_record("s" * 16, "sum(rate(m[5m]))", 0.05, 0.2,
                           status="shed"))
        cm.observe(_record("u" * 16, "sum(rate(m[5m]))", 0.05, None))
        snap = cm.snapshot()
        assert snap["observed"] == 0
        assert snap["fingerprints"] == []

    def test_snapshot_surfaces_predictions_and_errors(self):
        """GET /debug/costmodel payload: per-fingerprint prediction vs
        realized, family priors, evidence-tier counts."""
        cm = CostModel()
        fp, q = "a" * 16, "max(max_over_time(g[5m]))"
        pred, _ = cm.predict(fp, family=family_of(q))
        cm.observe(_record(fp, q, pred, 0.1))
        snap = cm.snapshot()
        assert snap["observed"] == 1
        assert snap["prediction_sources"]["prior"] == 1
        (e,) = snap["fingerprints"]
        assert e["fingerprint"] == fp
        assert e["last_realized_s"] == pytest.approx(0.1)
        assert e["last_error_ratio"] == pytest.approx(2.0)
        assert snap["families"]["max_over_time"]["n"] == 1


# ---------------------------------------------------------------------------
# device-second admission
# ---------------------------------------------------------------------------


class TestDeviceSecondAdmission:
    def test_legacy_query_quota_converts_unchanged(self):
        """A legacy ``{"rate": 1, "burst": 2}`` (queries) quota converted
        to device-seconds via the prior admits exactly the same pattern:
        2-query burst, then one query/second — unit conversion alone
        changes no admission decision."""
        clk = FakeClock()
        ctl = AdmissionController({"demo/app": {"rate": 1.0, "burst": 2}},
                                  clock=clk, prior_cost_s=0.05)
        with ctl.admit("demo", "app"):
            pass
        with ctl.admit("demo", "app"):
            pass
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("demo", "app")
        assert ei.value.outcome == "shed_rate"
        # one prior-priced query refills in exactly 1/rate seconds
        assert ei.value.retry_after_s == pytest.approx(1.0)
        snap = ctl.snapshot()
        assert snap["unit"] == "device_seconds"
        assert snap["prior_cost_s"] == pytest.approx(0.05)

    def test_legacy_quota_floors_cheap_queries_at_one(self):
        """A legacy query-count quota charges at least one prior-priced
        query even when the model prices the query far cheaper — "2
        queries/s" configured by the operator keeps meaning 2, not
        thousands of model-priced cheap ones."""
        clk = FakeClock()
        ctl = AdmissionController({"demo/app": {"rate": 1.0, "burst": 2}},
                                  clock=clk, prior_cost_s=0.05)
        with ctl.admit("demo", "app", cost_s=1e-4):
            pass
        with ctl.admit("demo", "app", cost_s=1e-4):
            pass
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("demo", "app", cost_s=1e-4)
        assert ei.value.outcome == "shed_rate"

    def test_cheap_tenant_flows_while_monster_sheds(self):
        """The tentpole fairness contract: 100 cheap queries fit the
        cheap tenant's device-second budget while one monster query
        drains (and then sheds) its own tenant's bucket — expensive
        queries drain proportionally, they don't count as '1'."""
        clk = FakeClock()
        ctl = AdmissionController(
            {"demo/cheap": {"rate_device_s": 0.5, "burst_device_s": 1.0},
             "demo/monster": {"rate_device_s": 0.5, "burst_device_s": 1.0}},
            clock=clk,
        )
        for _ in range(100):
            with ctl.admit("demo", "cheap", cost_s=0.002):
                pass
            clk.t += 0.01  # 0.2 dev-s/s arrival rate < 0.5 refill
        # the monster's first admit is the full-bucket clamp (a query
        # pricier than the burst admits after a full drain, not never)...
        with ctl.admit("demo", "monster", cost_s=30.0):
            pass
        # ...and leaves the bucket empty: the next one sheds
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("demo", "monster", cost_s=30.0)
        assert ei.value.outcome == "shed_rate"
        assert ei.value.predicted_cost_s == pytest.approx(30.0)
        # the cheap tenant's own bucket is untouched by the monster
        with ctl.admit("demo", "cheap", cost_s=0.002):
            pass

    def test_expensive_queries_drain_proportionally(self):
        clk = FakeClock()
        ctl = AdmissionController(
            {"*": {"rate_device_s": 1.0, "burst_device_s": 1.0}},
            clock=clk,
        )
        for _ in range(4):  # 4 x 0.25 dev-s empties the 1.0 dev-s burst
            with ctl.admit("t", "a", cost_s=0.25):
                pass
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit("t", "a", cost_s=0.1)
        # Retry-After is THIS query's drain time (0.1 dev-s at 1/s), not
        # a flat per-query constant
        assert ei.value.retry_after_s == pytest.approx(0.1)

    def test_shed_plus_advertised_wait_admits(self):
        """Regression (the 429 contract): a shed tenant that waits
        exactly the advertised Retry-After is admitted — the hint is the
        bucket's computed drain time, not a guess."""
        clk = FakeClock()
        ctl = AdmissionController(
            {"*": {"rate_device_s": 0.25, "burst_device_s": 0.5}},
            clock=clk,
        )
        with ctl.admit("t", "a", cost_s=0.5):
            pass
        for cost in (0.5, 0.125, 0.04):
            with pytest.raises(AdmissionRejected) as ei:
                ctl.admit("t", "a", cost_s=cost)
            assert ei.value.outcome == "shed_rate"
            assert 0 < ei.value.retry_after_s <= 60
            clk.t += ei.value.retry_after_s
            with ctl.admit("t", "a", cost_s=cost):
                pass  # waiting the advertised seconds admits
            # leave the bucket empty again for the next round
            drain = ctl._states["t/a"].bucket
            drain._tokens = 0.0


# ---------------------------------------------------------------------------
# adaptive batch window
# ---------------------------------------------------------------------------


class TestAdaptiveWindow:
    def test_widens_under_load_and_collapses_idle(self):
        clk = FakeClock()
        s = DispatchScheduler(window_ms=2, window_cap_ms=50,
                              load_ref_cost_s=0.25, clock=clk)
        assert s.enabled and s.adaptive
        assert s.window_s == 0.0  # idle pipe: a lone query never waits
        s._note_load(0.05)  # a fifth of the reference cost
        assert s.window_s == pytest.approx(0.05 * 0.05 / 0.25)
        s._note_load(1.0)  # well past the reference: clamp at the cap
        assert s.window_s == pytest.approx(0.050)
        clk.t += 30.0  # ~15 decay constants with no arrivals
        assert s.window_s < 0.001

    def test_without_cap_window_is_constant(self):
        clk = FakeClock()
        s = DispatchScheduler(window_ms=5, clock=clk)
        assert s.enabled and not s.adaptive
        s._note_load(100.0)
        assert s.window_s == pytest.approx(0.005)
        assert DispatchScheduler(window_ms=0, clock=clk).enabled is False

    def test_load_decays_between_arrivals(self):
        clk = FakeClock()
        s = DispatchScheduler(window_ms=2, window_cap_ms=40,
                              load_ref_cost_s=1.0, clock=clk)
        s._note_load(1.0)
        w_full = s.window_s
        clk.t += s._load_tau_s  # one decay constant
        assert s.window_s == pytest.approx(w_full * np.exp(-1.0), rel=1e-6)


# ---------------------------------------------------------------------------
# executable pre-warm
# ---------------------------------------------------------------------------


class TestPrewarm:
    DESC = {"promql": "sum(rate(m[5m]))", "step_ms": 60_000,
            "span_ms": 900_000, "end_lag_ms": 0}

    def test_ring_keys_warm_once_past_the_bar(self):
        s = DispatchScheduler(window_ms=0, prewarm_min_count=3)
        warmed = []
        s.register_prewarmer(lambda desc: warmed.append(desc["promql"]))
        s.key_ring.observe("k1", self.DESC)
        assert s.prewarm_tick(storms={}) == []  # 1 observation < bar
        s.key_ring.observe("k1", self.DESC)
        s.key_ring.observe("k1", self.DESC)
        assert s.prewarm_tick(storms={}) == ["k1"]
        assert warmed == ["sum(rate(m[5m]))"]
        # once-only: a warmed key never re-runs
        assert s.prewarm_tick(storms={}) == []
        assert s.stats["prewarmed"] == 1

    def test_recompile_storm_lowers_the_bar(self):
        s = DispatchScheduler(window_ms=0, prewarm_min_count=3)
        s.register_prewarmer(lambda desc: None)
        s.key_ring.observe("k2", self.DESC)
        assert s.prewarm_tick(storms={}) == []
        # a live storm annotation: every cold executable is about to be
        # hot — one observation suffices
        assert s.prewarm_tick(storms={"fused_agg": {"n": 6}}) == ["k2"]

    def test_prewarm_errors_are_advisory(self):
        def boom(desc):
            raise RuntimeError("trace failed")

        s = DispatchScheduler(window_ms=0, prewarm_min_count=1)
        s.register_prewarmer(boom)
        s.key_ring.observe("k3", self.DESC)
        assert s.prewarm_tick(storms={}) == []  # error -> not "warmed"
        assert s.stats["prewarmed"] == 0
        # the failing key is memoed anyway: no retry storm
        assert s.prewarm_tick(storms={}) == []

    def test_prewarmed_key_first_real_dispatch_compiles_nothing(self, store):
        """The acceptance contract: seed the recurrence ring with a
        not-yet-compiled query shape, run one prewarm tick, then issue
        the query for real — the serving dispatch must record ZERO new
        compiles (the tick paid trace+compile off the serving path)."""
        sched = DispatchScheduler(window_ms=5, prewarm_min_count=3)
        engine = QueryEngine(store, "ds", PlannerParams(
            batch_window_ms=5, dispatch_scheduler=sched))
        # a grid shape nothing else in the suite compiles: 15 steps
        end_s = START + 840
        q = "sum by (job) (rate(http_requests_total[6m]))"
        desc = {"promql": q, "step_ms": 60_000, "span_ms": 840_000,
                "end_lag_ms": (time.time() - end_s) * 1000}
        key = ("prewarm-proof", q)
        for _ in range(3):
            sched.key_ring.observe(key, desc)
        before = KERNELS.totals()["compiles"]
        assert sched.prewarm_tick(storms={}) == [key]
        warmed = KERNELS.totals()["compiles"]
        assert warmed > before, "the tick itself must trace+compile"
        engine.query_range(q, START, end_s, STEP)
        assert KERNELS.totals()["compiles"] == warmed, (
            "first real dispatch after prewarm must record zero compiles"
        )


# ---------------------------------------------------------------------------
# min/max_over_time on jittered/holey grids: fused, bit-equal, no fallback
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def minmax_engines(store):
    fused = QueryEngine(store, "ds")
    ref = QueryEngine(store, "ds", PlannerParams(fused_aggregate=False))
    return fused, ref


MINMAX_QUERIES = [
    "min(min_over_time({m}[5m]))",
    "max(max_over_time({m}[5m]))",
    "min by (job) (min_over_time({m}[3m]))",
    "max by (job) (max_over_time({m}[5m]))",
]


@pytest.mark.parametrize("metric", ["gauge_jit", "gauge_holes"])
@pytest.mark.parametrize("q_tpl", MINMAX_QUERIES)
def test_minmax_fused_bit_equal_no_fallback(minmax_engines, metric, q_tpl):
    """min/max_over_time on jittered and holey grids rides the fused
    minmax programs: BIT-equal to the reference tree (min/max are exact
    reduces under min/max epilogues — no accumulation-order ulps) with
    the grid_jitter/grid_holes degrade reasons NOT firing."""
    fused, ref = minmax_engines
    q = q_tpl.format(m=metric)
    before = (_fallback_count("grid_jitter"), _fallback_count("grid_holes"))
    a = _rows(fused.query_range(q, START, END, STEP))
    b = _rows(ref.query_range(q, START, END, STEP))
    assert (_fallback_count("grid_jitter"),
            _fallback_count("grid_holes")) == before, q
    assert a.keys() == b.keys(), q
    for k in a:
        assert np.array_equal(a[k], b[k], equal_nan=True), (q, k)


@pytest.mark.parametrize("metric", ["gauge_jit", "gauge_holes"])
def test_minmax_warm_single_dispatch_with_cost_model_active(store, metric):
    """The warm canonical query stays exactly ONE fused dispatch with the
    whole cost-model plane active (admission pricing + adaptive window +
    recurrence ring all in the loop)."""
    ctl = AdmissionController(
        {"*": {"rate_device_s": 100.0, "burst_device_s": 100.0}})
    sched = DispatchScheduler(window_ms=5, window_cap_ms=50)
    engine = QueryEngine(store, "ds", PlannerParams(
        admission=ctl, batch_window_ms=5, dispatch_scheduler=sched))
    q = f"min(min_over_time({metric}[5m]))"
    engine.query_range(q, START, END, STEP)  # stage + compile warm
    before = kernel_dispatch_total()
    engine.query_range(q, START, END, STEP)
    assert kernel_dispatch_total() - before == 1, (
        f"warm {q} must stay ONE fused dispatch with the cost model on"
    )


def test_engine_stamps_costs_on_querylog(store):
    """End-to-end: a served query's cost record carries the admission
    prediction AND the realized device time, and the global model folds
    the observation in (fingerprint goes warm)."""
    from filodb_tpu.obs.querylog import promql_fingerprint
    from filodb_tpu.query.costmodel import COST_MODEL

    engine = QueryEngine(store, "ds")
    q = "max by (job) (max_over_time(gauge_jit[4m]))"
    res = engine.query_range(q, START, END, STEP)
    rec = res.query_log
    assert rec is not None
    assert rec["predicted_cost_s"] is not None and rec["predicted_cost_s"] > 0
    assert rec["realized_cost_s"] is not None and rec["realized_cost_s"] > 0
    fp = promql_fingerprint("ds", q, int(STEP * 1000),
                            int((END - START) * 1000))
    assert rec["fingerprint"] == fp
    # the observation landed: the model now prices this fingerprint from
    # its own evidence tier
    cost, src = COST_MODEL.predict(fp, family=family_of(q))
    assert src == "fingerprint"
    assert cost > 0


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------


def test_costmodel_http_surfaces():
    """GET /debug/costmodel, the querylog cost fields on
    /api/v1/query_profile, and the error-ratio histogram on the
    self-scrape."""
    import json
    import urllib.parse
    import urllib.request

    from filodb_tpu.server import FiloServer

    srv = FiloServer({"dataset": "prometheus", "shards": 2})
    port = srv.start(port=0)
    host = f"http://127.0.0.1:{port}"
    try:
        srv.memstore.ingest_routed(
            "prometheus",
            counter_batch(n_series=12, n_samples=N_SAMPLES, start_ms=BASE),
            spread=1,
        )
        q = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
        url = (f"{host}/api/v1/query_range?query={q}"
               f"&start={START}&end={END}&step={STEP}")
        for _ in range(2):
            with urllib.request.urlopen(url) as r:
                assert json.loads(r.read())["status"] == "success"
        with urllib.request.urlopen(f"{host}/debug/costmodel") as r:
            snap = json.loads(r.read())["data"]
        assert snap["observed"] >= 1
        assert snap["fingerprints"], "served queries must appear"
        assert any(e["last_realized_s"] for e in snap["fingerprints"])
        with urllib.request.urlopen(f"{host}/debug/querylog") as r:
            records = json.loads(r.read())["data"]
        rec = next(r for r in records
                   if r.get("predicted_cost_s") is not None)
        assert rec["realized_cost_s"] is not None
        with urllib.request.urlopen(
            f"{host}/api/v1/query_profile?id={rec['id']}"
        ) as r:
            prof = json.loads(r.read())["data"]
        assert prof["predicted_cost_s"] == rec["predicted_cost_s"]
        assert prof["realized_cost_s"] == rec["realized_cost_s"]
        with urllib.request.urlopen(f"{host}/metrics") as r:
            scrape = r.read().decode()
        assert "filodb_costmodel_error_ratio" in scrape
    finally:
        srv.stop()
