"""Persistence, flush, recovery, gateway, downsample tests (model: reference
IngestionAndRecoverySpec multi-jvm flow — ingest, flush, kill, recover,
verify query correctness — plus CsvStream / parser specs)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.downsample.downsampler import (
    DS_GAUGE,
    ShardDownsampler,
    batch_downsample,
    downsample_samples,
)
from filodb_tpu.gateway.parsers import (
    influx_to_batch,
    parse_influx_line,
    parse_prom_text,
    prom_text_to_batches,
)
from filodb_tpu.gateway.stream import CsvStream, IngestionPipeline, MemoryStream
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.store.columnstore import LocalColumnStore, NullColumnStore
from filodb_tpu.store.flush import FlushCoordinator, recover_shard
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


class TestFlushAndRecovery:
    def test_flush_write_read_roundtrip(self, tmp_path):
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=5, n_samples=250, start_ms=BASE), offset=7)
        store = LocalColumnStore(str(tmp_path))
        fc = FlushCoordinator(ms, store)
        res = fc.flush_shard("ds", 0)
        assert res.chunks_written == 5 * 3  # 250 samples / 100 -> 3 chunks
        assert store.read_checkpoints("ds", 0)  # every group checkpointed
        chunks = list(store.read_chunks("ds", 0))
        assert len(chunks) == 15
        header, schema_name, encs = chunks[0]
        assert schema_name == "gauge"
        assert header["n"] == 100

    def test_kill_and_recover_query_correct(self, tmp_path):
        """ingest -> flush -> 'kill' -> recover into a fresh memstore ->
        same query answers (the reference's IngestionAndRecoverySpec)."""
        store = LocalColumnStore(str(tmp_path))
        batch = machine_metrics(n_series=8, n_samples=300, start_ms=BASE)

        ms1 = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms1.setup(Dataset("ds"), [0])
        ms1.ingest("ds", 0, batch, offset=0)
        FlushCoordinator(ms1, store).flush_shard("ds", 0)
        start_s = (BASE + 600_000) / 1000
        end_s = (BASE + 2_400_000) / 1000
        want = QueryEngine(ms1, "ds").query_range("avg(heap_usage0)", start_s, end_s, 60.0)

        ms2 = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms2.setup(Dataset("ds"), [0])
        replay_from = recover_shard(ms2, store, "ds", 0)
        assert replay_from == 0
        sh = ms2.shard("ds", 0)
        assert sh.num_partitions == 8
        got = QueryEngine(ms2, "ds").query_range("avg(heap_usage0)", start_s, end_s, 60.0)
        np.testing.assert_allclose(
            got.grids[0].values_np(), want.grids[0].values_np(), rtol=1e-5, equal_nan=True
        )

    def test_recovery_replays_unflushed_tail(self, tmp_path):
        """Rows ingested after the last flush come back via stream replay."""
        store = LocalColumnStore(str(tmp_path))
        stream = MemoryStream()
        b1 = machine_metrics(n_series=3, n_samples=100, start_ms=BASE)
        b2 = machine_metrics(n_series=3, n_samples=100, start_ms=BASE + 100 * 10_000)
        stream.append(b1)
        stream.append(b2)

        ms1 = TimeSeriesMemStore(StoreConfig(max_chunk_size=50))
        ms1.setup(Dataset("ds"), [0])
        fc = FlushCoordinator(ms1, store)
        ms1.ingest("ds", 0, b1, offset=0)
        fc.flush_shard("ds", 0, offset=0)
        ms1.ingest("ds", 0, b2, offset=1)  # never flushed -> lost on kill

        ms2 = TimeSeriesMemStore(StoreConfig(max_chunk_size=50))
        ms2.setup(Dataset("ds"), [0])
        pipe = IngestionPipeline(ms2, "ds", 0, stream)
        pipe.recover_and_run(store)
        part = ms2.shard("ds", 0).partitions[0]
        assert part.num_samples() == 200  # 100 recovered + 100 replayed

    def test_null_store(self):
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=50))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=2, n_samples=120, start_ms=BASE))
        store = NullColumnStore()
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        # 120 samples / 50-chunks -> 2 sealed + the open buffer sealed at flush
        assert store.chunks_written == 2 * 3


class TestGatewayParsers:
    def test_influx_basic(self):
        out = list(parse_influx_line("cpu,host=a,dc=us value=0.5 1600000000000000000"))
        assert out == [("cpu", {"host": "a", "dc": "us"}, 1_600_000_000_000, 0.5)]

    def test_influx_multi_field(self):
        out = list(parse_influx_line("mem,host=a used=10i,free=20i 1600000000000000000"))
        metrics = {m for m, *_ in out}
        assert metrics == {"mem_used", "mem_free"}

    def test_influx_escapes_and_strings(self):
        out = list(parse_influx_line('disk,path=/var\\ log value=1.5,label="x" 1600000000000000000'))
        assert len(out) == 1
        assert out[0][1]["path"] == "/var log"

    def test_influx_to_batch_ingestable(self):
        batch = influx_to_batch(
            ["cpu,host=a value=1 1600000000000000000", "cpu,host=b value=2 1600000001000000000"],
            default_ts_ms=BASE,
        )
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), range(2))
        assert ms.ingest_routed("ds", batch, spread=1) == 2

    def test_prom_text(self):
        text = """# HELP http_requests_total total
# TYPE http_requests_total counter
http_requests_total{method="get",code="200"} 1027 1600000000000
http_requests_total{method="post"} 3
# TYPE temp gauge
temp 36.6
"""
        out = list(parse_prom_text(text))
        assert len(out) == 3
        assert out[0] == ("http_requests_total", {"method": "get", "code": "200"}, 1_600_000_000_000, 1027.0, "counter")
        assert out[2][4] == "gauge"

    def test_prom_text_to_batches_schema_split(self):
        text = "# TYPE c counter\nc 5\ng 1\n"
        batches = prom_text_to_batches(text, BASE)
        names = {b.schema.name for b in batches}
        assert names == {"gauge", "prom-counter"}


class TestCsvStream:
    def test_csv_roundtrip(self, tmp_path):
        p = tmp_path / "data.csv"
        lines = [f"cpu,host=h{i % 3},{BASE + i * 1000},{float(i)}" for i in range(100)]
        p.write_text("\n".join(lines))
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        pipe = IngestionPipeline(ms, "ds", 0, CsvStream(str(p), batch_size=30))
        n = pipe.run()
        assert n == 100
        assert ms.shard("ds", 0).num_partitions == 3

    def test_csv_replay_from_offset(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("\n".join(f"m,,{BASE + i * 1000},{i}" for i in range(50)))
        got = []
        for off, batch in CsvStream(str(p), batch_size=10).batches(from_offset=30):
            got.extend(batch.timestamps.tolist())
        assert len(got) == 20


class TestDownsample:
    def test_downsample_samples_math(self):
        ts = BASE + np.arange(100, dtype=np.int64) * 10_000  # 10s over ~16m
        vals = np.arange(100, dtype=np.float64)
        out_ts, cols = downsample_samples(ts, vals, 300_000)  # 5m periods
        assert (np.diff(out_ts) == 300_000).all()
        # first full period: samples within [aligned_start, +5m)
        period0 = ts // 300_000 == ts[0] // 300_000
        np.testing.assert_allclose(cols["sum"][0], vals[period0].sum())
        np.testing.assert_allclose(cols["min"][0], vals[period0].min())
        np.testing.assert_allclose(cols["count"][0], period0.sum())

    def test_ingest_time_downsampler(self):
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        dsm = TimeSeriesMemStore()
        dsm.setup(Dataset("ds_5m", schemas=[DS_GAUGE]), [0])
        dsm.setup(Dataset("ds_60m", schemas=[DS_GAUGE]), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=2, n_samples=400, start_ms=BASE))
        shard = ms.shard("ds", 0)
        d = ShardDownsampler(dsm, "ds")
        for part in shard.partitions.values():
            part.switch_buffers()
            d.downsample_chunks(0, part, part.chunks)
        ds_shard = dsm.shard("ds_5m", 0)
        assert ds_shard.num_partitions == 2
        part = ds_shard.partitions[0]
        ts, avg = part.samples_in_range(0, 2**62, "avg")
        assert len(ts) >= 12  # 400 samples @10s ≈ 67m -> ≥12 5m periods
        _, mins = part.samples_in_range(0, 2**62, "min")
        _, maxs = part.samples_in_range(0, 2**62, "max")
        assert (mins <= maxs).all()

    def test_batch_downsample_from_store(self, tmp_path):
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=2, n_samples=300, start_ms=BASE))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        dsm = TimeSeriesMemStore()
        dsm.setup(Dataset("ds_5m", schemas=[DS_GAUGE]), [0])
        dsm.setup(Dataset("ds_60m", schemas=[DS_GAUGE]), [0])
        d = ShardDownsampler(dsm, "ds")
        n = batch_downsample(store, ms, "ds", [0], dsm, d)
        assert n > 0
        assert dsm.shard("ds_5m", 0).num_partitions == 2

    def test_batch_downsample_process_pool_parity(self, tmp_path):
        """The Spark-executor analog: the process-pool path produces exactly
        the in-process results, shard for shard."""
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0, 1])
        for s in (0, 1):
            ms.ingest("ds", s, machine_metrics(n_series=3, n_samples=300, start_ms=BASE, seed=s))
            FlushCoordinator(ms, store).flush_shard("ds", s)

        def run(processes):
            dsm = TimeSeriesMemStore()
            dsm.setup(Dataset("ds_5m", schemas=[DS_GAUGE]), [0, 1])
            dsm.setup(Dataset("ds_60m", schemas=[DS_GAUGE]), [0, 1])
            d = ShardDownsampler(dsm, "ds")
            n = batch_downsample(store, ms, "ds", [0, 1], dsm, d, processes=processes)
            return n, dsm

        n_seq, dsm_seq = run(0)
        n_par, dsm_par = run(2)
        assert n_par == n_seq > 0
        for s in (0, 1):
            sh_a, sh_b = dsm_seq.shard("ds_5m", s), dsm_par.shard("ds_5m", s)
            assert sh_a.num_partitions == sh_b.num_partitions
            for part in sh_a.partitions.values():
                pid_b = sh_b._by_partkey[part.partkey]
                ts_a, v_a = part.samples_in_range(0, 2**62, "avg")
                ts_b, v_b = sh_b.partitions[pid_b].samples_in_range(0, 2**62, "avg")
                np.testing.assert_array_equal(ts_a, ts_b)
                np.testing.assert_allclose(v_a, v_b)


class TestTornWrites:
    def test_truncated_segment_reads_prefix(self, tmp_path):
        """A crash mid-append must not lose previously flushed chunks nor
        crash recovery (reference torn-write tolerance)."""
        import os

        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=3, n_samples=250, start_ms=BASE))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        full = list(store.read_chunks("ds", 0))
        assert len(full) == 9
        # truncate the largest segment mid-frame
        d = os.path.join(str(tmp_path), "ds", "shard-0")
        seg = max(
            (os.path.join(d, f) for f in os.listdir(d) if f.startswith("chunks-")),
            key=os.path.getsize,
        )
        size = os.path.getsize(seg)
        with open(seg, "r+b") as f:
            f.truncate(size - 37)
        after = list(store.read_chunks("ds", 0))
        assert 0 < len(after) < len(full)
        # recovery still works on the remaining data
        ms2 = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms2.setup(Dataset("ds"), [0])
        recover_shard(ms2, store, "ds", 0)
        assert ms2.shard("ds", 0).num_partitions == 3

    def test_garbage_segment_ignored(self, tmp_path):
        import os

        store = LocalColumnStore(str(tmp_path))
        d = store._shard_dir("ds", 0)
        with open(os.path.join(d, "chunks-g0.seg"), "wb") as f:
            f.write(b"\xde\xad\xbe\xef" * 100)
        assert list(store.read_chunks("ds", 0)) in ([], list(store.read_chunks("ds", 0)))


class TestHistogramDownsample:
    def test_hist_downsample_hlast_and_quantile(self):
        from filodb_tpu.coordinator.planners import DownsampleClusterPlanner
        from filodb_tpu.query.exec.plans import QueryContext
        from filodb_tpu.query.promql import query_range_to_logical_plan
        from filodb_tpu.testkit import histogram_batch

        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=120))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, histogram_batch(n_series=2, n_samples=400, start_ms=BASE))
        d = ShardDownsampler(ms, "ds", periods_ms=(300_000,))
        sh = ms.shard("ds", 0)
        for part in list(sh.partitions.values()):
            part.switch_buffers()
            n = d.downsample_chunks(0, part, part.chunks)
            assert n > 0
        ds_shard = ms.shard("ds_5m", 0)
        assert ds_shard.num_partitions == 2
        part = ds_shard.partitions[0]
        assert part.schema.name == "prom-histogram"
        ts, h = part.samples_in_range(0, 2**62, "h")
        assert h.ndim == 2 and len(ts) >= 12
        # cumulative: hLast values are non-decreasing over periods
        assert (np.diff(h[:, -1]) >= 0).all()
        # quantile query against the downsample dataset works end-to-end
        planner = DownsampleClusterPlanner(ms, "ds_5m")
        plan = query_range_to_logical_plan(
            "histogram_quantile(0.9, rate(http_request_latency[10m]))",
            (BASE + 900_000) / 1000, (BASE + 3_600_000) / 1000, 300)
        res = planner.materialize(plan).execute(QueryContext(ms, "ds_5m"))
        series = [v for _, _, v in res.all_series()]
        assert len(series) == 2
        for vals in series:
            assert np.isfinite(vals).all() and (vals > 0).all()


class TestJsonlTail:
    def test_batch_and_replay(self, tmp_path):
        import json

        from filodb_tpu.gateway.tail import JsonlTailStream

        p = tmp_path / "log.jsonl"
        with open(p, "w") as f:
            for i in range(100):
                f.write(json.dumps({"metric": "m", "tags": {"h": str(i % 4)},
                                    "ts_ms": BASE + i * 1000, "value": float(i)}) + "\n")
        stream = JsonlTailStream(str(p), batch_lines=30)
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        pipe = IngestionPipeline(ms, "ds", 0, stream)
        assert pipe.run() == 100
        assert ms.shard("ds", 0).num_partitions == 4
        # replay from offset 60: 40 rows
        got = sum(len(b) for _, b in stream.batches(from_offset=60))
        assert got == 40

    def test_follow_sees_appends(self, tmp_path):
        import json
        import threading
        import time as _t

        from filodb_tpu.gateway.tail import JsonlTailStream

        p = tmp_path / "grow.jsonl"
        p.write_text("")
        stop_flag = []

        def writer():
            with open(p, "a") as f:
                for i in range(50):
                    f.write(json.dumps({"metric": "m", "tags": {},
                                        "ts_ms": BASE + i * 1000, "value": 1.0}) + "\n")
                    f.flush()
                    _t.sleep(0.005)
            _t.sleep(0.3)
            stop_flag.append(True)

        t = threading.Thread(target=writer)
        t.start()
        stream = JsonlTailStream(str(p), batch_lines=10)
        rows = 0
        for off, batch in stream.follow(stop=lambda: bool(stop_flag)):
            rows += len(batch)
        t.join()
        assert rows == 50


def test_store_format_versioning(tmp_path):
    import os

    from filodb_tpu.store.columnstore import FORMAT_VERSION

    root = str(tmp_path / "s")
    LocalColumnStore(root)
    with open(os.path.join(root, "FORMAT")) as f:
        assert int(f.read()) == FORMAT_VERSION
    # reopening same version is fine
    LocalColumnStore(root)
    # future format refuses
    with open(os.path.join(root, "FORMAT"), "w") as f:
        f.write(str(FORMAT_VERSION + 1))
    with pytest.raises(ValueError, match="format"):
        LocalColumnStore(root)


class TestParserEdges:
    def test_influx_no_timestamp_uses_default(self):
        out = list(parse_influx_line("cpu,host=a value=1.5"))
        assert out == [("cpu", {"host": "a"}, None, 1.5)]
        batch = influx_to_batch(["cpu,host=a value=1.5"], default_ts_ms=BASE)
        assert batch.timestamps[0] == BASE

    def test_prom_nan_value(self):
        out = list(parse_prom_text("m 1\nm2 NaN\n"))
        assert out[0][3] == 1.0
        assert np.isnan(out[1][3])

    def test_influx_bool_and_int_fields(self):
        out = dict((m, v) for m, _, _, v in parse_influx_line(
            "s up=t,down=f,count=42i 1600000000000000000"))
        assert out == {"s_up": 1.0, "s_down": 0.0, "s_count": 42.0}


class TestExemplarParsing:
    def test_label_value_containing_exemplar_marker(self):
        """Review regression: a legal label value containing ' # {' must not
        be mistaken for an exemplar suffix."""
        out = list(parse_prom_text('foo{msg="x # {y} 1"} 5\n'))
        assert out == [("foo", {"msg": "x # {y} 1"}, None, 5.0, "untyped")]

    def test_exemplar_parsed_and_sample_intact(self):
        rows = list(parse_prom_text(
            'http_requests_total{job="api"} 42 1600000000000 '
            '# {trace_id="abc"} 0.67 1600000000.5\n',
            with_exemplars=True,
        ))
        name, tags, ts, val, typ, ex = rows[0]
        assert (name, tags, ts, val) == ("http_requests_total", {"job": "api"}, 1600000000000, 42.0)
        assert ex == ({"trace_id": "abc"}, 0.67, 1600000000500)

    def test_exemplar_without_ts_inherits_nothing(self):
        rows = list(parse_prom_text('m 5 # {t="x"} 1.5\n', with_exemplars=True))
        assert rows[0][5] == ({"t": "x"}, 1.5, None)

    def test_plain_lines_unchanged(self):
        rows = list(parse_prom_text("m 1\nm2 NaN\n"))
        assert len(rows) == 2 and rows[0][:2] == ("m", {})


class TestConcurrentFlush:
    def test_racing_flushes_never_double_write(self, tmp_path):
        """Review regression: concurrent flush cycles (maintenance loop vs
        /admin/flush) must not write the same chunks twice."""
        import json
        import threading

        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=250, start_ms=BASE))
        fc = FlushCoordinator(ms, store)
        threads = [threading.Thread(target=lambda: fc.flush_all("ds")) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        frames = list(store.read_chunks("ds", 0))
        # 4 series x 3 chunks (2 sealed + the 50-tail sealed at flush)
        starts = [(json.dumps(h["tags"], sort_keys=True), h["start"]) for h, _, _ in frames]
        assert len(starts) == len(set(starts)) == 12, "duplicate frames written"
