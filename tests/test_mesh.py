"""Distributed mesh execution tests on the 8-device virtual CPU mesh
(model: reference multi-jvm specs — single-host stand-in for the cluster)."""

import numpy as np
import pytest

import jax

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.staging import stage_series
from filodb_tpu.parallel import mesh as M

import oracle

BASE = 1_600_000_000_000


def make_shard_blocks(n_shards=8, series_per_shard=5, n=200, seed=0):
    rng = np.random.default_rng(seed)
    blocks, gids, all_series = [], [], []
    for s in range(n_shards):
        series = []
        for i in range(series_per_shard):
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
            vals = np.cumsum(rng.uniform(0, 10, n))
            series.append((ts, vals))
            all_series.append((s, i, ts, vals))
        blocks.append(stage_series(series, BASE, counter_corrected=True))
        # two global groups: even/odd series index
        gids.append(np.arange(series_per_shard, dtype=np.int32) % 2)
    return blocks, gids, all_series


def test_distributed_sum_rate_matches_oracle():
    mesh = M.make_mesh()
    assert mesh.devices.size == 8
    blocks, gids, all_series = make_shard_blocks()
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size)
    sharded = M.shard_arrays(mesh, *arrays)
    num_steps = K.pad_steps(10)
    start = BASE + 400_000
    out = M.distributed_agg_range(
        mesh, "rate", "sum", *sharded,
        np.int32(start - BASE), np.int32(60_000), np.int32(300_000),
        num_steps, 2, is_counter=True,
    )
    got = np.asarray(out)[:, :10]
    want = np.zeros((2, 10))
    for s, i, ts, vals in all_series:
        r = oracle.range_function("rate", ts, vals, start, 60_000, 10, 300_000, is_counter=True)
        want[i % 2] += np.where(np.isnan(r), 0, r)
    np.testing.assert_allclose(got, want, rtol=1e-3)


@pytest.mark.parametrize("op", ["sum", "count", "avg", "min", "max"])
def test_distributed_ops(op):
    mesh = M.make_mesh()
    blocks, gids, all_series = make_shard_blocks(seed=3)
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size)
    sharded = M.shard_arrays(mesh, *arrays)
    num_steps = K.pad_steps(5)
    start = BASE + 400_000
    out = np.asarray(
        M.distributed_agg_range(
            mesh, "sum_over_time", op, *sharded,
            np.int32(start - BASE), np.int32(60_000), np.int32(300_000),
            num_steps, 2,
        )
    )[:, :5]
    # oracle
    per_series = []
    for s, i, ts, vals in all_series:
        # blocks were staged counter_corrected; sum_over_time sees the
        # corrected-minus-baseline values, so replicate that here
        corr = oracle.correct_counter(vals) - vals[0]
        r = oracle.range_function("sum_over_time", ts, corr, start, 60_000, 5, 300_000)
        per_series.append((i % 2, r))
    want = np.full((2, 5), np.nan)
    for g in range(2):
        rows = np.stack([r for gg, r in per_series if gg == g])
        if op == "sum":
            want[g] = np.nansum(rows, axis=0)
        elif op == "count":
            want[g] = (~np.isnan(rows)).sum(axis=0)
        elif op == "avg":
            want[g] = np.nanmean(rows, axis=0)
        elif op == "min":
            want[g] = np.nanmin(rows, axis=0)
        elif op == "max":
            want[g] = np.nanmax(rows, axis=0)
    np.testing.assert_allclose(out, want, rtol=2e-3, err_msg=op)


def test_sharding_actually_distributes():
    mesh = M.make_mesh()
    blocks, gids, _ = make_shard_blocks()
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size)
    sharded = M.shard_arrays(mesh, *arrays)
    ts = sharded[0]
    assert len(ts.sharding.device_set) == 8


def test_distributed_jitter_sum_rate_matches_oracle():
    """Jittered scrape grids over the mesh: harmonized common nominal grid +
    the jitter MXU kernel inside shard_map must match the per-series oracle
    exactly (ops/mxu_jitter.py via parallel/exec._run_jitter plumbing)."""
    from filodb_tpu.ops.mxu_jitter import JitterWindowMatrices
    from filodb_tpu.ops.staging import TS_PAD, harmonize_nominal

    mesh = M.make_mesh()
    rng = np.random.default_rng(7)
    n, n_shards, per = 200, 8, 5
    blocks, gids, all_series = [], [], []
    for s in range(n_shards):
        series = []
        for i in range(per):
            dev = np.rint(rng.uniform(-0.2, 0.2, n) * 10_000).astype(np.int64)
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000 + dev
            vals = np.cumsum(rng.uniform(0, 10, n))
            series.append((ts, vals))
            all_series.append((s, i, ts, vals))
        blocks.append(stage_series(series, BASE, counter_corrected=True))
        gids.append(np.arange(per, dtype=np.int32) % 2)
    assert all(b.nominal_ts is not None for b in blocks)
    assert harmonize_nominal(blocks)
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size, with_dev=True)
    sharded = M.shard_arrays(mesh, *arrays[:6])
    from jax.sharding import NamedSharding, PartitionSpec as P

    dev_sh = jax.device_put(arrays[6], NamedSharding(mesh, P("shard", None)))
    num_steps = K.pad_steps(10)
    start = BASE + 400_000
    b0 = blocks[0]
    n_valid = int(np.asarray(b0.lens)[0])
    T_stack = arrays[1].shape[1]
    nominal = np.full(T_stack, TS_PAD, dtype=np.int32)
    nominal[:n_valid] = np.asarray(b0.nominal_ts)[:n_valid]
    wm = JitterWindowMatrices(nominal, n_valid, b0.maxdev_ms,
                              start - BASE, 60_000, num_steps, 300_000)
    assert wm.ok
    ts_a, vals_a, lens_a, base_a, raw_a, gids_a = sharded
    out = M.distributed_agg_range_jitter(
        mesh, "rate", "sum", vals_a, raw_a, dev_sh, lens_a, gids_a,
        wm.d_W0, wm.d_SEL, wm.d_idx,
        wm.d_count0, wm.d_c0pos, wm.d_c0ge2, wm.d_has_klo, wm.d_has_khi,
        wm.d_F0_rel, wm.d_L0_rel, wm.d_L2_rel, wm.d_Klo_rel, wm.d_Khi_rel,
        wm.d_blo_rel, wm.d_ehi_rel,
        np.float32(300_000), 2, is_counter=True,
    )
    got = np.asarray(out)[:, :10]
    want = np.zeros((2, 10))
    for s, i, ts, vals in all_series:
        r = oracle.range_function("rate", ts, vals, start, 60_000, 10, 300_000,
                                  is_counter=True)
        want[i % 2] += np.where(np.isnan(r), 0, r)
    np.testing.assert_allclose(got, want, rtol=1e-3)
