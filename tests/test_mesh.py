"""Distributed mesh execution tests on the 8-device virtual CPU mesh
(model: reference multi-jvm specs — single-host stand-in for the cluster)."""

import numpy as np
import pytest

import jax

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.staging import stage_series
from filodb_tpu.parallel import mesh as M

import oracle

BASE = 1_600_000_000_000


def make_shard_blocks(n_shards=8, series_per_shard=5, n=200, seed=0):
    rng = np.random.default_rng(seed)
    blocks, gids, all_series = [], [], []
    for s in range(n_shards):
        series = []
        for i in range(series_per_shard):
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
            vals = np.cumsum(rng.uniform(0, 10, n))
            series.append((ts, vals))
            all_series.append((s, i, ts, vals))
        blocks.append(stage_series(series, BASE, counter_corrected=True))
        # two global groups: even/odd series index
        gids.append(np.arange(series_per_shard, dtype=np.int32) % 2)
    return blocks, gids, all_series


def test_distributed_sum_rate_matches_oracle():
    mesh = M.make_mesh()
    assert mesh.devices.size == 8
    blocks, gids, all_series = make_shard_blocks()
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size)
    sharded = M.shard_arrays(mesh, *arrays)
    num_steps = K.pad_steps(10)
    start = BASE + 400_000
    out = M.distributed_agg_range(
        mesh, "rate", "sum", *sharded,
        np.int32(start - BASE), np.int32(60_000), np.int32(300_000),
        num_steps, 2, is_counter=True,
    )
    got = np.asarray(out)[:, :10]
    want = np.zeros((2, 10))
    for s, i, ts, vals in all_series:
        r = oracle.range_function("rate", ts, vals, start, 60_000, 10, 300_000, is_counter=True)
        want[i % 2] += np.where(np.isnan(r), 0, r)
    np.testing.assert_allclose(got, want, rtol=1e-3)


@pytest.mark.parametrize("op", ["sum", "count", "avg", "min", "max"])
def test_distributed_ops(op):
    mesh = M.make_mesh()
    blocks, gids, all_series = make_shard_blocks(seed=3)
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size)
    sharded = M.shard_arrays(mesh, *arrays)
    num_steps = K.pad_steps(5)
    start = BASE + 400_000
    out = np.asarray(
        M.distributed_agg_range(
            mesh, "sum_over_time", op, *sharded,
            np.int32(start - BASE), np.int32(60_000), np.int32(300_000),
            num_steps, 2,
        )
    )[:, :5]
    # oracle
    per_series = []
    for s, i, ts, vals in all_series:
        # blocks were staged counter_corrected; sum_over_time sees the
        # corrected-minus-baseline values, so replicate that here
        corr = oracle.correct_counter(vals) - vals[0]
        r = oracle.range_function("sum_over_time", ts, corr, start, 60_000, 5, 300_000)
        per_series.append((i % 2, r))
    want = np.full((2, 5), np.nan)
    for g in range(2):
        rows = np.stack([r for gg, r in per_series if gg == g])
        if op == "sum":
            want[g] = np.nansum(rows, axis=0)
        elif op == "count":
            want[g] = (~np.isnan(rows)).sum(axis=0)
        elif op == "avg":
            want[g] = np.nanmean(rows, axis=0)
        elif op == "min":
            want[g] = np.nanmin(rows, axis=0)
        elif op == "max":
            want[g] = np.nanmax(rows, axis=0)
    np.testing.assert_allclose(out, want, rtol=2e-3, err_msg=op)


def test_sharding_actually_distributes():
    mesh = M.make_mesh()
    blocks, gids, _ = make_shard_blocks()
    arrays = M.stack_blocks_for_mesh(blocks, gids, mesh.devices.size)
    sharded = M.shard_arrays(mesh, *arrays)
    ts = sharded[0]
    assert len(ts.sharding.device_set) == 8
