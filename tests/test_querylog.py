"""Query observatory (doc/observability.md "Query observatory"):
exemplar-level query-log ring, per-phase latency decomposition, the
`_system` round trip for phase quantiles through the fused path, and the
SLO burn-rate recording rules updating from live traffic.

Contracts pinned here:

- ring bounds + concurrency (record-vs-resize race, newest-first, by-id
  index eviction);
- the phase-sum invariant: the engine-phase sum equals engine wall time
  by construction (``other`` is the computed residual);
- the warm canonical query stays exactly ONE kernel dispatch with
  query-log capture enabled, and the per-query record is metadata-only;
- shed (429) and errored queries leave records too (status shed|error);
- the slow-query ring links to the query log by query_id (one execution,
  two views — never disjoint surfaces);
- "p99 render phase" and "p99 latency by tenant" answer through the
  fused path from ``_system`` (classic-bucket histogram_quantile);
- the default SLO rules register and demonstrably update from live
  traffic end-to-end (FiloServer, config-gated).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.api.http import serve_background
from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.metrics import QUERY_PHASES, REGISTRY
from filodb_tpu.obs.querylog import (
    QUERY_LOG,
    PhaseRecorder,
    QueryLogRing,
    promql_fingerprint,
)
from filodb_tpu.testkit import counter_batch

pytestmark = pytest.mark.observability

BASE = 1_600_000_000_000
N_SAMPLES = 240
START_S = (BASE + 600_000) / 1000
END_S = (BASE + 1_800_000) / 1000
Q = "sum by (job) (rate(http_requests_total[5m]))"


def _make_engine(n_shards=4, n_series=24, **params):
    ms = TimeSeriesMemStore(StoreConfig())
    ms.setup(Dataset("ds"), list(range(n_shards)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=n_series, n_samples=N_SAMPLES,
                            start_ms=BASE),
        spread=3,
    )
    return ms, QueryEngine(ms, "ds", PlannerParams(**params))


def _dispatch_total() -> int:
    total = 0
    with REGISTRY._lock:
        for (name, _labels), m in REGISTRY._metrics.items():
            if name == "filodb_kernel_dispatch_seconds":
                total += m.total
    return total


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# PhaseRecorder


class TestPhaseRecorder:
    def test_unknown_phase_rejected(self):
        rec = PhaseRecorder()
        with pytest.raises(ValueError, match="unknown query phase"):
            rec.add("reticulate", 0.1)

    def test_accumulates_and_clamps(self):
        rec = PhaseRecorder()
        rec.add("stage", 0.1)
        rec.add("stage", 0.2)
        rec.add("render", -5.0)  # negative clock skew clamps to 0
        snap = rec.snapshot()
        assert snap["stage"] == pytest.approx(0.3)
        assert snap["render"] == 0.0
        assert rec.total() == pytest.approx(0.3)

    def test_context_manager(self):
        rec = PhaseRecorder()
        with rec.phase("parse_plan"):
            time.sleep(0.01)
        assert rec.snapshot()["parse_plan"] >= 0.005

    def test_canonical_set_matches_metrics(self):
        # the recorder's set IS metrics.QUERY_PHASES (one taxonomy)
        for p in QUERY_PHASES:
            PhaseRecorder().add(p, 0.0)


# ---------------------------------------------------------------------------
# ring bounds + concurrency


class TestQueryLogRing:
    @staticmethod
    def _entry(i: int) -> dict:
        return {"id": f"q{i}", "phases_ms": {}, "time": i}

    def test_bounds_and_newest_first(self):
        ring = QueryLogRing(max_entries=8)
        for i in range(20):
            ring.record(self._entry(i))
        assert len(ring) == 8
        ids = [e["id"] for e in ring.entries()]
        assert ids == [f"q{i}" for i in range(19, 11, -1)]
        # evicted ids leave the index too
        assert ring.get("q0") is None
        assert ring.get("q19")["id"] == "q19"

    def test_limit_pages(self):
        ring = QueryLogRing(max_entries=16)
        for i in range(10):
            ring.record(self._entry(i))
        assert [e["id"] for e in ring.entries(limit=3)] == ["q9", "q8", "q7"]

    def test_entries_are_copies(self):
        ring = QueryLogRing()
        ring.record({"id": "a", "phases_ms": {"stage": 1.0}})
        got = ring.get("a")
        got["phases_ms"]["stage"] = 999.0
        got["id"] = "tampered"
        assert ring.get("a")["phases_ms"]["stage"] == 1.0

    def test_concurrent_record_vs_configure_resize(self):
        ring = QueryLogRing(max_entries=8)
        errors: list = []
        stop = threading.Event()

        def recorder(tid: int):
            try:
                i = 0
                while not stop.is_set():
                    ring.record({"id": f"t{tid}-{i}", "phases_ms": {}})
                    i += 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def resizer():
            try:
                while not stop.is_set():
                    for n in (4, 64, 1, 16):
                        ring.configure(n)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=recorder, args=(t,))
                   for t in range(3)] + [threading.Thread(target=resizer)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=2)
        assert not errors
        ring.configure(16)
        assert len(ring) <= 16
        # the ring stays internally consistent: every listed id resolves
        for e in ring.entries():
            assert ring.get(e["id"]) is not None

    def test_finish_serving_first_wins(self):
        ring = QueryLogRing()
        e = ring.record({"id": "x", "dataset": "ds", "phases_ms": {},
                         "ws": "unknown", "ns": "unknown"})
        ring.finish_serving(e, 0.1, 0.2, body_bytes=10, code=200)
        ring.finish_serving(e, 9.0, 9.0, body_bytes=99, code=500)
        got = ring.get("x")
        assert got["phases_ms"]["render"] == pytest.approx(200.0)
        assert got["result"]["bytes"] == 10
        assert got["code"] == 200


# ---------------------------------------------------------------------------
# engine capture


class TestEngineCapture:
    def test_record_schema_and_phase_sum_invariant(self):
        _ms, eng = _make_engine()
        res = eng.query_range(Q, START_S, END_S, 60)
        rec = res.query_log
        assert rec["path"] == "fused"
        assert rec["fallback_reason"] is None
        assert rec["grid_class"] == "regular"
        assert rec["status"] == "ok"
        assert rec["stats"]["series_scanned"] == 24
        assert rec["result"]["series"] >= 1
        # engine-phase sum == wall time (``other`` is the residual);
        # tolerance covers the 3-decimal per-phase rounding only
        ph = rec["phases_ms"]
        assert set(ph) <= set(QUERY_PHASES)
        assert {"parse_plan", "admission", "stage", "dispatch"} <= set(ph)
        assert sum(ph.values()) == pytest.approx(rec["duration_ms"],
                                                 abs=0.05)
        assert QUERY_LOG.get(rec["id"]) is not None

    def test_fingerprint_normalizes_live_edge(self):
        # same panel, sliding end, same span/step -> ONE fingerprint;
        # different step -> different
        a = promql_fingerprint("ds", Q, 60_000, 1_200_000)
        b = promql_fingerprint("ds", Q, 60_000, 1_200_000)
        c = promql_fingerprint("ds", Q, 15_000, 1_200_000)
        assert a == b != c
        _ms, eng = _make_engine()
        r1 = eng.query_range(Q, START_S, START_S + 600, 60).query_log
        r2 = eng.query_range(Q, START_S + 60, START_S + 660, 60).query_log
        assert r1["fingerprint"] == r2["fingerprint"]
        assert r1["id"] != r2["id"]

    def test_warm_query_single_dispatch_with_capture(self):
        """Acceptance: the warm canonical query is exactly ONE kernel
        dispatch with query-log capture enabled, and the record is
        metadata-only (cache hit, zero bytes staged)."""
        _ms, eng = _make_engine()
        eng.query_range(Q, START_S, END_S, 60)  # stage + compile
        before = _dispatch_total()
        res = eng.query_range(Q, START_S, END_S, 60)
        assert _dispatch_total() - before == 1
        rec = res.query_log
        assert rec["path"] == "fused"
        assert rec["stats"]["cache_hits"] == 1
        assert rec["stats"]["bytes_staged"] == 0

    def test_shed_query_records_status(self):
        from filodb_tpu.query.scheduler import (
            AdmissionController, AdmissionRejected,
        )

        _ms, eng = _make_engine()
        eng.planner.params.admission = AdmissionController(
            {"*": {"rate": 0.0001, "burst": 1}}
        )
        eng.query_range(Q, START_S, END_S, 60)  # takes the only token
        with pytest.raises(AdmissionRejected):
            eng.query_range(Q, START_S + 1, END_S, 60)
        shed = [e for e in QUERY_LOG.entries(limit=4)
                if e["status"] == "shed"]
        assert shed and shed[0]["promql"] == Q
        assert "AdmissionRejected" in shed[0]["error"]

    def test_error_query_records_status(self):
        from filodb_tpu.query.exec.transformers import QueryError

        _ms, eng = _make_engine(max_series=2)
        with pytest.raises(QueryError):
            eng.query_range(Q, START_S, END_S, 60)
        err = [e for e in QUERY_LOG.entries(limit=4)
               if e["status"] == "error"]
        assert err and "QueryError" in err[0]["error"]

    def test_fallback_path_annotated(self):
        _ms, eng = _make_engine()
        res = eng.query_range(Q, START_S, END_S, 60,
                              allow_partial_results=True)
        rec = res.query_log
        assert rec["path"] == "fallback"
        assert rec["fallback_reason"] == "partial_results"


# ---------------------------------------------------------------------------
# HTTP edge: endpoints, serving phases, slow-query link


class TestHttpEdge:
    @pytest.fixture()
    def server(self):
        _ms, eng = _make_engine(slow_query_threshold_s=0.0)
        srv, port = serve_background(eng, port=0)
        yield eng, port
        srv.shutdown()

    def test_profile_round_trip_and_serving_phases(self, server):
        _eng, port = server
        base = f"http://127.0.0.1:{port}"
        _get_json(f"{base}/api/v1/query_range?query="
                  + urllib.parse.quote(Q)
                  + f"&start={START_S}&end={END_S}&step=60")
        # the edge folds its serving phases into the ring entry AFTER the
        # response body goes out (render time is measured around the send),
        # so a fast follow-up read can land in that window — retry briefly
        rec = None
        for _ in range(50):
            entries = _get_json(f"{base}/debug/querylog?limit=1")["data"]
            assert len(entries) == 1
            rec = entries[0]
            if "transfer" in rec["phases_ms"]:
                break
            time.sleep(0.02)
        assert "transfer" in rec["phases_ms"] and "render" in rec["phases_ms"]
        assert rec["code"] == 200
        assert rec["result"]["bytes"] > 0
        prof = _get_json(f"{base}/api/v1/query_profile?id={rec['id']}")
        assert prof["data"]["id"] == rec["id"]

    def test_profile_unknown_id_404(self, server):
        _eng, port = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"http://127.0.0.1:{port}/api/v1/query_profile?id=nope")
        assert ei.value.code == 404

    def test_slow_query_links_to_profile(self, server):
        """Satellite: /debug/slow_queries entries join the query log by
        query_id + profile link instead of being a disjoint surface."""
        _eng, port = server
        base = f"http://127.0.0.1:{port}"
        _get_json(f"{base}/api/v1/query_range?query="
                  + urllib.parse.quote(Q)
                  + f"&start={START_S}&end={END_S}&step=60")
        slow = _get_json(f"{base}/debug/slow_queries")["data"]
        assert slow, "threshold 0 must slow-log every query"
        entry = slow[0]
        assert entry["query_id"]
        assert entry["profile"] == f"/api/v1/query_profile?id={entry['query_id']}"
        prof = _get_json(base + entry["profile"])["data"]
        assert prof["id"] == entry["query_id"]
        assert prof["promql"] == entry["promql"]

    def test_http_responses_counted(self, server):
        _eng, port = server
        base = f"http://127.0.0.1:{port}"
        before = REGISTRY.counter("filodb_http_responses", code="200",
                                  **{"class": "2xx"}).value
        _get_json(f"{base}/api/v1/query?query="
                  + urllib.parse.quote("vector(1)") + "&time=100")
        after = REGISTRY.counter("filodb_http_responses", code="200",
                                 **{"class": "2xx"}).value
        assert after == before + 1


# ---------------------------------------------------------------------------
# the _system round trip: phase quantiles through the fused path


class TestSystemRoundTrip:
    def test_p99_phases_by_tenant_through_fused_path(self):
        """`histogram_quantile(0.99, sum by (le) (rate(
        filodb_query_phase_seconds_bucket{phase="render"}[5m])))` and the
        per-tenant latency p99 both answer from _system THROUGH the fused
        path (the classic-bucket quantile rides one by-(le) agg
        dispatch)."""
        from filodb_tpu.telemetry import SYSTEM_DATASET, SelfScraper

        ms, eng = _make_engine(n_shards=2, n_series=8)
        ms.setup(Dataset(SYSTEM_DATASET), list(range(2)))
        sys_eng = QueryEngine(ms, SYSTEM_DATASET, PlannerParams())
        scraper = SelfScraper(ms, interval_s=3600)
        now = int(time.time() * 1000)
        for k in range(6):
            res = eng.query_range(Q, START_S + k, END_S, 60)
            # the render/transfer phases an HTTP edge would observe
            QUERY_LOG.finish_serving(res.query_log, 0.001, 0.002,
                                     body_bytes=100, code=200)
            scraper.scrape_once(now_ms=now + k * 15_000)
        for promql in (
            'histogram_quantile(0.99, sum by (le) (rate('
            'filodb_query_phase_seconds_bucket{phase="render"}[5m])))',
            'histogram_quantile(0.99, sum by (le) (rate('
            'filodb_tenant_query_latency_seconds_bucket'
            '{ws="unknown"}[5m])))',
        ):
            res = sys_eng.query_range(promql, (now + 30_000) / 1000,
                                      (now + 75_000) / 1000, 15)
            rec = res.query_log
            assert rec["path"] == "fused", (promql, rec["fallback_reason"])
            assert len(res.grids) == 1
            vals = res.grids[0].values_np()
            assert np.isfinite(vals).any(), promql


# ---------------------------------------------------------------------------
# SLO burn-rate rules


class TestSloRules:
    def test_default_rules_shape(self):
        from filodb_tpu.obs.slo import default_slo_rules

        rules = default_slo_rules()
        names = [r["name"] for r in rules]
        # per window: availability burn + global p99 + global latency burn
        assert len(rules) == 6
        assert "slo:availability:burnrate:5m" in names
        assert "slo:latency:p99:1h" in names
        assert "slo:latency:burnrate:5m" in names
        for r in rules:
            assert r["interval_s"] == 15.0
        # per-tenant objective mints a per-tenant burn rule
        rules_t = default_slo_rules(
            {"latency_objectives_s": {"*": 2.0, "demo/app": 0.5},
             "windows": ["5m"]}
        )
        assert "slo:latency:burnrate:demo_app:5m" in [
            r["name"] for r in rules_t
        ]
        assert any('ws="demo"' in r["expr"] and 'ns="app"' in r["expr"]
                   for r in rules_t)

    def test_objective_validation(self):
        from filodb_tpu.obs.slo import default_slo_rules

        with pytest.raises(ValueError):
            default_slo_rules({"availability_objective": 1.0})
        with pytest.raises(ValueError):
            default_slo_rules({"latency_objectives_s": {"*": 0.0}})

    def test_slo_rules_update_from_live_traffic_e2e(self, tmp_path):
        """Acceptance e2e: FiloServer with telemetry + standing wires the
        _system SLO maintainer; real HTTP traffic drives the latency
        histograms, the self-scrape lands them in _system, and the
        recording rules write burn-rate series back — queryable over the
        same API and listed in /api/v1/rules."""
        from filodb_tpu.server import FiloServer
        from filodb_tpu.telemetry import SYSTEM_DATASET

        srv = FiloServer({
            "dataset": "ds",
            "shards": 2,
            "store_root": str(tmp_path / "store"),
            "telemetry": {"self_scrape_interval_s": 3600},
            "slo": {"interval_s": 15.0, "windows": ["5m"]},
        })
        port = srv.start(port=0)
        try:
            assert srv.system_standing is not None
            assert len(srv.slo_rules) == 3
            srv.memstore.ingest_routed(
                "ds", counter_batch(n_series=6, n_samples=N_SAMPLES,
                                    start_ms=BASE), spread=1,
            )
            base = f"http://127.0.0.1:{port}"
            now = int(time.time() * 1000)
            for k in range(6):
                # live traffic: real API queries (the latency + phase
                # histograms and http response counters this feeds are
                # exactly what the rules quantile over)
                _get_json(f"{base}/api/v1/query_range?query="
                          + urllib.parse.quote(Q)
                          + f"&start={START_S + k}&end={END_S}&step=60")
                assert srv.self_scraper.scrape_once(
                    now_ms=now + k * 15_000) > 0
            # two deterministic evaluations (the maintainer thread also
            # ticks on eval_interval_s in production)
            for sq in srv.slo_rules:
                srv.system_standing.refresh(sq, now_ms=now + 75_000)
                assert sq.last_error is None, (sq.rule_name, sq.last_error)
            for sq in srv.slo_rules:
                srv.system_standing.refresh(sq, now_ms=now + 90_000)
            rules = _get_json(f"{base}/api/v1/rules")["data"]["groups"]
            listed = [r["name"] for g in rules for r in g["rules"]]
            assert "slo:latency:p99:5m" in listed
            assert "slo:availability:burnrate:5m" in listed
            out = _get_json(
                f"{base}/api/v1/query_range?dataset={SYSTEM_DATASET}"
                "&query=" + urllib.parse.quote("slo:latency:p99:5m")
                + f"&start={now / 1000}&end={(now + 95_000) / 1000}&step=15"
            )["data"]
            vals = [float(v) for series in out["result"]
                    for _, v in series["values"] if v != "NaN"]
            assert vals and max(vals) > 0, "p99 rule never recorded"
            out2 = _get_json(
                f"{base}/api/v1/query_range?dataset={SYSTEM_DATASET}"
                "&query=" + urllib.parse.quote("slo:latency:burnrate:5m")
                + f"&start={now / 1000}&end={(now + 95_000) / 1000}&step=15"
            )["data"]
            burn = [float(v) for series in out2["result"]
                    for _, v in series["values"] if v != "NaN"]
            # burn = p99 / objective(2.0): live traffic p99 is well under
            # the objective, so 0 < burn < 1
            assert burn and 0 < max(burn) < 1
        finally:
            srv.stop()
