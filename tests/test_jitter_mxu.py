"""Jittered-grid MXU path (ops/mxu_jitter.py) vs the general kernel path on
the same data — the fast path must be semantically indistinguishable for
arbitrary per-sample timestamp jitter within the staging bound (the window
semantics contract: reference PeriodicSamplesMapper.scala:256)."""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.mxu_jitter import JITTER_FUNCS
from filodb_tpu.ops.staging import stage_series

BASE = 1_600_000_000_000
INTERVAL = 10_000


def jittered_series(n_series=6, n=300, seed=0, counter=False, jitter=0.05):
    """Nominal 10s grid with per-sample uniform jitter of +/- jitter*interval."""
    rng = np.random.default_rng(seed)
    nominal = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL
    out = []
    for i in range(n_series):
        dev = rng.uniform(-jitter, jitter, n) * INTERVAL
        ts = nominal + np.rint(dev).astype(np.int64)
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            k = n // 2 + i
            vals[k:] -= vals[k] - rng.uniform(0, 5)  # a reset per series
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        out.append((ts, vals))
    return out


def run_path(func, series, counter, force_general, window_ms=300_000,
             diff=False):
    block = stage_series(
        series, BASE, counter_corrected=counter and not diff, diff_encode=diff
    )
    assert block.nominal_ts is not None, "staging must detect the jittered grid"
    assert block.regular_ts is None
    if force_general:
        block.nominal_ts = None
    params = K.RangeParams(BASE + 400_000, 60_000, 20, window_ms)
    return np.asarray(
        K.run_range_function(
            func, block, params, is_counter=counter or diff
        )
    )[: len(series), :20]


GAUGE_FUNCS = sorted(JITTER_FUNCS - {"rate", "increase", "irate"})
COUNTER_FUNCS = ["rate", "increase", "irate"]


@pytest.mark.parametrize("jitter", [0.01, 0.05, 0.2, 0.3])
@pytest.mark.parametrize("func", GAUGE_FUNCS)
def test_jitter_matches_general_gauge(func, jitter):
    series = jittered_series(seed=3, jitter=jitter)
    fast = run_path(func, series, False, False)
    slow = run_path(func, series, False, True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow), err_msg=func)
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=2e-4, atol=1e-3, err_msg=func)


@pytest.mark.parametrize("jitter", [0.01, 0.05, 0.2, 0.3])
@pytest.mark.parametrize("func", COUNTER_FUNCS)
def test_jitter_matches_general_counter(func, jitter):
    series = jittered_series(seed=4, counter=True, jitter=jitter)
    fast = run_path(func, series, True, False)
    slow = run_path(func, series, True, True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow), err_msg=func)
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=1e-3, atol=1e-3, err_msg=func)


def test_counter_idelta_diff_encoded():
    series = jittered_series(seed=5, counter=True)
    fast = run_path("idelta", series, True, False, diff=True)
    slow = run_path("idelta", series, True, True, diff=True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow))
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=1e-3, atol=1e-3)


def test_boundary_membership_is_exact():
    """Samples sitting exactly ON a window boundary: (lo, hi] semantics must
    survive the certain/uncertain decomposition bit-for-bit."""
    n = 60
    nominal = BASE + (1 + np.arange(n, dtype=np.int64)) * INTERVAL
    # series 0: every 6th sample jittered late to land exactly on a step
    # boundary (in: ts <= out_t); series 1 jittered just past it (out)
    steps = BASE + 400_000 + np.arange(5, dtype=np.int64) * 60_000
    ts0, ts1 = nominal.copy(), nominal.copy()
    for st in steps:
        k = int(np.argmin(np.abs(nominal - st)))
        ts0[k] = st          # exactly on the upper boundary -> in window
        ts1[k] = st + 1      # one ms past -> out of this window
    rng = np.random.default_rng(0)
    series = [(ts0, rng.standard_normal(n)), (ts1, rng.standard_normal(n))]
    fast = run_path("count_over_time", series, False, False)
    slow = run_path("count_over_time", series, False, True)
    np.testing.assert_array_equal(fast, slow)


def test_tiny_window_falls_back():
    """window <= 2*maxdev can't isolate one uncertain slot per boundary;
    the dispatcher must transparently use the general path."""
    series = jittered_series(seed=6, jitter=0.3)
    # maxdev ~3000ms -> window 4000ms < 2*maxdev
    fast = run_path("sum_over_time", series, False, False, window_ms=4_000)
    slow = run_path("sum_over_time", series, False, True, window_ms=4_000)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow))
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=2e-4, atol=1e-3)


def test_staging_detection_bounds():
    """Jitter below half-interval -> nominal grid detected; above -> not."""
    rng = np.random.default_rng(1)
    n = 100
    nominal = BASE + np.arange(n, dtype=np.int64) * INTERVAL

    def mk(jfrac):
        out = []
        for _ in range(4):
            dev = rng.uniform(-jfrac, jfrac, n) * INTERVAL
            out.append((nominal + np.rint(dev).astype(np.int64),
                        rng.standard_normal(n)))
        return stage_series(out, BASE)

    ok = mk(0.2)
    assert ok.nominal_ts is not None and ok.ts_dev is not None
    assert ok.maxdev_ms * 2 < INTERVAL
    too_much = mk(0.9)  # adjacent samples can cross -> no safe nominal grid
    assert too_much.nominal_ts is None


def test_exact_grid_still_uses_exact_path():
    ts = BASE + (1 + np.arange(100, dtype=np.int64)) * INTERVAL
    rng = np.random.default_rng(2)
    series = [(ts.copy(), rng.standard_normal(100)) for _ in range(3)]
    block = stage_series(series, BASE)
    assert block.regular_ts is not None
    assert block.nominal_ts is None


def test_engine_e2e_jittered_mesh_matches_no_mesh():
    """Full path: jittered ingest -> PromQL sum(rate) through QueryEngine
    with a device mesh (jitter MXU mesh kernel) vs the engine without a mesh
    (per-block dispatch) — results must agree."""
    import jax

    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import Dataset, METRIC_TAG, PROM_COUNTER, shard_for
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(11)
    n = 240
    nominal = BASE + np.arange(n, dtype=np.int64) * INTERVAL

    def build():
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        for i in range(40):
            dev = np.rint(rng.uniform(-0.05, 0.05, n) * INTERVAL).astype(np.int64)
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            tags = {METRIC_TAG: "rq_total", "_ws_": "w", "_ns_": "n",
                    "inst": f"h{i}"}
            shard = shard_for(tags, spread=2, num_shards=4)
            ms.shard("prometheus", shard).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, nominal + dev, {"count": vals})
            )
        return ms

    rng = np.random.default_rng(11)
    ms1 = build()
    rng = np.random.default_rng(11)
    ms2 = build()
    start_s = (BASE + 400_000) / 1000
    end_s = (BASE + 2_000_000) / 1000
    q = "sum(rate(rq_total[5m]))"
    e_mesh = QueryEngine(ms1, "prometheus",
                         PlannerParams(mesh=make_mesh(jax.devices()[:1])))
    e_plain = QueryEngine(ms2, "prometheus")
    r1 = e_mesh.query_range(q, start_s, end_s, 60.0)
    r2 = e_plain.query_range(q, start_s, end_s, 60.0)
    v1 = r1.grids[0].values_np()[0]
    v2 = r2.grids[0].values_np()[0]
    np.testing.assert_array_equal(np.isnan(v1), np.isnan(v2))
    m = ~np.isnan(v2)
    np.testing.assert_allclose(v1[m], v2[m], rtol=1e-3)
