"""Replicated shard plane suite (doc/robustness.md "Replicated shard
plane"): placement invariants, ingest fan-out + lag watermarks, breaker/
endpoint-driven replica failover serving bit-equal results, live rebalance
with standing-query handoff, and the chaos scenario — kill a node mid
query-storm with partial results OFF and zero 5xx (make test-replica)."""

import json
import threading
import time
import urllib.request

import pytest

from filodb_tpu.coordinator.cluster import ShardManager, ShardStatus
from filodb_tpu.metrics import REGISTRY
from filodb_tpu.testkit import machine_metrics, replica_cluster

pytestmark = pytest.mark.replica

T0_MS = 1_600_000_000_000
T0_S = T0_MS / 1000.0


def _rows(res):
    """Bit-comparable rows: exact float values, no tolerance."""
    return sorted(
        (tuple(sorted(lbls.items())), tuple(ts), tuple(v))
        for lbls, ts, v in res.all_series()
    )


def _counter(family: str, **labels) -> float:
    want = {k: str(v) for k, v in labels.items()}
    total = 0.0
    for key, val in REGISTRY.counter_samples(family).items():
        inner = key[len(family) + 1 : -1]
        have = dict(p.split("=", 1) for p in inner.split(",") if "=" in p)
        if all(have.get(k) == v for k, v in want.items()):
            total += val
    return total


# -- placement invariants --------------------------------------------------


class TestPlacement:
    def test_replicas_land_on_distinct_nodes(self):
        mgr = ShardManager(8, shards_per_node=4, num_replicas=2)
        for i in range(4):
            mgr.node_joined(f"node-{i}")
        for s in range(8):
            nodes = mgr.mapper.nodes_of(s)
            assert len(nodes) == len(set(nodes)), f"shard {s} doubled a node"
            assert len(nodes) == 2, f"shard {s} under-replicated: {nodes}"
            assert mgr.mapper.node_of(s) == nodes[0]  # primary listed first

    def test_replication_bounded_by_node_count(self):
        mgr = ShardManager(4, shards_per_node=4, num_replicas=3)
        mgr.node_joined("a")
        mgr.node_joined("b")
        for s in range(4):
            nodes = mgr.mapper.nodes_of(s)
            # RF=3 but only 2 nodes: never two replicas on one node
            assert len(nodes) == len(set(nodes)) == 2

    def test_reassign_is_one_batch_assignment(self):
        mgr = ShardManager(8, shards_per_node=8)
        mgr.node_joined("node-a")
        mgr.node_joined("node-b")
        calls = []
        orig = mgr.strategy.assign

        def counting(mapper, nodes, spn):
            calls.append(list(nodes))
            return orig(mapper, nodes, spn)

        mgr.strategy.assign = counting
        mgr.node_left("node-a")
        # the regression: per-shard strategy.assign turned N lost shards
        # into N full passes — losing a node must cost ONE batch call
        assert len(calls) == 1
        for s in range(8):
            assert mgr.mapper.node_of(s) == "node-b"

    def test_dead_node_never_named_after_node_left(self):
        mgr = ShardManager(6, shards_per_node=3, num_replicas=2)
        for i in range(3):
            mgr.node_joined(f"node-{i}")
        mgr.node_left("node-0")
        assert mgr.mapper.shards_of_node("node-0") == []
        assert mgr.mapper.replica_shards_of_node("node-0") == []
        for s in range(6):
            assert "node-0" not in mgr.mapper.replicas_of(s)
            assert mgr.mapper.node_of(s) != "node-0"

    def test_survivor_promoted_in_place_without_reassignment(self):
        mgr = ShardManager(4, shards_per_node=4, num_replicas=2)
        mgr.node_joined("a")
        mgr.node_joined("b")
        for s in range(4):
            mgr.mapper.set_replica(s, "a", ShardStatus.ACTIVE)
            mgr.mapper.set_replica(s, "b", ShardStatus.ACTIVE)
        mgr.node_left("a")
        for s in range(4):
            assert mgr.mapper.node_of(s) == "b"
            assert mgr.mapper.status_of(s) is ShardStatus.ACTIVE
        assert any(e["event"] == "promoted" for e in mgr.recent)

    def test_rebalance_damper_suppresses_bounce(self):
        mgr = ShardManager(4, shards_per_node=4, num_replicas=2,
                           reassignment_damper_s=3600.0)
        mgr.node_joined("a")
        mgr.node_joined("b")
        assert mgr.rebalance(0, "b") is True
        assert mgr.rebalance(0, "a") is False  # inside the damper window
        assert mgr.damper_active(0)
        assert any(e["event"] == "damped" for e in mgr.recent)
        with pytest.raises(ValueError):
            mgr.rebalance(0, "nope")


# -- ingest fan-out + lag watermarks ---------------------------------------


class TestFanout:
    def test_append_fans_to_all_replicas_with_acks(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        try:
            wm_max = int(batch.timestamps.max())
            for s in range(4):
                for node in ("node-0", "node-1"):
                    assert c.plane._acks[(s, node)] == c.plane._seq[s]
                    assert c.plane.lag_watermark(s, node) == wm_max
            # both memstores hold every shard — the fan-out actually landed
            for n in c.nodes.values():
                assert sorted(n.memstore.shard_nums("prometheus")) == [0, 1, 2, 3]
        finally:
            c.stop()

    def test_recovering_replica_filtered_by_watermark(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        try:
            wm = c.plane.lag_watermark(0, "node-1")
            c.manager.mapper.set_replica(0, "node-1", ShardStatus.RECOVERY)
            ep1 = c.nodes["node-1"].endpoint
            # query ends past the watermark: the lagging replica is not a
            # candidate; at/behind the watermark it serves
            assert ep1 not in c.router.candidates(0, end_ms=wm + 1)
            assert ep1 in c.router.candidates(0, end_ms=wm)
            assert ep1 in c.router.candidates(0, end_ms=None)
        finally:
            c.stop()

    def test_down_node_recovery_replays_the_gap(self):
        batch = machine_metrics(n_series=8, n_samples=10)
        c = replica_cluster(batch=batch, n_shards=2)
        try:
            c.plane.set_node_down("node-0")
            late = machine_metrics(n_series=8, n_samples=10,
                                   start_ms=T0_MS + 3_600_000)
            c.plane.append(late)
            wm_new = int(late.timestamps.max())
            assert c.plane.lag_watermark(0, "node-1") == wm_new
            assert c.plane.lag_watermark(0, "node-0") < wm_new

            replayed = c.plane.recover("node-0")
            assert set(replayed) == {0, 1}
            for s in (0, 1):
                assert c.plane.lag_watermark(s, "node-0") == wm_new
                assert c.plane._acks[(s, "node-0")] == c.plane._seq[s]
                assert (c.manager.mapper.replica_status_of(s, "node-0")
                        is ShardStatus.ACTIVE)
        finally:
            c.stop()


# -- replica failover: bit-equal reads -------------------------------------


class TestFailover:
    def test_kill_node_serves_bit_equal_from_survivor(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        try:
            res0 = c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10)
            before = _rows(res0)
            assert before, "baseline query returned nothing"
            c.kill("node-0")
            res1 = c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10)
            assert _rows(res1) == before
        finally:
            c.stop()

    def test_dispatch_layer_failover_on_stale_mapping(self):
        # server dies but the control plane has NOT noticed: the mapper
        # still routes to it. The dispatch layer must re-pin each leg to
        # its sibling replica — counted, and still bit-equal.
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        try:
            before = _rows(
                c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10))
            fo0 = _counter("filodb_replica_failovers", reason="endpoint_failure")
            sib0 = _counter("filodb_replica_selection", which="sibling")
            c.nodes["node-0"].server.stop(grace=0)  # no set_node_down
            res = c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10)
            assert _rows(res) == before
            assert _counter("filodb_replica_failovers",
                            reason="endpoint_failure") > fo0
            assert _counter("filodb_replica_selection", which="sibling") > sib0
        finally:
            c.stop()

    def test_open_breaker_is_a_routing_signal(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        try:
            before = _rows(
                c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10))
            # force every breaker guarding node-0 open: routing must re-pin
            # to the sibling BEFORE allow_partial_results is considered
            ep0 = c.nodes["node-0"].endpoint
            b = c.breakers.breaker_for(ep0)
            for _ in range(b.min_calls):
                b.record_failure()
            assert b.state() == "open"
            fo0 = _counter("filodb_replica_failovers", reason="breaker_open")
            res = c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10)
            assert _rows(res) == before
            assert _counter("filodb_replica_failovers",
                            reason="breaker_open") > fo0
        finally:
            c.stop()


# -- live rebalance + standing handoff -------------------------------------


class TestRebalance:
    def test_rebalance_moves_primary_with_effect_log_proof(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        try:
            before = _rows(
                c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10))
            src = c.manager.mapper.node_of(0)
            dst = "node-1" if src == "node-0" else "node-0"
            outcome = c.plane.rebalance(0, dst)
            assert outcome in ("clean", "replayed", "rebuilt")
            assert c.manager.mapper.node_of(0) == dst
            assert c.manager.mapper.status_of(0) is ShardStatus.ACTIVE
            res = c.engine.query_range("sum(heap_usage0)", T0_S, T0_S + 290, 10)
            assert _rows(res) == before
        finally:
            c.stop()

    def test_standing_query_follows_the_shard(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4, standing=True)
        try:
            spec = c.plane.register_standing("sum(heap_usage0)", 10_000, shard=0)
            old_owner = spec.owner
            old_qid = spec.qid
            assert old_qid is not None
            sq = c.plane.standing_query(spec)
            assert sq is not None
            payload0 = c.nodes[old_owner].standing.refresh(sq, now_ms=T0_MS + 300_000)
            assert payload0

            dst = "node-1" if old_owner == "node-0" else "node-0"
            outcome = c.plane.rebalance(0, dst)
            assert outcome in ("clean", "replayed", "rebuilt")
            assert spec.owner == dst and spec.qid is not None
            # delta refreshes resume on the new owner...
            sq2 = c.plane.standing_query(spec)
            assert sq2 is not None
            payload1 = c.nodes[dst].standing.refresh(sq2, now_ms=T0_MS + 300_000)
            assert payload1
            # ...and the old owner no longer maintains it
            assert c.nodes[old_owner].standing.registry.get(old_qid) is None
        finally:
            c.stop()


# -- admin surface ---------------------------------------------------------


class TestClusterSurface:
    def test_debug_cluster_and_querylog_endpoint(self):
        from filodb_tpu.api.http import serve_background

        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        srv = None
        try:
            srv, port = serve_background(c.engine, port=0,
                                         cluster=c.plane.snapshot)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/cluster", timeout=30) as r:
                snap = json.loads(r.read())["data"]
            assert snap["num_replicas"] == 2
            assert {n["name"] for n in snap["nodes"]} == {"node-0", "node-1"}
            row = snap["shards"][0]
            assert set(row["replicas"]) == {"node-0", "node-1"}
            assert set(row["watermarks_ms"]) == {"node-0", "node-1"}
            assert row["log_seq"] >= 1 and "damper_active" in row

            url = (f"http://127.0.0.1:{port}/api/v1/query_range"
                   f"?query=sum(heap_usage0)&start={T0_MS // 1000}"
                   f"&end={T0_MS // 1000 + 290}&step=10")
            with urllib.request.urlopen(url, timeout=30) as r:
                assert json.loads(r.read())["status"] == "success"
            # the query-log record is folded after the response is sent:
            # retry briefly until OUR query's entry lands in the ring
            entry = None
            for _ in range(100):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/debug/querylog",
                        timeout=30) as r:
                    entries = json.loads(r.read())["data"]
                hits = [e for e in entries
                        if e.get("promql") == "sum(heap_usage0)"
                        and e.get("endpoint")]
                if hits:
                    entry = hits[-1]
                    break
                time.sleep(0.05)
            # the serving endpoint(s) are attributed in the query log and
            # thus in /api/v1/query_profile (same record by id)
            assert entry is not None, entries
            assert "grpc://" in entry["endpoint"]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/v1/query_profile?id={entry['id']}",
                    timeout=30) as r:
                prof = json.loads(r.read())["data"]
            assert prof["endpoint"] == entry["endpoint"]
        finally:
            if srv is not None:
                srv.shutdown()
            c.stop()


# -- chaos: kill a node mid query-storm ------------------------------------


class TestChaosKill:
    def test_node_kill_mid_storm_zero_5xx_partial_off(self):
        batch = machine_metrics(n_series=40, n_samples=30)
        c = replica_cluster(batch=batch, n_shards=4)
        from filodb_tpu.api.http import serve_background

        srv = None
        try:
            assert c.engine.planner.params.allow_partial_results is False
            srv, port = serve_background(c.engine, port=0,
                                         cluster=c.plane.snapshot)
            url = (f"http://127.0.0.1:{port}/api/v1/query_range"
                   f"?query=sum(heap_usage0)&start={T0_MS // 1000}"
                   f"&end={T0_MS // 1000 + 290}&step=10")

            def fetch():
                req = urllib.request.Request(url)
                try:
                    with urllib.request.urlopen(req, timeout=60) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:  # capture, don't raise
                    return e.code, e.read()

            code0, body0 = fetch()
            assert code0 == 200
            baseline = json.loads(body0)["data"]["result"]
            assert baseline

            http5_0 = _counter("filodb_http_responses", **{"class": "5xx"})
            partial0 = _counter("filodb_partial_results")

            n_clients = 16
            results = [[] for _ in range(n_clients)]
            stop_evt = threading.Event()

            def worker(i):
                while not stop_evt.is_set():
                    results[i].append(fetch())

            threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                       for i in range(n_clients)]
            for t in threads:
                t.start()
            # storm is rolling on every client, then the node dies mid-flight
            while not all(len(r) >= 2 for r in results):
                pass
            marks = [len(r) for r in results]
            c.kill("node-0")
            # every client completes several post-kill queries
            while not all(len(r) >= m + 3 for r, m in zip(results, marks)):
                pass
            stop_evt.set()
            for t in threads:
                t.join(timeout=60)

            flat = [x for r in results for x in r]
            assert flat and all(code == 200 for code, _ in flat)
            for _, body in flat:
                # bit-equal across the kill: same rendered samples exactly
                assert json.loads(body)["data"]["result"] == baseline
            assert _counter("filodb_http_responses",
                            **{"class": "5xx"}) == http5_0
            assert _counter("filodb_partial_results") == partial0
        finally:
            if srv is not None:
                srv.shutdown()
            c.stop()
