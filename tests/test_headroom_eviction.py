"""Memory-pressure eviction (reference evictForHeadroom,
TimeSeriesShard.scala:1799 + evicted-partkey BloomFilter :540): sustained
ingest under a small resident-byte budget must stay under the cap, keep
answering queries (via ODP), and never raise MemoryError."""

import numpy as np

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.store.columnstore import LocalColumnStore
from filodb_tpu.store.flush import FlushCoordinator
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000
BUDGET = 256 << 10  # 256 KiB — tiny, forces eviction quickly


def _cfg():
    return StoreConfig(max_chunk_size=100, max_resident_bytes=BUDGET)


class TestHeadroomEviction:
    def test_sustained_ingest_stays_under_cap(self, tmp_path):
        """VERDICT done-criterion: small budget, sustained ingest + flushes;
        residency stays bounded, queries answer via ODP, no MemoryError."""
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        fc = FlushCoordinator(ms, store)
        rounds = 12
        samples_per_round = 200
        for r in range(rounds):
            start = BASE + r * samples_per_round * 10_000
            ms.ingest("ds", 0, machine_metrics(
                n_series=20, n_samples=samples_per_round, start_ms=start))
            fc.flush_shard("ds", 0)
            sh.evict_for_headroom()
            assert sh.resident_bytes() <= BUDGET, f"round {r}: over budget"
        assert sh.stats.headroom_evictions > 0
        assert sh.stats.bytes_reclaimed > 0
        assert len(sh.evicted_keys) > 0  # tier-2 ran
        # queries over the EVICTED (oldest) range still answer through ODP
        engine = QueryEngine(ms, "ds")
        q_start = (BASE + 600_000) / 1000
        q_end = (BASE + 1_500_000) / 1000
        res = engine.query_range("avg(heap_usage0)", q_start, q_end, 60.0)
        vals = res.grids[0].values_np()
        assert np.isfinite(vals).any(), "evicted range unanswerable"
        assert sh.odp_stats_pages > 0

    def test_odp_roundtrip_matches_pre_eviction(self, tmp_path):
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        ms.ingest("ds", 0, machine_metrics(n_series=10, n_samples=400, start_ms=BASE))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        engine = QueryEngine(ms, "ds")
        q_start, q_end = (BASE + 600_000) / 1000, (BASE + 3_900_000) / 1000
        want = engine.query_range("sum(heap_usage0)", q_start, q_end, 60.0).grids[0].values_np().copy()
        freed = sh.evict_for_headroom(target_bytes=0)
        assert freed > 0
        got = engine.query_range("sum(heap_usage0)", q_start, q_end, 60.0).grids[0].values_np()
        np.testing.assert_allclose(got, want, rtol=1e-5, equal_nan=True)

    def test_unflushed_data_never_dropped(self):
        """No ODP store + nothing flushed: tier 2 must not run; data intact."""
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        ms.ingest("ds", 0, machine_metrics(n_series=20, n_samples=400, start_ms=BASE))
        before = sum(p.num_samples() for p in sh.partitions.values())
        sh.evict_for_headroom()
        assert sum(p.num_samples() for p in sh.partitions.values()) == before
        assert len(sh.evicted_keys) == 0

    def test_tier1_drops_decoded_keeps_encoded_queryable(self, tmp_path):
        """Flushed but no ODP store: tier 1 reclaims decoded arrays; queries
        decode from the retained encoded form."""
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)  # odp_store NOT set
        ms.ingest("ds", 0, machine_metrics(n_series=10, n_samples=300, start_ms=BASE))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        engine = QueryEngine(ms, "ds")
        q_start, q_end = (BASE + 600_000) / 1000, (BASE + 2_900_000) / 1000
        want = engine.query_range("avg(heap_usage0)", q_start, q_end, 60.0).grids[0].values_np().copy()
        freed = sh.evict_for_headroom(target_bytes=0)
        assert freed > 0
        # decoded arrays gone from flushed chunks, chunks still present
        n_encoded_only = sum(
            1 for p in sh.partitions.values() for c in p.chunks if c.arrays is None
        )
        assert n_encoded_only > 0
        got = engine.query_range("avg(heap_usage0)", q_start, q_end, 60.0).grids[0].values_np()
        np.testing.assert_allclose(got, want, rtol=1e-5, equal_nan=True)

    def test_retention_keeps_evicted_partitions_queryable(self, tmp_path):
        """Review regression: tier-2-emptied partitions must survive the
        retention pass while their persisted data is within retention —
        otherwise the index entry dies and ODP can never find them."""
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        ms.ingest("ds", 0, machine_metrics(n_series=5, n_samples=300, start_ms=BASE))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        sh.evict_for_headroom(target_bytes=0)   # tier 2 empties flushed chunks
        assert len(sh.evicted_keys) > 0
        # retention pass with "now" well within retention of the data
        sh.evict_for_retention(now_ms=BASE + 3_500_000)
        assert sh.num_partitions == 5, "evicted shells must survive retention"
        engine = QueryEngine(ms, "ds")
        res = engine.query_range(
            "avg(heap_usage0)", (BASE + 600_000) / 1000, (BASE + 2_500_000) / 1000, 60.0
        )
        assert np.isfinite(res.grids[0].values_np()).any()
        # once the data truly ages out, the shells + index entries go too
        sh.update_index_end_times()
        sh.update_index_end_times()  # two cycles: watermark then mark ended
        dropped = sh.evict_for_retention(
            now_ms=BASE + 300 * 10_000 + sh.config.retention_ms + 10_000
        )
        assert sh.num_partitions == 0

    def test_ooo_guard_survives_tier2_eviction(self, tmp_path):
        """Review regression: redelivered old samples must still be rejected
        after the chunk list was reclaimed (high-water mark survives)."""
        from filodb_tpu.core.records import SeriesBatch
        from filodb_tpu.core.schemas import GAUGE, METRIC_TAG

        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        ts = BASE + np.arange(200, dtype=np.int64) * 10_000
        vals = np.linspace(1, 2, 200)
        sb = SeriesBatch(GAUGE, {METRIC_TAG: "m", "instance": "a"}, ts, {"value": vals})
        sh.ingest_series(sb)
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        part = next(iter(sh.partitions.values()))
        sh.evict_for_headroom(target_bytes=0)
        assert part.latest_ts() == int(ts[-1])  # hwm survives reclaim
        # at-least-once redelivery of the SAME batch: all rows rejected
        got = sh.ingest_series(SeriesBatch(GAUGE, {METRIC_TAG: "m", "instance": "a"}, ts, {"value": vals}))
        assert got == 0

    def test_under_budget_is_noop(self):
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        ms.ingest("ds", 0, machine_metrics(n_series=2, n_samples=100, start_ms=BASE))
        assert sh.evict_for_headroom() == 0
        assert sh.stats.headroom_evictions == 0


class TestEvictablePartIdQueueSet:
    """Dedup FIFO of eviction candidates (reference
    EvictablePartIdQueueSet.scala): eviction touches only partitions that
    flushed something, never the whole partition map."""

    def test_offer_dedups_and_reoffer_moves_to_back(self):
        """Head = least-recently-flushed: a hot partition that re-flushes
        migrates away from the eviction front."""
        from filodb_tpu.memstore.shard import EvictablePartIdQueueSet

        q = EvictablePartIdQueueSet()
        for pid in (3, 1, 3, 2, 1):
            q.offer(pid)
        assert q.snapshot() == [3, 2, 1]  # 3 and 1 re-offered -> moved back
        assert len(q) == 3 and 2 in q
        q.remove(1)
        assert q.snapshot() == [3, 2] and 1 not in q

    def test_flush_populates_candidates_and_eviction_consumes(self, tmp_path):
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        fc = FlushCoordinator(ms, store)
        ms.ingest("ds", 0, machine_metrics(n_series=8, n_samples=400, start_ms=BASE))
        assert len(sh.evictable) == 0  # nothing flushed yet
        fc.flush_shard("ds", 0)
        assert len(sh.evictable) == 8  # every flushed partition is a candidate
        # tier-2 eviction to (near) zero: consumed candidates leave the queue
        freed = sh.evict_for_headroom(target_bytes=0)
        assert freed > 0
        assert len(sh.evictable) == 0
        assert len(sh.evicted_keys) == 8

    def test_never_flushed_partitions_are_not_candidates(self):
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        ms.ingest("ds", 0, machine_metrics(n_series=5, n_samples=300, start_ms=BASE))
        # unflushed-only shard: eviction has no candidates and frees nothing
        assert sh.evict_for_headroom(target_bytes=0) == 0
        assert len(sh.evictable) == 0

    def test_recovery_reoffers_candidates(self, tmp_path):
        from filodb_tpu.store.flush import recover_shard

        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(_cfg())
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        fc = FlushCoordinator(ms, store)
        ms.ingest("ds", 0, machine_metrics(n_series=6, n_samples=200, start_ms=BASE))
        fc.flush_shard("ds", 0)
        # fresh store process: recovery must repopulate the candidate set
        ms2 = TimeSeriesMemStore(_cfg())
        ms2.setup(Dataset("ds"), [0])
        recover_shard(ms2, store, "ds", 0)
        sh2 = ms2.shard("ds", 0)
        assert len(sh2.evictable) == 6
        sh2.odp_store = store
        assert sh2.evict_for_headroom(target_bytes=0) > 0
