"""Round-trip tests for chunk codecs (model: reference DoubleVectorTest,
NibblePackTest, HistogramTest under core/src/test/scala/filodb.memory/format/)."""

import numpy as np
import pytest

from filodb_tpu.core import encodings as E


def roundtrip_u64(vals):
    v = np.asarray(vals, dtype=np.uint64)
    packed = E.nibble_pack(v)
    out = E.nibble_unpack(packed, len(v))
    np.testing.assert_array_equal(out, v)
    return packed


class TestNibblePack:
    def test_zeros(self):
        packed = roundtrip_u64(np.zeros(16, dtype=np.uint64))
        assert len(packed) == 2  # one bitmask byte per group of 8

    def test_small_values(self):
        roundtrip_u64([1, 2, 3, 4, 5, 6, 7, 8, 9, 10])

    def test_mixed_zero_nonzero(self):
        roundtrip_u64([0, 5, 0, 1 << 40, 0, 0, 7, 0, 3])

    def test_large_values(self):
        rng = np.random.default_rng(42)
        roundtrip_u64(rng.integers(0, 2**63, 1000, dtype=np.uint64))

    def test_max_u64(self):
        roundtrip_u64([np.uint64(2**64 - 1)] * 9)

    def test_trailing_zero_exploit(self):
        # values with common trailing zeros should compress well
        v = np.arange(8, dtype=np.uint64) << np.uint64(32)
        packed = roundtrip_u64(v)
        assert len(packed) < 8 * 8

    def test_non_multiple_of_8(self):
        for n in [1, 3, 7, 9, 15, 17]:
            roundtrip_u64(np.arange(n, dtype=np.uint64) * 1000)

    def test_empty(self):
        assert E.nibble_unpack(E.nibble_pack(np.array([], dtype=np.uint64)), 0).size == 0


class TestDeltaDelta:
    def test_regular_timestamps_const(self):
        ts = np.arange(0, 720 * 10_000, 10_000, dtype=np.int64) + 1_600_000_000_000
        enc = E.encode_int64(ts)
        assert enc.fmt == E.FMT_CONST_DELTA
        assert enc.nbytes < 30  # base+slope only
        np.testing.assert_array_equal(E.decode(enc), ts)

    def test_jittered_timestamps(self):
        rng = np.random.default_rng(0)
        ts = 1_600_000_000_000 + np.arange(720, dtype=np.int64) * 10_000
        ts += rng.integers(-50, 50, 720)
        enc = E.encode_int64(ts)
        assert enc.fmt == E.FMT_DELTA_DELTA
        np.testing.assert_array_equal(E.decode(enc), ts)
        assert enc.nbytes < 2 * 720  # ~2 bytes/sample for small jitter

    def test_random_walk(self):
        rng = np.random.default_rng(1)
        ts = np.cumsum(rng.integers(-1000, 1000, 500)).astype(np.int64)
        enc = E.encode_int64(ts)
        np.testing.assert_array_equal(E.decode(enc), ts)

    def test_single_and_empty(self):
        np.testing.assert_array_equal(E.decode(E.encode_int64(np.array([42], dtype=np.int64))), [42])
        assert E.decode(E.encode_int64(np.array([], dtype=np.int64))).size == 0

    def test_negative(self):
        ts = np.array([-(10**12), 5, -3, 10**14], dtype=np.int64)
        np.testing.assert_array_equal(E.decode(E.encode_int64(ts)), ts)


class TestDouble:
    def test_integral_promotes(self):
        v = np.arange(100, dtype=np.float64) * 5
        enc = E.encode_double(v)
        assert enc.fmt in (E.FMT_CONST_DELTA, E.FMT_DELTA_DELTA)
        np.testing.assert_array_equal(E.decode_double(enc), v)

    def test_gauge_values(self):
        rng = np.random.default_rng(2)
        v = 50 + 10 * rng.standard_normal(720)
        enc = E.encode_double(v)
        np.testing.assert_array_equal(E.decode_double(enc), v)

    def test_nan_staleness_roundtrip(self):
        v = np.array([1.5, np.nan, 2.5, np.nan, np.nan, 3.0])
        out = E.decode_double(E.encode_double(v))
        np.testing.assert_array_equal(np.isnan(out), np.isnan(v))
        np.testing.assert_array_equal(out[~np.isnan(v)], v[~np.isnan(v)])

    def test_counter_like_compresses(self):
        # slowly increasing counter with repeated values: XOR stream is sparse
        v = np.repeat(np.arange(90, dtype=np.float64) * 1000 + 0.5, 8)
        enc = E.encode_double(v)
        assert enc.nbytes < v.nbytes / 2
        np.testing.assert_array_equal(E.decode_double(enc), v)

    def test_inf_and_extremes(self):
        v = np.array([np.inf, -np.inf, 1e308, -1e-308, 0.0, -0.0])
        out = E.decode_double(E.encode_double(v))
        np.testing.assert_array_equal(out.view(np.uint64), v.view(np.uint64))


class TestHistogram:
    def test_cumulative_hist_roundtrip(self):
        rng = np.random.default_rng(3)
        # cumulative counts over 64 buckets, increasing in time
        incr = rng.poisson(3, size=(50, 64))
        counts = np.cumsum(np.cumsum(incr, axis=1), axis=0).astype(np.int64)
        enc = E.encode_hist(counts)
        np.testing.assert_array_equal(E.decode(enc), counts)
        assert enc.nbytes < counts.nbytes / 4

    def test_hist_single_row(self):
        counts = np.array([[1, 2, 3, 10]], dtype=np.int64)
        np.testing.assert_array_equal(E.decode(E.encode_hist(counts)), counts)


class TestIntPack:
    @pytest.mark.parametrize("vmax,nbits_max", [(1, 1), (3, 2), (15, 4), (200, 8), (60000, 16), (10**9, 32)])
    def test_roundtrip_widths(self, vmax, nbits_max):
        rng = np.random.default_rng(vmax)
        v = rng.integers(0, vmax + 1, 777).astype(np.int64)
        enc = E.encode_int_packed(v)
        assert enc.fmt == E.FMT_INT_PACK
        np.testing.assert_array_equal(E.decode(enc), v)
        assert enc.nbytes <= 777 * max(nbits_max // 8, 1) + 32

    def test_negative_offsets(self):
        v = np.array([-5, -3, -5, 2], dtype=np.int64)
        np.testing.assert_array_equal(E.decode(E.encode_int_packed(v)), v)

    def test_wide_falls_back(self):
        v = np.array([0, 2**60], dtype=np.int64)
        np.testing.assert_array_equal(E.decode(E.encode_int_packed(v)), v)

    def test_empty(self):
        assert E.decode(E.encode_int_packed(np.array([], dtype=np.int64))).size == 0


class TestDictUTF8:
    def test_roundtrip(self):
        strings = ["api", "web", "api", "db", "api", "web"] * 100
        enc = E.encode_utf8_dict(strings)
        assert E.decode_utf8_dict(enc) == strings
        # dictionary encoding beats raw join for repetitive values
        assert enc.nbytes < sum(len(s) for s in strings) / 2

    def test_unicode_and_empty(self):
        strings = ["héllo", "", "日本語", ""]
        assert E.decode_utf8_dict(E.encode_utf8_dict(strings)) == strings


class TestCorruptVectors:
    def test_truncated_payloads_raise_cleanly(self):
        rng = np.random.default_rng(0)
        cases = [
            E.encode_double(50 + rng.standard_normal(200)),
            E.encode_int64(np.cumsum(rng.integers(1, 100, 200)).astype(np.int64)),
            E.encode_hist(np.cumsum(rng.poisson(2, (20, 8)), axis=0).astype(np.int64)),
        ]
        for enc in cases:
            for cut in (1, len(enc.payload) // 2):
                bad = E.Encoded(enc.fmt, enc.n, enc.payload[:cut])
                with pytest.raises(E.CorruptVectorError):
                    E.decode(bad)

    def test_unknown_format(self):
        with pytest.raises(E.CorruptVectorError):
            E.decode(E.Encoded(99, 5, b"xx"))
