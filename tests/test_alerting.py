"""Alerting plane (doc/observability.md "Alerting plane"): rule groups,
the per-labelset pending→firing state machine on the standing engine, and
deduplicated notification fan-out.

Contracts pinned here:

- rule-file schema validation rejects malformed groups with pointed
  messages, and the SHIPPED conf/rules/*.yml files validate;
- the state machine holds ``pending`` until ``for:`` elapses, fires
  exactly at the threshold, resolves silently when a pending labelset
  disappears (never notified → nothing to resolve), and ``keep_firing_for``
  suppresses flaps through short gaps;
- every evaluation writes ``ALERTS{alertname,alertstate,...}`` and
  ``ALERTS_FOR_STATE`` back through the production ingest path, so alert
  state is QUERYABLE and a restarted process rehydrates pending/firing
  without resetting the ``for:`` clock;
- the notifier keeps the Alertmanager timing contract (group_wait /
  group_interval / repeat_interval), deduplicates by grouped fingerprint
  hash (repeated evaluation of the same firing alert → exactly ONE
  delivery), retries with backoff inside a deadline budget, and a dead
  receiver trips the per-receiver circuit breaker;
- the HTTP surfaces are real Prometheus shapes: /api/v1/rules (top-level
  ``groups``, camelCase eval fields, recording AND alerting types, no
  double listing), /api/v1/alerts, POST /api/v1/rules/alert, and
  /debug/querylog?path= filters alert evaluations out;
- the e2e proof: injected 5xx → SLO burn → pending → firing → exactly ONE
  grouped webhook → recovery → resolved notification, with the warm
  canonical query still exactly ONE kernel dispatch while alerting runs.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.metrics import REGISTRY
from filodb_tpu.obs.alerting import (
    ALERT_STATES,
    ALERTS_FOR_STATE_SERIES,
    ALERTS_SERIES,
    AlertingEngine,
    AlertRule,
    RuleFileError,
    expand_template,
    fingerprint,
    load_rule_file,
    parse_rule_groups,
    rfc3339,
)
from filodb_tpu.obs.notify import Notifier, Receiver, _Group
from filodb_tpu.obs.querylog import QUERY_LOG
from filodb_tpu.query.faults import RetryPolicy
from filodb_tpu.standing import StandingEngine
from filodb_tpu.testkit import counter_batch, kernel_dispatch_total

pytestmark = pytest.mark.alerting

BASE = 1_600_000_000_000
INTERVAL = 10_000
N_SAMPLES = 260
EDGE = BASE + N_SAMPLES * INTERVAL  # newest ingested sample
STEP_MS = 15_000
Q = "sum by (job) (rate(http_requests_total[5m]))"


def _setup(**acfg):
    """(memstore, engine, standing, alerting) over one dataset of counter
    series (all job="api"), clock pinned just past the ingest edge."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(4)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=24, n_samples=N_SAMPLES,
                            start_ms=BASE), spread=3,
    )
    eng = QueryEngine(ms, "ds")
    se = StandingEngine(eng, {"default_span_ms": 1_200_000},
                        clock=lambda: (EDGE + 5_000) / 1e3)
    alt = AlertingEngine(se, {"default_interval_s": 15.0, **acfg})
    return ms, eng, se, alt


def _counter(name: str, **labels) -> float:
    m = REGISTRY._metrics.get((name, tuple(sorted(labels.items()))))
    return float(m.value) if m is not None else 0.0


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def _get_status(url: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post_json(url: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# -- rule files ---------------------------------------------------------------


class TestRuleFiles:
    def test_shipped_rule_files_validate(self):
        """The example rule files the dev config loads must stay valid —
        they double as the documented schema reference."""
        slo = load_rule_file("conf/rules/slo.yml")
        assert [g.name for g in slo] == ["slo-burn"]
        assert sorted(r.name for r in slo[0].rules) == [
            "AvailabilityBurnFast", "AvailabilityBurnSlow",
            "LatencyBurnFast",
        ]
        fast = next(r for r in slo[0].rules
                    if r.name == "AvailabilityBurnFast")
        assert fast.for_s == 30.0 and fast.keep_firing_for_s == 30.0
        assert fast.labels == {"severity": "page"}
        assert slo[0].interval_s == 15.0

        plat = load_rule_file("conf/rules/platform.yml")
        assert [g.name for g in plat] == ["platform"]
        assert sorted(r.name for r in plat[0].rules) == [
            "LedgerDrift", "RebalanceFailures", "RecompileStorm",
            "ReplicaWatermarkLag",
        ]

    @pytest.mark.parametrize("doc,frag", [
        ([], "mapping"),
        ({"groups": [], "extra": 1}, "unknown"),
        ({"groups": [{"rules": [{"alert": "A", "expr": "x"}]}]}, "name"),
        ({"groups": [{"name": "g", "rules": []}]}, "non-empty `rules:`"),
        ({"groups": [{"name": "g", "rules": [
            {"alert": "A", "record": "r", "expr": "x"}]}]},
         "exactly one of"),
        ({"groups": [{"name": "g", "rules": [{"expr": "x"}]}]},
         "exactly one of"),
        ({"groups": [{"name": "g", "rules": [
            {"record": "r", "expr": "x", "labels": {"a": "b"}}]}]},
         "labels"),
        ({"groups": [{"name": "g", "rules": [
            {"alert": "A", "expr": "x", "for": True}]}]}, "duration"),
        ({"groups": [{"name": "g", "rules": [
            {"alert": "A", "expr": "x",
             "labels": {"alertname": "B"}}]}]}, "reserved"),
        ({"groups": [{"name": "g", "rules": [
            {"alert": "A", "expr": "x"}, {"alert": "A", "expr": "y"}]}]},
         "duplicate"),
    ])
    def test_schema_errors(self, doc, frag):
        with pytest.raises(RuleFileError) as ei:
            parse_rule_groups(doc, file="t.yml")
        assert frag in str(ei.value)

    def test_durations_and_defaults(self):
        groups = parse_rule_groups({"groups": [{
            "name": "g", "interval": "30s", "rules": [
                {"alert": "A", "expr": "x > 1", "for": "1m",
                 "keep_firing_for": 90},
                {"alert": "B", "expr": "y > 1"},
                {"record": "job:rate", "expr": "rate(z[5m])"},
            ],
        }]})
        g = groups[0]
        assert g.interval_s == 30.0
        a, b, rec = g.rules
        assert a.for_s == 60.0 and a.keep_firing_for_s == 90.0
        assert b.for_s == 0.0 and b.keep_firing_for_s == 0.0
        assert not isinstance(rec, AlertRule) and rec.name == "job:rate"

    def test_expand_template(self):
        lbl = {"job": "api", "shard": "3"}
        assert expand_template("{{ $labels.job }}/{{$labels.shard}}",
                               lbl, 1.5) == "api/3"
        assert expand_template("at {{ $value }}x", lbl, 2.5) == "at 2.5x"
        # unknown label → empty, not a crash and not a literal
        assert expand_template("[{{ $labels.nope }}]", lbl, 0) == "[]"

    def test_fingerprint_ignores_alertstate(self):
        a = {"alertname": "A", "job": "api"}
        assert fingerprint({**a, "alertstate": "pending"}) == \
            fingerprint({**a, "alertstate": "firing"}) == fingerprint(a)
        assert fingerprint(a) != fingerprint({**a, "job": "web"})

    def test_rfc3339(self):
        assert rfc3339(0) == "0001-01-01T00:00:00Z"
        assert rfc3339(1_600_000_000_123) == "2020-09-13T12:26:40.123Z"


# -- state machine ------------------------------------------------------------


def _rule(alt, *, name="HighTraffic", for_="30s", keep="30s",
          group="sm", annotations=None):
    """Register an alert rule whose expr never matches real data — the
    tests drive its state machine with synthetic evaluation vectors."""
    spec = {
        "alert": name, "expr": Q + " > 1e12", "for": for_,
        "keep_firing_for": keep, "labels": {"severity": "page"},
        "annotations": annotations
        or {"summary": "job {{ $labels.job }} at {{ $value }}"},
    }
    return alt.add_rule(spec, group=group)


class TestStateMachine:
    def test_pending_hold_then_firing(self):
        _ms, eng, _se, alt = _setup()
        try:
            rule = _rule(alt)
            t0 = EDGE
            alt._eval_rule(rule, t0, [({"job": "j0"}, 2.0)])
            (a,) = rule.active.values()
            assert a.state == "pending" and a.active_at_ms == t0
            assert a.annotations["summary"] == "job j0 at 2"
            # 15s elapsed < for:30s — still pending
            alt._eval_rule(rule, t0 + 15_000, [({"job": "j0"}, 3.5)])
            assert a.state == "pending" and a.value == 3.5
            assert a.annotations["summary"] == "job j0 at 3.5"
            # exactly at the threshold — fires
            alt._eval_rule(rule, t0 + 30_000, [({"job": "j0"}, 4.0)])
            assert a.state == "firing" and a.fired_at_ms == t0 + 30_000
            assert a.active_at_ms == t0  # for: clock never reset
            # payload shape (Prometheus /api/v1/alerts)
            (p,) = alt.alerts_payload()["alerts"]
            assert p["state"] == "firing"
            assert p["labels"] == {"alertname": "HighTraffic",
                                   "job": "j0", "severity": "page"}
            assert p["activeAt"] == rfc3339(t0) and p["value"] == "4"
            assert alt.alerts_payload("pending")["alerts"] == []
        finally:
            alt.stop()

    def test_for_zero_fires_on_first_eval(self):
        _ms, _eng, _se, alt = _setup()
        try:
            rule = _rule(alt, for_=0, keep=0)
            alt._eval_rule(rule, EDGE, [({"job": "j0"}, 1.0)])
            (a,) = rule.active.values()
            assert a.state == "firing"
        finally:
            alt.stop()

    def test_pending_resolves_silently(self):
        """A labelset that vanishes while still pending was never
        notified — it must go straight back to inactive, not produce a
        resolved notification."""
        _ms, _eng, _se, alt = _setup()
        resolved: list = []
        alt.notifier = type("N", (), {
            "note_resolved": staticmethod(resolved.extend),
            "start": staticmethod(lambda: None),
            "stop": staticmethod(lambda: None),
        })()
        try:
            rule = _rule(alt)
            alt._eval_rule(rule, EDGE, [({"job": "j0"}, 2.0)])
            assert len(rule.active) == 1
            alt._eval_rule(rule, EDGE + 15_000, [])
            assert not rule.active and resolved == []
        finally:
            alt.stop()

    def test_keep_firing_for_suppresses_flaps(self):
        _ms, _eng, _se, alt = _setup()
        resolved: list = []
        alt.notifier = type("N", (), {
            "note_resolved": staticmethod(resolved.extend),
            "start": staticmethod(lambda: None),
            "stop": staticmethod(lambda: None),
        })()
        try:
            rule = _rule(alt, for_=0, keep="30s")
            t0 = EDGE
            alt._eval_rule(rule, t0, [({"job": "j0"}, 2.0)])
            (a,) = rule.active.values()
            assert a.state == "firing"
            # one missed eval inside keep_firing_for: held, not resolved
            alt._eval_rule(rule, t0 + 15_000, [])
            assert a.state == "firing" and not resolved
            # condition returns: last_true advances, still the same alert
            alt._eval_rule(rule, t0 + 30_000, [({"job": "j0"}, 2.5)])
            assert len(rule.active) == 1 and not resolved
            # gone past the hold window: resolved, handed to the notifier
            alt._eval_rule(rule, t0 + 45_000, [])
            assert a.state == "firing" and not resolved  # 15s gap: held
            alt._eval_rule(rule, t0 + 60_000, [])
            assert not rule.active and len(resolved) == 1
            assert resolved[0]["labels"]["job"] == "j0"
            assert resolved[0]["ends_at_ms"] == t0 + 60_000
        finally:
            alt.stop()

    def test_per_labelset_independence(self):
        _ms, _eng, _se, alt = _setup()
        try:
            rule = _rule(alt, keep=0)
            t0 = EDGE
            alt._eval_rule(rule, t0, [({"job": "j0"}, 1.0),
                                      ({"job": "j1"}, 2.0)])
            assert len(rule.active) == 2
            # j1 keeps burning, j0 recovers while pending
            alt._eval_rule(rule, t0 + 15_000, [({"job": "j1"}, 2.0)])
            alt._eval_rule(rule, t0 + 30_000, [({"job": "j1"}, 2.0)])
            states = {a.labels["job"]: a.state
                      for a in rule.active.values()}
            assert states == {"j1": "firing"}
        finally:
            alt.stop()

    def test_state_written_back_queryable(self):
        """ALERTS / ALERTS_FOR_STATE ride the production ingest path into
        the bound dataset — alert state is a real queryable series."""
        _ms, eng, _se, alt = _setup()
        try:
            rule = _rule(alt, for_=0)
            t0 = EDGE
            for k in range(3):
                alt._eval_rule(rule, t0 + k * 15_000,
                               [({"job": "j0"}, 2.0)])
            res = eng.query_range(
                ALERTS_SERIES + '{alertstate="firing"}',
                (t0 - 60_000) / 1e3, (t0 + 60_000) / 1e3, 15.0,
            )
            vals = [v for g in res.grids
                    for row in np.asarray(g.values_np(), dtype=float)
                    for v in row if not np.isnan(v)]
            assert vals and set(vals) == {1.0}
            lbls = [dict(lb) for g in res.grids for lb in g.labels]
            assert any(d.get("alertname") == "HighTraffic"
                       and d.get("job") == "j0" for d in lbls)
            res2 = eng.query_range(
                ALERTS_FOR_STATE_SERIES,
                (t0 - 60_000) / 1e3, (t0 + 60_000) / 1e3, 15.0,
            )
            # value = seconds since active (f32-safe age, not epoch):
            # evals at t0, t0+15s, t0+30s with active_at=t0 → 0/15/30
            vals2 = {v for g in res2.grids
                     for row in np.asarray(g.values_np(), dtype=float)
                     for v in row if not np.isnan(v)}
            assert vals2 == {0.0, 15.0, 30.0}
        finally:
            alt.stop()

    def test_rehydration_preserves_for_clock(self):
        """Restart safety: a fresh AlertingEngine (what the server builds
        on boot) restores pending/firing from ALERTS_FOR_STATE — an alert
        that was firing before the restart must come back firing with its
        original active_at, not restart the for: hold."""
        _ms, _eng, se, alt = _setup()
        t0 = EDGE
        try:
            rule = _rule(alt)
            for k in range(3):  # pending @t0 → firing @t0+30s
                alt._eval_rule(rule, t0 + k * 15_000,
                               [({"job": "j0"}, 2.0)])
            assert next(iter(rule.active.values())).state == "firing"
        finally:
            alt.stop()
        # "restart": new engine, same rules, same store
        alt2 = AlertingEngine(se, {"default_interval_s": 15.0})
        try:
            rule2 = _rule(alt2)
            assert not rule2.active
            assert alt2.rehydrate(now_ms=t0 + 60_000) == 1
            (a,) = rule2.active.values()
            # active_at recovers to within one grid step (age encoding)
            assert a.state == "firing"
            assert abs(a.active_at_ms - t0) <= STEP_MS
            assert a.labels["job"] == "j0"
            # a second rehydrate is a no-op (fingerprint already active)
            assert alt2.rehydrate(now_ms=t0 + 60_000) == 0
        finally:
            alt2.stop()
        # restored state short of the for: hold comes back PENDING
        alt3 = AlertingEngine(se, {"default_interval_s": 15.0})
        try:
            rule3 = _rule(alt3)
            assert alt3.rehydrate(now_ms=t0 + 15_000) == 1
            (a3,) = rule3.active.values()
            assert a3.state == "pending" and a3.active_at_ms == t0
        finally:
            alt3.stop()

    def test_refresh_drives_sink_and_querylog(self):
        """The real evaluation path: the standing maintainer's refresh
        feeds the alert sink the newest closed step, and every evaluation
        leaves a query-observatory record (path=standing:*)."""
        _ms, _eng, se, alt = _setup()
        try:
            rule = alt.add_rule({
                "alert": "Traffic", "expr": Q + " > 0",
                "annotations": {"summary": "{{ $labels.job }}"},
            }, group="live")
            n0 = len(QUERY_LOG)
            se.refresh(rule.sq, now_ms=EDGE + 5_000)
            assert rule.sq.last_error is None
            assert rule.last_error is None
            # for: 0 → firing on the creation eval; one job label ("api")
            (a,) = rule.active.values()
            assert a.state == "firing" and a.labels["job"] == "api"
            assert a.annotations["summary"] == "api"
            assert len(QUERY_LOG) > n0
            rec = next(e for e in QUERY_LOG.entries(10)
                       if e["promql"] == rule.expr)
            assert rec["path"].startswith("standing:")
            assert rule.eval_duration_s > 0 and rule.last_eval_s > 0
        finally:
            alt.stop()

    def test_warm_canonical_query_one_dispatch_with_alerting(self):
        """Alerting riding the standing engine must not cost the serving
        path anything: with an alert rule registered and evaluating, the
        warm canonical query is still exactly ONE kernel dispatch."""
        _ms, eng, se, alt = _setup()
        try:
            rule = alt.add_rule({"alert": "Traffic", "expr": Q + " > 0"},
                                group="live")
            se.refresh(rule.sq, now_ms=EDGE + 5_000)
            start_s = (BASE + 600_000) / 1000
            end_s = (BASE + 1_800_000) / 1000
            eng.query_range(Q, start_s, end_s, 15.0)  # warm it
            se.refresh(rule.sq, now_ms=EDGE + 20_000)  # alerting ticks on
            d0 = kernel_dispatch_total()
            eng.query_range(Q, start_s, end_s, 15.0)
            assert kernel_dispatch_total() - d0 == 1
        finally:
            alt.stop()

    def test_eval_failure_counted_not_fatal(self):
        _ms, _eng, _se, alt = _setup()
        try:
            rule = _rule(alt)
            before = _counter("filodb_alert_eval_failures",
                              rule="HighTraffic")
            alt._eval_rule(rule, EDGE, [("not-a-labels-dict",)])
            assert _counter("filodb_alert_eval_failures",
                            rule="HighTraffic") == before + 1
            assert rule.last_error
            # the next good eval clears the error
            alt._eval_rule(rule, EDGE + 15_000, [({"job": "j0"}, 1.0)])
            assert rule.last_error is None
        finally:
            alt.stop()

    def test_alerts_gauge_tracks_states(self):
        _ms, _eng, _se, alt = _setup()
        try:
            rule = _rule(alt)
            alt._publish_gauges()
            assert _counter("filodb_alerts", alertstate="inactive") >= 1
            alt._eval_rule(rule, EDGE, [({"job": "j0"}, 2.0)])
            alt._publish_gauges()
            assert _counter("filodb_alerts", alertstate="pending") == 1
        finally:
            alt.stop()


# -- notifier -----------------------------------------------------------------


def _alert(fp, name="A", job="j0"):
    return {"fingerprint": fp,
            "labels": {"alertname": name, "job": job},
            "annotations": {"summary": f"{job} burning"},
            "starts_at_ms": EDGE}


def _notifier(name, src, transport, **kw):
    r = Receiver(name=name, url="http://invalid.test/hook",
                 group_wait_s=5.0, group_interval_s=30.0,
                 repeat_interval_s=300.0)
    kw.setdefault("retry", RetryPolicy(max_attempts=1))
    return r, Notifier([r], alerts_source=lambda: list(src),
                       transport=transport, **kw)


class TestNotifier:
    def test_receiver_config_validation(self):
        r = Receiver.from_config({"name": "am", "url": "http://x/",
                                  "group_by": "cluster",
                                  "group_wait": "10s",
                                  "repeat_interval": "4h"})
        assert r.group_by == ("cluster",) and r.group_wait_s == 10.0
        assert r.repeat_interval_s == 14_400.0 and r.send_resolved
        with pytest.raises(ValueError):
            Receiver.from_config({"name": "am"})  # no url
        with pytest.raises(ValueError):
            Receiver.from_config({"name": "am", "url": "u", "bogus": 1})

    def test_group_wait_then_exactly_one_delivery(self):
        sent = []
        src = [_alert("f1")]
        _r, n = _notifier("wh-wait", src,
                          lambda url, body, t: sent.append(
                              json.loads(body)))
        assert n.tick(now_s=0.0) == 0  # group_wait holds
        assert n.tick(now_s=4.0) == 0
        assert n.tick(now_s=5.0) == 1
        (p,) = sent
        assert p["version"] == "4" and p["status"] == "firing"
        assert p["receiver"] == "wh-wait"
        assert p["groupLabels"] == {"alertname": "A"}
        assert p["groupKey"] == '{}:{alertname="A"}'
        (a,) = p["alerts"]
        assert a["status"] == "firing" and a["fingerprint"] == "f1"
        assert a["startsAt"] == rfc3339(EDGE)
        assert a["endsAt"] == "0001-01-01T00:00:00Z"
        # dedup: unchanged group → silent until repeat_interval
        for t in (6.0, 30.0, 100.0, 304.0):
            assert n.tick(now_s=t) == 0
        assert len(sent) == 1
        assert _counter("filodb_alert_notify", receiver="wh-wait",
                        outcome="ok") == 1

    def test_membership_change_renotifies_after_group_interval(self):
        sent = []
        src = [_alert("f1")]
        _r, n = _notifier("wh-member", src,
                          lambda url, body, t: sent.append(
                              json.loads(body)))
        n.tick(now_s=0.0)  # registers the group (group_wait starts)
        assert n.tick(now_s=5.0) == 1
        src.append(_alert("f2", job="j1"))
        assert n.tick(now_s=10.0) == 0  # changed, but inside group_interval
        assert n.tick(now_s=35.0) == 1
        assert len(sent) == 2 and len(sent[1]["alerts"]) == 2
        assert sent[1]["commonLabels"] == {"alertname": "A"}

    def test_resolved_notification_and_cleanup(self):
        sent = []
        src = [_alert("f1")]
        _r, n = _notifier("wh-res", src,
                          lambda url, body, t: sent.append(
                              json.loads(body)))
        n.tick(now_s=0.0)
        assert n.tick(now_s=5.0) == 1
        gone = src.pop()
        n.note_resolved([{**gone, "ends_at_ms": EDGE + 60_000}])
        assert n.tick(now_s=36.0) == 1  # group_interval after last notify
        assert sent[1]["status"] == "resolved"
        (a,) = sent[1]["alerts"]
        assert a["status"] == "resolved"
        assert a["endsAt"] == rfc3339(EDGE + 60_000)
        # delivered + nothing firing → the group is forgotten
        assert n.snapshot()["groups"] == []

    def test_resolved_without_prior_notification_is_silent(self):
        sent = []
        src: list = []
        _r, n = _notifier("wh-silent", src,
                          lambda url, body, t: sent.append(body))
        n.note_resolved([{**_alert("f1"), "ends_at_ms": EDGE}])
        assert n.tick(now_s=100.0) == 0 and sent == []

    def test_repeat_interval(self):
        sent = []
        src = [_alert("f1")]
        _r, n = _notifier("wh-repeat", src,
                          lambda url, body, t: sent.append(body))
        n.tick(now_s=0.0)
        assert n.tick(now_s=5.0) == 1
        assert n.tick(now_s=304.0) == 0
        assert n.tick(now_s=305.0) == 1  # repeat_interval elapsed
        assert len(sent) == 2

    def test_retry_backoff_then_error(self):
        def boom(url, body, t):
            raise OSError("connection refused")

        sleeps: list = []
        src = [_alert("f1")]
        _r, n = _notifier(
            "wh-retry", src, boom,
            retry=RetryPolicy(max_attempts=3, base_backoff_s=0.5,
                              multiplier=2.0, jitter=0.0,
                              sleep=sleeps.append),
            deadline_s=60.0,
        )
        n.tick(now_s=0.0)
        assert n.tick(now_s=5.0) == 1
        assert sleeps == [0.5, 1.0]  # exponential backoff between tries
        assert _counter("filodb_alert_notify", receiver="wh-retry",
                        outcome="retry") == 2
        assert _counter("filodb_alert_notify", receiver="wh-retry",
                        outcome="error") == 1
        # failed delivery does NOT dedup: the group stays due
        assert n.tick(now_s=36.0) == 1

    def test_breaker_opens_on_dead_receiver(self):
        calls = []

        def boom(url, body, t):
            calls.append(url)
            raise OSError("connection refused")

        src = [_alert("f1")]
        r, n = _notifier("wh-breaker", src, boom)
        g = _Group(key=(("alertname", "A"),),
                   group_labels={"alertname": "A"}, first_seen_s=0.0)
        for _ in range(4):  # breaker: min_calls=4, failure_rate=0.5
            assert not n._deliver(r, g, list(src), [])
        assert len(calls) == 4
        assert not n._deliver(r, g, list(src), [])
        assert len(calls) == 4  # breaker open: transport never invoked
        assert _counter("filodb_alert_notify", receiver="wh-breaker",
                        outcome="breaker_open") == 1


# -- HTTP surfaces ------------------------------------------------------------


_ALERT_RULE_KEYS = {
    "name", "query", "duration", "keepFiringFor", "labels", "annotations",
    "alerts", "state", "health", "lastError", "evaluationTime",
    "lastEvaluation", "type",
}
_GROUP_KEYS = {"name", "file", "interval", "evaluationTime",
               "lastEvaluation", "rules"}


class TestHttpSurfaces:
    def test_rules_and_alerts_endpoints(self, tmp_path):
        from filodb_tpu.server import FiloServer

        srv = FiloServer({
            "dataset": "ds", "shards": 2,
            "store_root": str(tmp_path / "store"),
            "telemetry": {"self_scrape_interval_s": 3600},
            "slo": {"interval_s": 15.0, "windows": ["5m"]},
        })
        port = srv.start(port=0)
        base = f"http://127.0.0.1:{port}"
        try:
            assert srv.alerting is not None  # auto-on with _system standing
            code, resp = _post_json(f"{base}/api/v1/rules/alert", {
                "alert": "HighBurn",
                "expr": "slo:latency:burnrate:5m > 10",
                "for": "30s", "keep_firing_for": "1m",
                "labels": {"severity": "page"},
                "annotations": {"summary": "burn {{ $value }}"},
                "group": "custom", "interval": "15s",
            })
            assert code == 200, resp
            assert resp["data"] == {
                "group": "custom", "name": "HighBurn",
                "query": "slo:latency:burnrate:5m > 10",
                "duration": 30.0, "keepFiringFor": 60.0,
                "type": "alerting",
            }
            # duplicate name and malformed spec both 400
            assert _post_json(f"{base}/api/v1/rules/alert", {
                "alert": "HighBurn", "expr": "x > 1", "group": "custom",
            })[0] == 400
            assert _post_json(f"{base}/api/v1/rules/alert",
                              {"alert": "NoExpr"})[0] == 400

            # golden: Prometheus rules shape, both rule types
            data = _get_json(f"{base}/api/v1/rules")["data"]
            assert set(data) == {"groups"}
            groups = {g["name"]: g for g in data["groups"]}
            custom = groups["custom"]
            assert set(custom) == _GROUP_KEYS
            assert custom["interval"] == 15.0
            (r,) = custom["rules"]
            assert set(r) == _ALERT_RULE_KEYS
            assert r["type"] == "alerting" and r["state"] == "inactive"
            assert r["health"] == "ok" and r["alerts"] == []
            assert r["duration"] == 30.0 and r["keepFiringFor"] == 60.0
            recs = [r for g in data["groups"] for r in g["rules"]
                    if r["type"] == "recording"]
            assert "slo:latency:burnrate:5m" in [r["name"] for r in recs]
            for r in recs:
                assert {"name", "query", "health", "evaluationTime",
                        "lastEvaluation", "type"} <= set(r)
            # no rule listed twice across groups
            names = [r["name"] for g in data["groups"]
                     for r in g["rules"]]
            assert len(names) == len(set(names))

            # ?type / ?state filters
            d = _get_json(f"{base}/api/v1/rules?type=alert")["data"]
            assert d["groups"] and all(
                r["type"] == "alerting"
                for g in d["groups"] for r in g["rules"])
            d = _get_json(f"{base}/api/v1/rules?type=record")["data"]
            assert d["groups"] and all(
                r["type"] == "recording"
                for g in d["groups"] for r in g["rules"])
            # nothing fires → a state filter empties every group
            d = _get_json(f"{base}/api/v1/rules?state=firing")["data"]
            assert d["groups"] == []
            assert _get_status(f"{base}/api/v1/rules?type=bogus")[0] == 400
            assert _get_status(f"{base}/api/v1/rules?state=bogus")[0] == 400

            # /api/v1/alerts: live (empty) + validation
            assert _get_json(f"{base}/api/v1/alerts")["data"] == \
                {"alerts": []}
            assert _get_json(
                f"{base}/api/v1/alerts?state=pending")["data"] == \
                {"alerts": []}
            assert _get_status(f"{base}/api/v1/alerts?state=nope")[0] == 400

            # /debug/querylog?path= filter
            srv.memstore.ingest_routed(
                "ds", counter_batch(n_series=4, n_samples=60,
                                    start_ms=BASE), spread=1)
            _get_json(f"{base}/api/v1/query_range?query="
                      + urllib.parse.quote(Q)
                      + f"&start={(BASE + 200_000) / 1000}"
                      f"&end={(BASE + 500_000) / 1000}&step=60")
            entries = _get_json(f"{base}/debug/querylog")["data"]
            assert entries
            p0 = entries[0]["path"]
            filt = _get_json(f"{base}/debug/querylog?path="
                             + urllib.parse.quote(p0))["data"]
            assert filt and all(e["path"] == p0 for e in filt)
            assert _get_json(f"{base}/debug/querylog?path=no-such-path"
                             )["data"] == []
        finally:
            srv.stop()


# -- end to end ---------------------------------------------------------------


class _Webhook(BaseHTTPRequestHandler):
    bodies: list = []

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        n = int(self.headers.get("Content-Length", 0))
        type(self).bodies.append(json.loads(self.rfile.read(n)))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *_a):
        pass


class TestAlertingE2E:
    def test_slo_burn_to_webhook_and_back(self, tmp_path):
        """The acceptance path: injected 5xx traffic → the SLO burn
        recording rule crosses 1 → AvailabilityBurnFast walks
        pending→firing on the standing engine → exactly ONE grouped
        webhook lands → recovery resolves the alert → one resolved
        notification — then the receiver dies and delivery shows real
        retries/backoff against the dead socket."""
        from filodb_tpu.server import FiloServer

        hook = ThreadingHTTPServer(("127.0.0.1", 0), _Webhook)
        _Webhook.bodies = []
        hook_thread = threading.Thread(target=hook.serve_forever,
                                       daemon=True)
        hook_thread.start()
        wport = hook.server_address[1]

        srv = FiloServer({
            "dataset": "ds", "shards": 2,
            "store_root": str(tmp_path / "store"),
            "telemetry": {"self_scrape_interval_s": 3600},
            "slo": {"interval_s": 15.0, "windows": ["5m"]},
            "alerting": {
                "rule_files": ["conf/rules/slo.yml"],
                "notify_tick_s": 3600,  # tests drive tick() directly
                "receivers": [{
                    "name": "am", "url": f"http://127.0.0.1:{wport}/",
                    "group_wait": 0, "group_interval": "15s",
                    "repeat_interval": "1h",
                }],
            },
        })
        port = srv.start(port=0)
        base = f"http://127.0.0.1:{port}"
        try:
            # deterministic timeline: the maintainer thread must not race
            # the test's explicit refreshes with wall-clock evaluations
            ss = srv.system_standing
            ss._stop.set()
            ss._wake.set()
            if ss._thread is not None:
                ss._thread.join(timeout=2)

            alt = srv.alerting
            assert alt is not None and alt.notifier is not None
            rule = next(r for g in alt.groups.values() for r in g.rules
                        if r.name == "AvailabilityBurnFast")
            assert rule.sq is not None

            srv.memstore.ingest_routed(
                "ds", counter_batch(n_series=6, n_samples=60,
                                    start_ms=BASE), spread=1)
            qurl = (f"{base}/api/v1/query_range?query="
                    + urllib.parse.quote(Q)
                    + f"&start={(BASE + 200_000) / 1000}"
                    f"&end={(BASE + 500_000) / 1000}&step=60")
            now = int(time.time() * 1000)

            # OUTAGE: every window, some real 2xx traffic plus a pile of
            # injected 5xx — the availability burn rate blows past 1
            for k in range(6):
                _get_json(qurl)
                for _ in range(40):
                    REGISTRY.counter("filodb_http_responses", code="500",
                                     **{"class": "5xx"}).inc()
                assert srv.self_scraper.scrape_once(
                    now_ms=now + k * 15_000) > 0

            def _tick(t_ms):
                for sq in srv.slo_rules:  # burn series first, then alert
                    srv.system_standing.refresh(sq, now_ms=t_ms)
                srv.system_standing.refresh(rule.sq, now_ms=t_ms)

            _tick(now + 75_000)
            (a,) = rule.active.values()
            assert a.state == "pending" and a.value > 1.0
            _tick(now + 90_000)
            assert a.state == "pending"  # 15s < for:30s
            _tick(now + 105_000)
            assert a.state == "firing"

            # the alert surface shows it, annotations expanded with $value
            alerts = _get_json(f"{base}/api/v1/alerts")["data"]["alerts"]
            fired = [x for x in alerts
                     if x["labels"]["alertname"] == "AvailabilityBurnFast"]
            assert len(fired) == 1 and fired[0]["state"] == "firing"
            assert "availability error budget burning at" in \
                fired[0]["annotations"]["summary"]
            assert "{{" not in fired[0]["annotations"]["summary"]
            rj = _get_json(f"{base}/api/v1/rules?state=firing")["data"]
            assert [r["name"] for g in rj["groups"]
                    for r in g["rules"]] == ["AvailabilityBurnFast"]

            # alert state is real data in _system…
            out = _get_json(
                f"{base}/api/v1/query_range?dataset=_system&query="
                + urllib.parse.quote(
                    'ALERTS{alertstate="firing",'
                    'alertname="AvailabilityBurnFast"}')
                + f"&start={now / 1000}&end={(now + 120_000) / 1000}"
                "&step=15")["data"]
            vals = [float(v) for s in out["result"]
                    for _, v in s["values"] if v != "NaN"]
            assert vals and set(vals) == {1.0}
            # …and every evaluation left a query-observatory record
            ql = _get_json(f"{base}/debug/querylog?path=standing:full"
                           )["data"]
            assert any(e["promql"] == rule.expr for e in ql)

            # EXACTLY ONE grouped webhook, then dedup across repeat ticks
            nt = alt.notifier
            assert nt.tick(now_s=1000.0) == 1
            for t in (1001.0, 1016.0, 1100.0):
                assert nt.tick(now_s=t) == 0
            assert len(_Webhook.bodies) == 1
            body = _Webhook.bodies[0]
            assert body["status"] == "firing" and body["receiver"] == "am"
            assert body["groupLabels"] == \
                {"alertname": "AvailabilityBurnFast"}
            (wa,) = body["alerts"]
            assert wa["status"] == "firing"
            assert wa["labels"]["severity"] == "page"

            # RECOVERY: only clean traffic; the 5m rate window slides past
            # the injected errors and the burn series drops to 0
            for k in range(6):
                _get_json(qurl)
                assert srv.self_scraper.scrape_once(
                    now_ms=now + 330_000 + k * 15_000) > 0
            _tick(now + 420_000)  # gap >> keep_firing_for: resolves now
            assert not rule.active
            assert _get_json(f"{base}/api/v1/alerts")["data"]["alerts"] \
                == []

            assert nt.tick(now_s=1200.0) == 1
            assert len(_Webhook.bodies) == 2
            res_body = _Webhook.bodies[1]
            assert res_body["status"] == "resolved"
            (ra,) = res_body["alerts"]
            assert ra["status"] == "resolved"
            assert ra["endsAt"] != "0001-01-01T00:00:00Z"

            # KILLED RECEIVER: the same receiver, socket now dead — the
            # delivery path really retries with backoff, then gives up
            hook.shutdown()
            hook.server_close()
            hook_thread.join(timeout=2)
            nt.retry = RetryPolicy(max_attempts=3, base_backoff_s=0.01,
                                   multiplier=2.0, jitter=0.0, seed=1)
            r0 = nt.receivers[0]
            g = _Group(key=(("alertname", "Dead"),),
                       group_labels={"alertname": "Dead"},
                       first_seen_s=0.0)
            retry0 = _counter("filodb_alert_notify", receiver="am",
                              outcome="retry")
            err0 = _counter("filodb_alert_notify", receiver="am",
                            outcome="error")
            ok0 = _counter("filodb_alert_notify", receiver="am",
                           outcome="ok")
            assert not nt._deliver(r0, g, [_alert("fdead", name="Dead")],
                                   [])
            assert _counter("filodb_alert_notify", receiver="am",
                            outcome="retry") == retry0 + 2
            assert _counter("filodb_alert_notify", receiver="am",
                            outcome="error") == err0 + 1
            assert ok0 == 2.0  # the two real deliveries above
        finally:
            srv.stop()
            try:
                hook.server_close()
            except OSError:
                pass
