"""TCP gateway tests (model: reference GatewayServer + TestTimeseriesProducer
round trip)."""

import time

import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.gateway.server import GatewayServer, produce_load
from filodb_tpu.memstore.memstore import TimeSeriesMemStore

BASE = 1_600_000_000_000


def test_gateway_ingest_roundtrip():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    gw = GatewayServer(ms, "prometheus", spread=2, ws="demo", ns="App-0")
    port = gw.start()
    try:
        sent = produce_load("127.0.0.1", port, n_series=10, n_samples=20, start_ms=BASE)
        assert sent == 200
        deadline = time.time() + 15
        while time.time() < deadline and gw.rows_ingested < 200:
            time.sleep(0.05)
        assert gw.rows_ingested == 200
        assert gw.parse_errors == 0
        total = sum(sh.num_partitions for sh in ms.shards("prometheus"))
        assert total == 10
        engine = QueryEngine(ms, "prometheus")
        res = engine.query_range(
            "sum(machine_cpu)", (BASE + 60_000) / 1000, (BASE + 180_000) / 1000, 30
        )
        assert sum(g.n_series for g in res.grids) == 1
    finally:
        gw.stop()


def test_gateway_bad_lines_counted():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0])
    gw = GatewayServer(ms, "prometheus", spread=0)
    port = gw.start()
    try:
        import socket

        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(b"this is not influx\ncpu,host=a value=1 1600000000000000000\n")
        deadline = time.time() + 10
        while time.time() < deadline and gw.rows_ingested < 1:
            time.sleep(0.05)
        assert gw.rows_ingested == 1
        assert gw.parse_errors == 1
    finally:
        gw.stop()
