"""Mesh-sharded fused superblocks (doc/perf.md "Mesh-sharded fused path").

The canonical query over a mesh-configured engine must execute as ONE
multi-device dispatch: the [ΣS, T] / [ΣS, T, B] superblock partitions its
series axis across the mesh (PartitionSpec(axis) row bands) and the whole
``range_fn -> segment_aggregate -> epilogue`` program runs under shard_map
with psum-combined [G, J] partials (topk/quantile combine winner/multiset
state across devices inside the same program).

Parity contract: sharded fused == single-device fused == reference tree
across the full operator set, for ΣS not divisible by the mesh size, and
for the mesh-size-1 degenerate case. Runs on the conftest-forced 8-device
virtual CPU mesh (make test-multichip).
"""

import numpy as np
import pytest

import jax

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.mesh import make_mesh, make_series_mesh
from filodb_tpu.testkit import counter_batch, histogram_batch, machine_metrics

pytestmark = [pytest.mark.perf, pytest.mark.fused_mesh]

BASE = 1_600_000_000_000
N_SHARDS = 8
START = (BASE + 600_000) / 1000
END = START + 1200
STEP = 60


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    ms.ingest_routed(
        "ds", counter_batch(n_series=48, n_samples=300, start_ms=BASE),
        spread=3,
    )
    ms.ingest_routed(
        "ds", machine_metrics(n_series=48, n_samples=300, start_ms=BASE),
        spread=3,
    )
    ms.ingest_routed(
        "ds", histogram_batch(n_series=24, n_samples=300, start_ms=BASE),
        spread=3,
    )
    return ms


@pytest.fixture(scope="module")
def engines(store):
    single = QueryEngine(store, "ds")
    sharded = QueryEngine(store, "ds", PlannerParams(mesh=make_mesh()))
    ref = QueryEngine(store, "ds", PlannerParams(fused_aggregate=False))
    return single, sharded, ref


def _rows(res):
    out = {}
    for g in res.grids:
        for i, lbls in enumerate(g.labels):
            vals = g.values_np()[i]
            h = g.hist_np()
            out[tuple(sorted(lbls.items()))] = (
                np.asarray(vals), None if h is None else np.asarray(h[i])
            )
    return out


def assert_three_way(single, sharded, ref, q, exact=False):
    """sharded == single-device fused == reference, NaN masks bit-identical,
    values within float32 accumulation-order ulps (the same tolerance the
    fused-vs-reference suite pins)."""
    rows = [_rows(e.query_range(q, START, END, STEP))
            for e in (single, sharded, ref)]
    a, b, c = rows
    assert a.keys() == b.keys() == c.keys(), (q, sorted(a), sorted(b))
    for k in a:
        for other in (b, c):
            va, ha = a[k]
            vb, hb = other[k]
            na, nb = np.isnan(va), np.isnan(vb)
            assert (na == nb).all(), (q, k, "NaN masks differ")
            if exact:
                assert (va[~na] == vb[~nb]).all(), (q, k)
            else:
                np.testing.assert_allclose(
                    va[~na], vb[~nb], rtol=2e-5, atol=1e-6, err_msg=f"{q} {k}"
                )
            if ha is not None or hb is not None:
                assert ha is not None and hb is not None, (q, k)
                np.testing.assert_allclose(
                    ha, hb, rtol=2e-5, atol=1e-6, equal_nan=True,
                    err_msg=f"{q} {k} hist",
                )


# -- parity across the full operator set -------------------------------------


@pytest.mark.parametrize("q", [
    "sum(rate(http_requests_total[5m]))",
    "sum by (instance) (rate(http_requests_total[5m]))",
    "avg(increase(http_requests_total[5m]))",
    "min(sum_over_time(heap_usage0[3m]))",
    "max by (instance) (avg_over_time(heap_usage0[3m]))",
    "count by (job) (delta(http_requests_total[5m]))",
])
def test_sharded_parity_simple_aggregates(engines, q):
    assert_three_way(*engines, q)


def test_sharded_parity_topk(engines):
    assert_three_way(*engines, "topk(3, rate(http_requests_total[5m]))")
    assert_three_way(*engines, "bottomk(2, rate(http_requests_total[5m]))")


def test_sharded_parity_quantile(engines):
    assert_three_way(*engines, "quantile(0.9, rate(http_requests_total[5m]))")


def test_sharded_parity_hist_sum(engines):
    assert_three_way(
        *engines, "sum by (le) (rate(http_request_latency_bucket[5m]))"
    )


def test_sharded_parity_histogram_quantile(engines):
    assert_three_way(
        *engines,
        "histogram_quantile(0.99, "
        "sum by (le) (rate(http_request_latency_bucket[5m])))",
    )


def test_sharded_plans_delegate(engines):
    """Plan shapes: simple aggregates keep the MeshAggregateExec root whose
    aggregate path delegates to the sharded FusedAggregateExec; the
    epilogue ops and fused histogram_quantile plan straight to a
    mesh-aware FusedAggregateExec."""
    from filodb_tpu.parallel.exec import MeshAggregateExec
    from filodb_tpu.query.exec.plans import FusedAggregateExec
    from filodb_tpu.query.promql import query_range_to_logical_plan

    _, sharded, _ = engines
    plan = query_range_to_logical_plan(
        "sum(rate(http_requests_total[5m]))", START, END, STEP)
    ep = sharded.planner.materialize(plan)
    assert isinstance(ep, MeshAggregateExec)
    delegate = ep._sharded_fused()
    assert isinstance(delegate, FusedAggregateExec)
    assert delegate.mesh is not None and delegate.mesh.devices.size == 8

    for q in (
        "topk(3, rate(http_requests_total[5m]))",
        "histogram_quantile(0.99, "
        "sum by (le) (rate(http_request_latency_bucket[5m])))",
    ):
        ep = sharded.planner.materialize(
            query_range_to_logical_plan(q, START, END, STEP))
        assert isinstance(ep, FusedAggregateExec), q
        assert ep.mesh is not None, q


# -- awkward shapes ----------------------------------------------------------


def test_sigma_s_not_divisible_by_mesh(store):
    """13 real series over an 8-device mesh: the padded ΣS rounds up to a
    mesh-divisible size and the trash-group masking keeps the pad rows
    inert — parity must hold exactly as for friendly shapes."""
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("odd"), list(range(3)))
    ms.ingest_routed(
        "odd", counter_batch(n_series=13, n_samples=200, start_ms=BASE),
        spread=1,
    )
    single = QueryEngine(ms, "odd")
    sharded = QueryEngine(ms, "odd", PlannerParams(mesh=make_mesh()))
    ref = QueryEngine(ms, "odd", PlannerParams(fused_aggregate=False))
    assert_three_way(single, sharded, ref,
                     "sum by (instance) (rate(http_requests_total[5m]))")
    assert_three_way(single, sharded, ref,
                     "topk(20, rate(http_requests_total[5m]))")
    sharded_entries = [
        e for e in ms._superblock_cache.snapshot() if e["sharding"]
    ]
    assert sharded_entries
    for e in sharded_entries:
        assert e["shape"][0] % 8 == 0, e  # mesh-divisible padded ΣS


def test_mesh_size_one_degenerate(store):
    """A 1-device mesh runs the same shard_map program shape — the
    degenerate case must behave exactly like the single-device fused
    path."""
    single = QueryEngine(store, "ds")
    one = QueryEngine(
        store, "ds", PlannerParams(mesh=make_series_mesh(jax.devices()[:1]))
    )
    ref = QueryEngine(store, "ds", PlannerParams(fused_aggregate=False))
    assert_three_way(single, one, ref,
                     "sum by (instance) (rate(http_requests_total[5m]))")
    assert_three_way(single, one, ref,
                     "quantile(0.5, rate(http_requests_total[5m]))")


# -- O(1) dispatch on the mesh -----------------------------------------------


def _dispatch_total() -> int:
    from filodb_tpu.testkit import kernel_dispatch_total

    return kernel_dispatch_total()


def test_warm_sharded_query_is_single_dispatch(engines):
    _, sharded, _ = engines
    q = "sum(rate(http_requests_total[5m]))"
    sharded.query_range(q, START, END, STEP)  # stage + compile + cache warm
    before = _dispatch_total()
    sharded.query_range(q, START, END, STEP)
    assert _dispatch_total() - before == 1, (
        "warm sharded sum(rate) must issue exactly ONE dispatch across the "
        "8-device mesh"
    )


def test_warm_sharded_hist_quantile_is_single_dispatch(engines):
    _, sharded, _ = engines
    q = ("histogram_quantile(0.99, "
         "sum by (le) (rate(http_request_latency_bucket[5m])))")
    sharded.query_range(q, START, END, STEP)
    before = _dispatch_total()
    sharded.query_range(q, START, END, STEP)
    assert _dispatch_total() - before == 1, (
        "warm sharded histogram_quantile must issue exactly ONE dispatch"
    )


# -- sharding-aware accounting & maintenance ---------------------------------


def test_superblock_cache_reports_sharding(engines, store):
    _, sharded, _ = engines
    sharded.query_range("sum(rate(http_requests_total[5m]))", START, END, STEP)
    entries = store._superblock_cache.snapshot()
    shard_entries = [e for e in entries if e["sharding"]]
    assert shard_entries, entries
    e = shard_entries[0]
    assert "x 8 devices" in e["sharding"]
    assert e["device_bytes"] and len(e["device_bytes"]) == 8
    assert sum(e["device_bytes"].values()) == e["bytes"]


def test_ledger_per_device_balances(engines, store):
    from filodb_tpu.ledger import LEDGER

    _, sharded, _ = engines
    sharded.query_range("sum(rate(http_requests_total[5m]))", START, END, STEP)
    dev = {k: v for k, v in LEDGER.device_balances().items()
           if k[0] == "superblock"}
    assert len(dev) == 8, dev
    assert all(v > 0 for v in dev.values())
    # the process ledger spans every live cache (other suites' stores may
    # still be alive): it must cover at least THIS store's sharded entries
    total = sum(
        e["bytes"] for e in store._superblock_cache.snapshot() if e["sharding"]
    )
    assert sum(dev.values()) >= total > 0
    LEDGER.publish()
    from filodb_tpu.metrics import REGISTRY

    out = REGISTRY.expose()
    assert 'filodb_device_bytes{device="' in out


def test_sharded_superblock_extends_under_live_ingest():
    """Live-edge appends must EXTEND the sharded superblock in place
    (placement preserved) and keep the warm query a single dispatch."""
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.core.schemas import PROM_COUNTER
    from filodb_tpu.metrics import REGISTRY

    def maintenance(outcome):
        for line in REGISTRY.expose().splitlines():
            if line.startswith(
                f'filodb_superblock_maintenance_total{{outcome="{outcome}"}}'
            ):
                return int(float(line.rsplit(" ", 1)[1]))
        return 0

    T = 300
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("live"), list(range(4)))
    ms.ingest_routed(
        "live", counter_batch(n_series=16, n_samples=T, start_ms=BASE),
        spread=2,
    )
    eng = QueryEngine(ms, "live", PlannerParams(mesh=make_mesh()))
    ref = QueryEngine(ms, "live", PlannerParams(fused_aggregate=False))
    end = (BASE + (T + 60) * 10_000) / 1000  # live edge
    q = "sum(rate(http_requests_total[5m]))"
    eng.query_range(q, START, end, STEP)
    eng.query_range(q, START, end, STEP)
    tags = [dict(p.tags) for sh in ms.shards("live")
            for p in sh.partitions.values()]
    t_new = BASE + T * 10_000
    ms.ingest_routed("live", RecordBatch(
        PROM_COUNTER, np.full(len(tags), t_new, np.int64),
        {"count": np.full(len(tags), 1e12)}, tags,
    ), spread=2)
    ext_before = maintenance("extend")
    before = _dispatch_total()
    r1 = eng.query_range(q, START, end, STEP)
    assert _dispatch_total() - before == 1
    assert maintenance("extend") == ext_before + 1
    r2 = ref.query_range(q, START, end, STEP)
    a = r1.grids[0].values_np()[0]
    c = r2.grids[0].values_np()[0]
    assert (np.isnan(a) == np.isnan(c)).all()
    m = ~np.isnan(c)
    np.testing.assert_allclose(a[m], c[m], rtol=2e-5, atol=1e-6)
    snap = ms._superblock_cache.snapshot()
    assert snap and snap[0]["sharding"] is not None  # placement survived


# -- fallback taxonomy -------------------------------------------------------


def test_unsupported_function_falls_back_to_legacy_mesh(engines):
    """A mesh-accepted function outside the fused set keeps the legacy
    per-shard mesh kernels, tagged mesh_unsupported."""
    from filodb_tpu.metrics import REGISTRY

    def fallback_count():
        for line in REGISTRY.expose().splitlines():
            if line.startswith(
                'filodb_fused_fallback_total{reason="mesh_unsupported"}'
            ):
                return int(float(line.rsplit(" ", 1)[1]))
        return 0

    single, sharded, _ = engines
    # absent_over_time is mesh-legal (MXU mesh set) but not in FUSED_FUNCS
    q = "sum(absent_over_time(no_such_metric[5m]))"
    before = fallback_count()
    sharded.query_range(q, START, END, STEP)
    assert fallback_count() == before + 1


def test_fused_disabled_keeps_legacy_mesh_quietly(store):
    """PlannerParams(fused_aggregate=False) + mesh = the pre-fusion mesh
    engine, with NO mesh_unsupported noise (explicit opt-out)."""
    from filodb_tpu.metrics import REGISTRY

    def fallback_count():
        for line in REGISTRY.expose().splitlines():
            if line.startswith(
                'filodb_fused_fallback_total{reason="mesh_unsupported"}'
            ):
                return int(float(line.rsplit(" ", 1)[1]))
        return 0

    eng = QueryEngine(store, "ds", PlannerParams(
        mesh=make_mesh(), fused_aggregate=False))
    before = fallback_count()
    r = eng.query_range("sum(rate(http_requests_total[5m]))", START, END, STEP)
    assert r.grids and np.isfinite(r.grids[0].values_np()).any()
    assert fallback_count() == before
