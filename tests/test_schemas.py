"""Schema registry, partkey hashing, shard routing tests (model: reference
RecordBuilder/Schemas specs under core/src/test)."""

import numpy as np

from filodb_tpu.core import schemas as S
from filodb_tpu.core.records import gauge_batch


def test_standard_schemas_registered():
    for name in [
        "gauge",
        "untyped",
        "prom-counter",
        "delta-counter",
        "prom-histogram",
        "delta-histogram",
        "otel-cumulative-histogram",
        "otel-delta-histogram",
        "otel-exp-delta-histogram",
    ]:
        assert name in S.SCHEMAS


def test_schema_ids_unique_and_stable():
    ids = [s.schema_id for s in S.SCHEMAS.values()]
    assert len(set(ids)) == len(ids)
    assert S.schema_by_id(S.GAUGE.schema_id) is S.GAUGE


def test_counter_flags():
    assert S.PROM_COUNTER.column("count").is_counter
    assert S.DELTA_COUNTER.column("count").is_delta
    assert not S.GAUGE.column("value").is_counter


def test_canonical_partkey_order_independent():
    a = S.canonical_partkey({"b": "2", "a": "1", "_metric_": "m"})
    b = S.canonical_partkey({"_metric_": "m", "a": "1", "b": "2"})
    assert a == b


def test_prom_name_normalized():
    a = S.canonical_partkey({"__name__": "m", "a": "1"})
    b = S.canonical_partkey({"_metric_": "m", "a": "1"})
    assert a == b


def test_shard_routing_spread():
    # all series of one metric land in exactly 2^spread shards
    spread, num_shards = 3, 32
    shards = set()
    for i in range(500):
        tags = {"_ws_": "demo", "_ns_": "App-0", "_metric_": "cpu", "instance": str(i)}
        shards.add(S.shard_for(tags, spread, num_shards))
    assert len(shards) <= 2**spread
    assert len(shards) > 1  # spread actually distributes


def test_shard_routing_distributes_metrics():
    spread, num_shards = 1, 64
    shards = set()
    for i in range(200):
        tags = {"_ws_": "demo", "_ns_": "App-0", "_metric_": f"metric_{i}"}
        shards.add(S.shard_for(tags, spread, num_shards))
    assert len(shards) > 16  # different metrics spread over the cluster


def test_record_batch_grouping():
    batch = gauge_batch(
        "cpu",
        [
            ({"host": "a"}, 1000, 1.0),
            ({"host": "b"}, 1000, 2.0),
            ({"host": "a"}, 2000, 3.0),
        ],
    )
    groups = batch.group_by_series()
    assert len(groups) == 2
    by_host = {g.tags["host"]: g for g in groups}
    np.testing.assert_array_equal(by_host["a"].timestamps, [1000, 2000])
    np.testing.assert_array_equal(by_host["a"].values["value"], [1.0, 3.0])


def test_shard_split_partitions_batch():
    batch = gauge_batch(
        "cpu", [({"host": str(i)}, 1000, float(i)) for i in range(100)]
    )
    split = batch.shard_split(spread=2, num_shards=8)
    assert sum(len(b) for b in split.values()) == 100
    for s, b in split.items():
        for t in b.tags:
            assert S.shard_for(t, 2, 8) == s


def test_histogram_schema_flags():
    from filodb_tpu.core.schemas import (
        DELTA_HISTOGRAM,
        OTEL_CUMULATIVE_HISTOGRAM,
        PROM_HISTOGRAM,
    )

    assert PROM_HISTOGRAM.has_histogram
    assert PROM_HISTOGRAM.column("h").is_counter
    assert DELTA_HISTOGRAM.column("h").is_delta
    assert OTEL_CUMULATIVE_HISTOGRAM.column("min").ctype.value == "double"


def test_base2_exp_bucket_bounds():
    from filodb_tpu.core.histograms import base2_exp_buckets
    import numpy as np

    s = base2_exp_buckets(scale=2, start_index=0, num=8)
    b = s.bounds()
    assert b[0] == 0.0 and np.isinf(b[-1])
    # growth factor 2^(2^-scale) between consecutive finite bounds
    ratios = b[2:-1] / b[1:-2]
    np.testing.assert_allclose(ratios, 2 ** (2**-2.0))


class TestGroupBySeries:
    """Run-length grouping edge cases (core/records.py group_by_series):
    the fast path walks runs of identical tag OBJECTS; interleaved series
    and per-row fresh dicts must still group correctly by content."""

    def _batch(self, tags_list, ts, vals):
        import numpy as np

        from filodb_tpu.core.records import RecordBatch
        from filodb_tpu.core.schemas import GAUGE

        return RecordBatch(
            GAUGE, np.asarray(ts, np.int64),
            {"value": np.asarray(vals, np.float64)}, tags_list,
        )

    def test_interleaved_series_group_by_content(self):
        import numpy as np

        a = {"_metric_": "m", "host": "a"}
        b = {"_metric_": "m", "host": "b"}
        batch = self._batch([a, b, a, b, a], [1, 1, 2, 2, 3], [10, 20, 11, 21, 12])
        got = {g.tags["host"]: g for g in batch.group_by_series()}
        assert sorted(got) == ["a", "b"]
        np.testing.assert_array_equal(got["a"].timestamps, [1, 2, 3])
        np.testing.assert_array_equal(got["a"].values["value"], [10, 11, 12])
        np.testing.assert_array_equal(got["b"].values["value"], [20, 21])

    def test_fresh_dicts_per_row_group_by_content(self):
        import numpy as np

        rows = [{"_metric_": "m", "host": "a"} for _ in range(3)]
        rows += [{"_metric_": "m", "host": "b"} for _ in range(2)]
        batch = self._batch(rows, [1, 2, 3, 1, 2], [1, 2, 3, 4, 5])
        got = {g.tags["host"]: g for g in batch.group_by_series()}
        np.testing.assert_array_equal(got["a"].values["value"], [1, 2, 3])
        np.testing.assert_array_equal(got["b"].values["value"], [4, 5])

    def test_contiguous_single_series_is_view_equivalent(self):
        import numpy as np

        t = {"_metric_": "m", "host": "a"}
        batch = self._batch([t, t, t], [1, 2, 3], [7, 8, 9])
        (g,) = batch.group_by_series()
        np.testing.assert_array_equal(g.timestamps, [1, 2, 3])
        np.testing.assert_array_equal(g.values["value"], [7, 8, 9])
