"""Native exposition scanner vs the Python regex parser (reference analog:
the gateway's compiled InputRecord parsers; test model: the codec
native-vs-python parity suites)."""

import random

import numpy as np
import pytest

from filodb_tpu import native as N
from filodb_tpu.gateway.parsers import (
    _native_prom_batches,
    prom_text_to_batches_and_exemplars,
)

pytestmark = pytest.mark.skipif(
    N.prom_lib() is None, reason="native prom scanner unavailable"
)

BASE = 1_600_000_000_000


def _python_reference(text, default_ts, ws="default", ns="default"):
    """The pure-Python path, bypassing the native fast path."""
    from filodb_tpu.core.schemas import GAUGE, METRIC_TAG, PROM_COUNTER
    from filodb_tpu.gateway import parsers as P

    gauges, counters = ([], []), ([], [])
    exemplars = []
    for name, tags, t, v, typ, ex in P.parse_prom_text(text, with_exemplars=True):
        full = dict(tags)
        full[METRIC_TAG] = name
        full.setdefault("_ws_", ws)
        full.setdefault("_ns_", ns)
        bucket = counters if typ == "counter" else gauges
        bucket[0].append(full)
        bucket[1].append((t if t is not None else default_ts, v))
        if ex is not None:
            ex_labels, ex_val, ex_ts = ex
            exemplars.append(
                (full, ex_ts if ex_ts is not None else (t if t is not None else default_ts),
                 ex_val, ex_labels))
    return P._assemble_batches(gauges, counters), exemplars


def _batches_equal(a, b):
    assert len(a) == len(b)
    for ba, bb in zip(a, b):
        assert ba.schema.name == bb.schema.name
        assert list(ba.tags) == list(bb.tags)
        np.testing.assert_array_equal(ba.timestamps, bb.timestamps)
        for col in ba.values:
            np.testing.assert_array_equal(
                ba.values[col], bb.values[col], err_msg=col)


CORPUS = """\
# HELP http_requests_total total requests
# TYPE http_requests_total counter
http_requests_total{job="api",code="200"} 1027 1600000000000
http_requests_total{job="api",code="500"} 3 1600000000000
# TYPE temp gauge
temp{site="a b",note="x=y,z"} -3.25
temp 0.5 1600000060000
plain_metric 42
nan_metric NaN 1600000000000
inf_metric +Inf
neg_inf -Inf 1600000000001
esc{v="quote\\"inside",w="back\\\\slash"} 7
colon:name:total 1 1600000000002
"""


class TestNativeParity:
    def test_corpus_matches_python(self):
        got = prom_text_to_batches_and_exemplars(CORPUS, BASE)
        want = _python_reference(CORPUS, BASE)
        _batches_equal(got[0], want[0])
        assert got[1] == want[1]

    def test_exemplar_lines(self):
        text = (
            "# TYPE rq counter\n"
            'rq{job="x"} 5 1600000000000 # {trace_id="abc"} 0.5 1600000000.5\n'
            'rq{job="y"} 6 # {trace_id="def"} 1.5\n'
        )
        got_b, got_ex = prom_text_to_batches_and_exemplars(text, BASE)
        want_b, want_ex = _python_reference(text, BASE)
        _batches_equal(got_b, want_b)
        assert got_ex == want_ex
        assert len(got_ex) == 2

    def test_hash_inside_label_value(self):
        # ' # {' inside a quoted label value must not be eaten as exemplar
        text = 'm{note="a # {weird} value"} 1 1600000000000\n'
        got = prom_text_to_batches_and_exemplars(text, BASE)
        want = _python_reference(text, BASE)
        _batches_equal(got[0], want[0])
        assert got[0][0].tags[0]["note"] == "a # {weird} value"

    def test_bad_lines_raise_like_python(self):
        for bad in ["{no_name} 1", "m 1 2 3", "m{a=}", "m{a=\"x\"} notanumber",
                    "m{unclosed=\"x\" 1", "m{a=\"1\"} 5 12.5"]:
            with pytest.raises(ValueError):
                prom_text_to_batches_and_exemplars(bad + "\n", BASE)
            with pytest.raises(ValueError):
                _python_reference(bad + "\n", BASE)

    @pytest.mark.parametrize("seed", range(10))
    def test_fuzzed_payloads_match(self, seed):
        rng = random.Random(seed)
        lines = []
        for i in range(rng.randint(50, 200)):
            name = rng.choice(["up", "rq_total", "mem_bytes", "x:y_total"])
            if rng.random() < 0.15:
                lines.append(f"# TYPE {name} {rng.choice(['counter', 'gauge', 'histogram'])}")
                continue
            nl = rng.randint(0, 3)
            labels = ",".join(
                f'{rng.choice("abcdwxyz")}{j}="{rng.choice(["v", "a b", "q,r", "e=f"])}{rng.randint(0, 99)}"'
                for j in range(nl)
            )
            val = rng.choice(["1", "-2.5", "3e7", "NaN", "+Inf", "0.001", "1e-9"])
            ts = f" {BASE + rng.randint(0, 10 ** 6)}" if rng.random() < 0.7 else ""
            body = f"{name}{{{labels}}}" if nl else name
            lines.append(f"{body} {val}{ts}")
        text = "\n".join(lines) + "\n"
        got = prom_text_to_batches_and_exemplars(text, BASE)
        want = _python_reference(text, BASE)
        _batches_equal(got[0], want[0])
        assert got[1] == want[1]

    def test_key_cache_reuse_is_copy_safe(self):
        text = 'm{a="1"} 5 1600000000000\n'
        b1, _ = _native_prom_batches(text, BASE, "default", "default")
        b1[0].tags[0]["mutated"] = "yes"
        b2, _ = _native_prom_batches(text, BASE, "default", "default")
        assert "mutated" not in b2[0].tags[0]

    def test_ws_ns_distinct_cache_entries(self):
        text = "m 1 1600000000000\n"
        a, _ = _native_prom_batches(text, BASE, "w1", "n1")
        b, _ = _native_prom_batches(text, BASE, "w2", "n2")
        assert a[0].tags[0]["_ws_"] == "w1"
        assert b[0].tags[0]["_ws_"] == "w2"


class TestReviewDivergences:
    """Regression corpus from the review: inputs where strtod/byte-scanning
    semantics could diverge from Python — each must behave IDENTICALLY on
    both paths (accept with same data, or raise on both)."""

    CASES = [
        "m 0x10 1600000000000",        # hex float: Python rejects
        "m 1_0",                        # underscore literal: Python accepts (10.0)
        "m 1 +1600000000000",           # '+'-signed ts: Python rejects
        "m 1 99999999999999999999",     # ts overflow: Python raises
        "#TYPE m counter\nm 1",         # no space: NOT a TYPE line for Python
        "# TYPEX m counter\nm 1",       # startswith quirk: IS a TYPE line
        "m 1\rn 2",                     # \r is a line separator
        "\x0cm 1",                      # \f separator
        'm{a="x"}} 1',                  # stray brace: Python's greedy regex accepts
        "m 1\u00a0",                   # Unicode trailing whitespace
        "m 1\u2028n 2",                # U+2028 separator -> python path wholesale
        "m infinity",                   # strtod-only spelling... float() accepts too
        "m1 5\n # HELP m1 x",     # NBSP-prefixed comment: python skips it
        "m1 5\n ",                # NBSP-only line: python skips it
        "m3 nan()",                     # C99 nan(): strtod accepts, float() rejects
        "m3 nan(abc)",                  # C99 nan(chars): same
        "m3 (1)",                       # parens alone: both reject
    ]

    @pytest.mark.parametrize("case", CASES)
    def test_same_outcome_both_paths(self, case):
        text = case + "\n"
        try:
            want = _python_reference(text, BASE)
            want_err = None
        except (ValueError, OverflowError) as e:
            want, want_err = None, type(e)
        try:
            got = prom_text_to_batches_and_exemplars(text, BASE)
            got_err = None
        except (ValueError, OverflowError) as e:
            got, got_err = None, type(e)
        if want_err is not None:
            assert got_err is not None, f"native accepted what python rejects: {case!r}"
        else:
            assert got_err is None, f"native rejected what python accepts: {case!r}"
            _batches_equal(got[0], want[0])
            assert got[1] == want[1]


class TestInfluxNativeParity:
    """Native Influx scanner vs parse_influx_line (same defer contract)."""

    CORPUS = [
        "cpu,host=h1,dc=us value=0.5 1600000000000000000",
        "cpu,host=h2 usage_user=1.5,usage_sys=2.5 1600000000000000000",
        "mem free=1024i,cached=2048i",
        "status,svc=api up=t,degraded=f 1600000001000000000",
        'notes,host=h1 msg="astring",level=3 1600000002000000000',
        "esc\\,metric,ta\\ g=v\\=1 value=9 1600000003000000000",
        "bools a=true,b=False,c=T",
        "neg v=-42.5 -1500000",
        "# a comment",
        "",
        "m value=3e7",
    ]

    def _python(self, text, default_ts):
        from filodb_tpu.core.schemas import METRIC_TAG
        from filodb_tpu.gateway.parsers import parse_influx_line

        tags_list, ts, vals = [], [], []
        for line in text.splitlines():
            for metric, tags, t, v in parse_influx_line(line) or ():
                full = dict(tags)
                full[METRIC_TAG] = metric
                full.setdefault("_ws_", "default")
                full.setdefault("_ns_", "default")
                tags_list.append(full)
                ts.append(t if t is not None else default_ts)
                vals.append(v)
        return tags_list, ts, vals

    def test_corpus_matches_python(self):
        from filodb_tpu.gateway.parsers import influx_to_batch

        text = "\n".join(self.CORPUS) + "\n"
        batch = influx_to_batch(text, BASE)
        wt, wts, wv = self._python(text, BASE)
        assert list(batch.tags) == wt
        np.testing.assert_array_equal(batch.timestamps, np.asarray(wts, np.int64))
        np.testing.assert_array_equal(batch.values["value"], np.asarray(wv))

    BAD = ["m", "m f=", "m f=abc", "m f=1 notanint", "m f=1_0", "m f=0x10",
           "m  f=1", "m f=1 1_0",
           # review regressions: escaped '=' before real '=', \x1f strip,
           # glibc nan(...), quoted value with i-suffix
           "m a\\==1", "\x1fm f=1", "m f=nan(123)", 'm f="x"i']

    @pytest.mark.parametrize("case", BAD)
    def test_divergence_cases_same_outcome(self, case):
        from filodb_tpu.gateway.parsers import influx_to_batch

        text = case + "\n"
        try:
            want = self._python(text, BASE)
            want_err = None
        except (ValueError, OverflowError) as e:
            want, want_err = None, type(e)
        try:
            got = influx_to_batch(text, BASE)
            got_err = None
        except (ValueError, OverflowError) as e:
            got, got_err = None, type(e)
        if want_err is not None:
            assert got_err is not None, f"native accepted, python rejects: {case!r}"
        else:
            assert got_err is None, f"native rejected, python accepts: {case!r}"
            assert list(got.tags) == want[0]
            np.testing.assert_array_equal(got.values["value"], np.asarray(want[2]))

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzzed_influx_match(self, seed):
        from filodb_tpu.gateway.parsers import influx_to_batch

        rng = random.Random(1000 + seed)
        lines = []
        # escaped-char candidates hoisted out of the f-string: a backslash
        # inside an f-string expression is a SyntaxError before py3.12
        tag_vals = ["v1", "x\\,y", "p\\=q"]
        for _ in range(rng.randint(30, 120)):
            meas = rng.choice(["cpu", "mem", "disk\\ io"])
            tags = "".join(
                f",{rng.choice('abcd')}={rng.choice(tag_vals)}"
                for _ in range(rng.randint(0, 2))
            )
            fields = ",".join(
                f"{rng.choice(['value', 'usage', 'free'])}={rng.choice(['1.5', '2i', 't', 'f', '3e4', '-0.25'])}"
                for _ in range(rng.randint(1, 3))
            )
            ts = f" {1_600_000_000_000_000_000 + rng.randint(0, 10 ** 9)}" if rng.random() < 0.8 else ""
            lines.append(f"{meas}{tags} {fields}{ts}")
        text = "\n".join(lines) + "\n"
        batch = influx_to_batch(text, BASE)
        wt, wts, wv = self._python(text, BASE)
        assert list(batch.tags) == wt
        np.testing.assert_array_equal(batch.timestamps, np.asarray(wts, np.int64))
        np.testing.assert_array_equal(batch.values["value"], np.asarray(wv))
