"""Arrow serialization + Flight transport tests (model: reference
FlightQueryProducerSpec / FlightClientManagerSpec — in-process Flight
server round-trips)."""

import numpy as np
import pytest

from filodb_tpu.api import arrow_edge as AE
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.rangevector import Grid, QueryResult
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def make_grid(S=5, J=10, seed=0):
    rng = np.random.default_rng(seed)
    vals = rng.standard_normal((S, J)).astype(np.float32)
    vals[0, 3] = np.nan
    labels = [{"_metric_": "m", "host": f"h{i}"} for i in range(S)]
    return Grid(labels, BASE, 60_000, J, vals)


class TestArrowRoundtrip:
    def test_record_batch_roundtrip(self):
        g = make_grid()
        g2 = AE.record_batch_to_grid(AE.grid_to_record_batch(g))
        assert g2.labels == g.labels
        assert g2.start_ms == g.start_ms and g2.step_ms == g.step_ms
        np.testing.assert_array_equal(g2.values_np(), g.values_np())

    def test_ipc_stream_roundtrip(self):
        res = QueryResult(grids=[make_grid(seed=1), make_grid(S=3, seed=2)])
        data = AE.result_to_ipc(res)
        back = AE.ipc_to_result(data)
        assert len(back.grids) == 2
        np.testing.assert_array_equal(back.grids[0].values_np(), res.grids[0].values_np())

    def test_empty_result(self):
        back = AE.ipc_to_result(AE.result_to_ipc(QueryResult()))
        assert back.grids == []


@pytest.mark.skipif(not AE.HAVE_FLIGHT, reason="pyarrow.flight unavailable")
class TestFlight:
    def test_flight_query_roundtrip(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=4, n_samples=100, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        server = AE.FlightQueryServer(engine)
        try:
            endpoint = f"grpc://127.0.0.1:{server.port}"
            res = AE.FlightQueryClient.query_range(
                endpoint, "sum(heap_usage0)", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60
            )
            assert sum(g.n_series for g in res.grids) == 1
            vals = res.grids[0].values_np()
            assert np.isfinite(vals).all()
            # cross-check against local execution
            local = engine.query_range("sum(heap_usage0)", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
            np.testing.assert_allclose(vals, local.grids[0].values_np(), rtol=1e-6)
        finally:
            server.shutdown()


def test_histogram_grid_roundtrip():
    rng = np.random.default_rng(3)
    S, J, B = 3, 6, 5
    hist = np.cumsum(rng.poisson(2, (S, J, B)), axis=-1).astype(np.float32)
    les = np.array([0.1, 0.5, 1.0, 5.0, np.inf])
    g = Grid([{"_metric_": "h", "i": str(i)} for i in range(S)],
             BASE, 60_000, J, np.full((S, J), np.nan, np.float32), hist=hist, les=les)
    g2 = AE.record_batch_to_grid(AE.grid_to_record_batch(g))
    assert g2.hist is not None
    np.testing.assert_array_equal(g2.hist_np(), hist)
    np.testing.assert_array_equal(g2.les, les)
    # full IPC roundtrip too
    back = AE.ipc_to_result(AE.result_to_ipc(QueryResult(grids=[g])))
    np.testing.assert_array_equal(back.grids[0].hist_np(), hist)


@pytest.mark.skipif(not AE.HAVE_FLIGHT, reason="pyarrow.flight unavailable")
class TestFlightPlanTicket:
    def test_plan_protobuf_ticket(self):
        """Plan-serialization over Flight tickets (reference
        FlightKryoSerDeser): the protobuf plan executes identically to the
        PromQL ticket."""
        from filodb_tpu.query.promql import query_range_to_logical_plan

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), [0])
        ms.ingest("prometheus", 0, machine_metrics(n_series=4, n_samples=100, start_ms=BASE))
        engine = QueryEngine(ms, "prometheus")
        server = AE.FlightQueryServer(engine)
        try:
            endpoint = f"grpc://127.0.0.1:{server.port}"
            s, e = (BASE + 600_000) / 1000, (BASE + 900_000) / 1000
            plan = query_range_to_logical_plan("sum(heap_usage0)", s, e, 60)
            via_plan = AE.FlightQueryClient.execute_plan(endpoint, plan)
            via_promql = AE.FlightQueryClient.query_range(
                endpoint, "sum(heap_usage0)", s, e, 60)
            np.testing.assert_allclose(
                via_plan.grids[0].values_np(), via_promql.grids[0].values_np(),
                rtol=1e-6)
        finally:
            server.shutdown()
