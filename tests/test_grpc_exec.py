"""gRPC RemoteExec tests (reference analog: query_service.proto RemoteExec
exec/executePlan, ProtoConverters round-trip specs in grpc/src/test)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine, SingleClusterPlanner
from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query import logical as L
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.query.proto_plan import (
    PlanDecodeError,
    RemoteExecError,
    frames_to_result,
    plan_from_bytes,
    plan_to_bytes,
    result_to_frames,
)
from filodb_tpu.query.rangevector import Grid, QueryResult, QueryStats, ScalarResult
from filodb_tpu.testkit import counter_batch

START = 1_600_000_000_000


class TestPlanProtoRoundtrip:
    @pytest.mark.parametrize("seed", range(40))
    def test_fuzzed_plans_roundtrip(self, seed):
        """Same corpus as the parser differential fuzz: every generated plan
        must survive proto encode/decode exactly (dataclass equality)."""
        import random

        from test_promql_diff_fuzz import gen_expr

        rng = random.Random(seed)
        q = gen_expr(rng)
        plan = query_range_to_logical_plan(q, 1_600_000_400, 1_600_000_900, 60)
        assert plan_from_bytes(plan_to_bytes(plan)) == plan, q

    def test_metadata_plans_roundtrip(self):
        for plan in [
            L.LabelValues("job", (ColumnFilter("job", "=", "api"),), 1, 2),
            L.LabelNames((), 1, 2),
            L.SeriesKeysByFilters((ColumnFilter("x", "=~", "a.*"),), 1, 2),
            L.TsCardinalities(("ws", "ns"), 3),
        ]:
            assert plan_from_bytes(plan_to_bytes(plan)) == plan

    def test_none_vs_empty_tuple_preserved(self):
        """by=None (no grouping) and by=() (group-all-away) are different
        aggregations — the wire must keep them distinct."""
        inner = L.PeriodicSeries(L.RawSeries((), 0, 10), 0, 10, 1)
        for by in (None, ()):
            p = L.Aggregate("sum", inner, by=by, without=None)
            back = plan_from_bytes(plan_to_bytes(p))
            assert back.by == by and back == p

    def test_in_filter_tuple_value(self):
        f = ColumnFilter("job", "in", ("a", "b"))
        p = L.RawSeries((f,), 5, 9)
        assert plan_from_bytes(plan_to_bytes(p)) == p

    def test_unknown_kind_rejected(self):
        from filodb_tpu.api import query_exec_pb2 as pb

        node = pb.PlanNode(kind="os.system")
        with pytest.raises(PlanDecodeError, match="unknown plan kind"):
            plan_from_bytes(node.SerializeToString())

    def test_unknown_field_rejected(self):
        from filodb_tpu.api import query_exec_pb2 as pb

        node = pb.PlanNode(kind="RawSeries")
        f = node.fields.add(name="nope")
        f.value.ival = 1
        with pytest.raises(PlanDecodeError, match="no field"):
            plan_from_bytes(node.SerializeToString())

    def test_missing_required_field_rejected(self):
        from filodb_tpu.api import query_exec_pb2 as pb

        node = pb.PlanNode(kind="Aggregate")  # no op/inner
        with pytest.raises(PlanDecodeError, match="cannot build"):
            plan_from_bytes(node.SerializeToString())


class TestResultFrames:
    def _roundtrip(self, res, **kw):
        return frames_to_result(iter(list(result_to_frames(res, **kw))))

    def test_grid_roundtrip_with_nans_and_chunking(self):
        vals = np.arange(5 * 7, dtype=np.float32).reshape(5, 7)
        vals[1, 3] = np.nan
        labels = [{"_metric_": "m", "i": str(i)} for i in range(5)]
        res = QueryResult(grids=[Grid(labels, START, 60_000, 7, vals)])
        res.stats = QueryStats(series_scanned=5, samples_scanned=35)
        back = self._roundtrip(res, chunk_rows=2)  # forces 3 chunks
        assert back.grids[0].labels == labels
        np.testing.assert_array_equal(back.grids[0].values_np(), vals)
        assert back.stats.series_scanned == 5
        assert back.stats.samples_scanned == 35

    def test_histogram_grid_roundtrip(self):
        les = np.array([0.5, 1.0, float("inf")])
        hist = np.random.default_rng(0).random((3, 4, 3)).astype(np.float32)
        sums = hist.sum(axis=2)
        labels = [{"_metric_": "h", "i": str(i)} for i in range(3)]
        res = QueryResult(grids=[Grid(labels, START, 1000, 4, sums, hist=hist, les=les)])
        back = self._roundtrip(res)
        np.testing.assert_array_equal(back.grids[0].hist_np(), hist)
        np.testing.assert_array_equal(back.grids[0].les, les)

    def test_scalar_and_metadata_roundtrip(self):
        res = QueryResult()
        res.scalar = ScalarResult(START, 1000, 4, np.array([1.0, 2.5, 3.0, 4.0]))
        res.result_type = "scalar"
        back = self._roundtrip(res)
        assert back.result_type == "scalar"
        np.testing.assert_array_equal(back.scalar.values, res.scalar.values)

        res2 = QueryResult()
        res2.metadata = ["a", "b"]
        res2.result_type = "metadata"
        assert self._roundtrip(res2).metadata == ["a", "b"]

    def test_empty_grid(self):
        res = QueryResult(grids=[Grid([], START, 1000, 4, np.zeros((0, 4), np.float32))])
        back = self._roundtrip(res)
        assert back.grids[0].n_series == 0
        assert back.grids[0].values_np().shape == (0, 4)

    def test_truncated_stream_detected(self):
        vals = np.ones((3, 2), np.float32)
        res = QueryResult(grids=[Grid([{"i": "0"}, {"i": "1"}, {"i": "2"}], START, 1000, 2, vals)])
        frames = list(result_to_frames(res, chunk_rows=2))
        # drop the second chunk: series count no longer matches the header
        with pytest.raises(RemoteExecError, match="series"):
            frames_to_result(iter([frames[0], frames[1], frames[-1]]))


def _make_engine(n_series=12, **params):
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed(
        "prometheus",
        counter_batch(n_series=n_series, n_samples=60, start_ms=START),
        spread=2,
    )
    return QueryEngine(ms, "prometheus", PlannerParams(spread=2, num_shards=4, **params))


class TestGrpcServer:
    @pytest.fixture(scope="class")
    def served(self):
        from filodb_tpu.api.grpc_exec import serve_grpc

        engine = _make_engine()
        server, port = serve_grpc(engine, port=0, host="127.0.0.1")
        yield engine, f"grpc://127.0.0.1:{port}"
        server.stop(grace=0)

    def test_exec_promql_matches_local(self, served):
        from filodb_tpu.api.grpc_exec import exec_promql

        engine, ep = served
        q = "sum(rate(http_requests_total[5m]))"
        s, e, st = START + 400_000, START + 900_000, 60_000
        want = engine.query_range(q, s / 1000, e / 1000, st / 1000)
        got = exec_promql(ep, q, s, e, st)
        np.testing.assert_allclose(
            got.grids[0].values_np(), want.grids[0].values_np(), rtol=1e-6
        )
        assert got.stats.series_scanned == want.stats.series_scanned

    def test_exec_instant(self, served):
        from filodb_tpu.api.grpc_exec import exec_promql

        engine, ep = served
        t = START + 600_000
        got = exec_promql(ep, "http_requests_total", 0, t, 0, instant=True)
        want = engine.query_instant("http_requests_total", t / 1000)
        assert got.result_type == "vector"
        assert len(got.grids[0].labels) == len(want.grids[0].labels)

    def test_execute_plan_matches_promql_path(self, served):
        from filodb_tpu.api.grpc_exec import exec_plan_remote, exec_promql

        _, ep = served
        q = "sum by (instance) (rate(http_requests_total[5m]))"
        s, e, st = START + 400_000, START + 900_000, 60_000
        plan = query_range_to_logical_plan(q, s / 1000, e / 1000, st / 1000)
        via_plan = exec_plan_remote(ep, plan)
        via_promql = exec_promql(ep, q, s, e, st)
        key = lambda g: sorted(map(str, g.labels))
        assert key(via_plan.grids[0]) == key(via_promql.grids[0])
        a = via_plan.grids[0].values_np()[np.argsort(key(via_plan.grids[0]))]
        b = via_promql.grids[0].values_np()[np.argsort(key(via_promql.grids[0]))]
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_metadata_plan_over_grpc(self, served):
        from filodb_tpu.api.grpc_exec import remote_metadata

        engine, ep = served
        vals = remote_metadata(ep, L.LabelValues("instance", (), START, START + 10**7))
        want = engine.label_values((), "instance", START, START + 10**7)
        assert sorted(vals) == sorted(want) and vals

    def test_query_error_propagates_typed(self, served):
        """In-band error frames re-raise as the LOCAL exception classes so
        the origin's API edge maps remote failures to the same status codes
        as local ones (400 bad query, 503 rejection/timeout)."""
        from filodb_tpu.api.grpc_exec import exec_promql
        from filodb_tpu.query.exec.transformers import QueryError

        _, ep = served
        with pytest.raises(QueryError, match="remote QueryError"):
            exec_promql(ep, "sum(rate(m[5m", START, START + 60_000, 60_000)

    def test_plan_decode_error_propagates(self, served):
        import grpc as grpclib

        from filodb_tpu.api import query_exec_pb2 as pb
        from filodb_tpu.api.grpc_exec import _EXECUTE_PLAN, grpc_target

        _, ep = served
        ch = grpclib.insecure_channel(grpc_target(ep))
        call = ch.unary_stream(
            _EXECUTE_PLAN,
            request_serializer=pb.ExecutePlanRequest.SerializeToString,
            response_deserializer=pb.StreamFrame.FromString,
        )
        from filodb_tpu.query.exec.transformers import QueryError

        req = pb.ExecutePlanRequest(plan=pb.PlanNode(kind="__import__"))
        with pytest.raises(QueryError, match="remote PlanDecodeError"):
            frames_to_result(call(req))
        ch.close()


class TestGrpcAuth:
    def test_token_enforced(self):
        from filodb_tpu.api.grpc_exec import exec_promql, serve_grpc

        engine = _make_engine(n_series=4)
        server, port = serve_grpc(engine, port=0, host="127.0.0.1", auth_token="s3cret")
        ep = f"grpc://127.0.0.1:{port}"
        try:
            with pytest.raises(RemoteExecError, match="UNAUTHENTICATED"):
                exec_promql(ep, "up", START, START + 60_000, 60_000)
            with pytest.raises(RemoteExecError, match="UNAUTHENTICATED"):
                exec_promql(ep, "up", START, START + 60_000, 60_000, auth_token="wrong")
            res = exec_promql(
                ep, "http_requests_total", START, START + 600_000, 60_000,
                auth_token="s3cret",
            )
            assert res.grids
        finally:
            server.stop(grace=0)


class TestGrpcPeerPlanning:
    def test_peer_leaves_use_plan_transport(self):
        """grpc:// peers get GrpcPlanRemoteExec leaves carrying the logical
        subtree; aggregate pushdown replaces it with the wrapped Aggregate."""
        from filodb_tpu.api.grpc_exec import GrpcPlanRemoteExec

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        pl = SingleClusterPlanner(
            ms, "prometheus",
            params=PlannerParams(num_shards=4, peer_endpoints=("grpc://peer:7777",)),
        )
        plan = query_range_to_logical_plan(
            "sum(rate(http_requests_total[5m]))", 1_600_000_400, 1_600_000_900, 60
        )
        tree = pl.materialize(plan)
        remotes = [p for p in _walk(tree) if isinstance(p, GrpcPlanRemoteExec)]
        assert len(remotes) == 1
        # pushdown happened: the peer computes mergeable components
        assert isinstance(remotes[0].logical_plan, L.PartialAggregate)
        assert remotes[0].logical_plan.op == "sum"
        assert remotes[0].local_only

    def test_http_peers_still_use_promql(self):
        from filodb_tpu.coordinator.planners import PromQlRemoteExec

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        pl = SingleClusterPlanner(
            ms, "prometheus",
            params=PlannerParams(num_shards=4, peer_endpoints=("http://peer:9090",)),
        )
        plan = query_range_to_logical_plan("up", 1_600_000_400, 1_600_000_900, 60)
        tree = pl.materialize(plan)
        assert any(isinstance(p, PromQlRemoteExec) for p in _walk(tree))


def _walk(plan):
    yield plan
    for c in plan.children():
        yield from _walk(c)


class TestTwoServerGrpcScatter:
    def test_scattered_query_matches_single_host(self):
        """Two FiloServers, each owning half the shards, scattering over
        gRPC plan transport — same assertion as the HTTP multihost test."""
        from filodb_tpu.server import FiloServer

        base = {"dataset": "prometheus", "shards": 8, "grpc_port": 0,
                "query": {"timeout_s": 300}}
        a = FiloServer({**base, "distributed": {"owned_shards": [0, 1, 2, 3]}})
        b = FiloServer({**base, "distributed": {"owned_shards": [4, 5, 6, 7]}})
        try:
            a.start(port=0)
            b.start(port=0)
            a.engine.planner.params.peer_endpoints = (f"grpc://127.0.0.1:{b.grpc_port}",)
            b.engine.planner.params.peer_endpoints = (f"grpc://127.0.0.1:{a.grpc_port}",)
            for srv in (a, b):
                srv.local_engine = QueryEngine(
                    srv.memstore, srv.dataset,
                    PlannerParams(num_shards=8, deadline_s=300),
                )
                srv._grpc = None  # replaced below with local_engine wired in
            # restart grpc servers with local engines (ports were ephemeral)
            from filodb_tpu.api.grpc_exec import serve_grpc

            ga, pa = serve_grpc(a.engine, port=0, host="127.0.0.1", local_engine=a.local_engine)
            gb, pb_ = serve_grpc(b.engine, port=0, host="127.0.0.1", local_engine=b.local_engine)
            a.engine.planner.params.peer_endpoints = (f"grpc://127.0.0.1:{pb_}",)
            b.engine.planner.params.peer_endpoints = (f"grpc://127.0.0.1:{pa}",)

            batch = counter_batch(n_series=24, n_samples=120, start_ms=START)
            na = a.memstore.ingest_routed("prometheus", batch, spread=3)
            nb = b.memstore.ingest_routed("prometheus", batch, spread=3)
            assert na + nb == 24 * 120 and na > 0 and nb > 0

            ms = TimeSeriesMemStore()
            ms.setup(Dataset("prometheus"), range(8))
            ms.ingest_routed(
                "prometheus",
                counter_batch(n_series=24, n_samples=120, start_ms=START),
                spread=3,
            )
            eng = QueryEngine(ms, "prometheus")
            s, e = START / 1000 + 400, START / 1000 + 1100
            q = "sum(rate(http_requests_total[5m]))"
            want = eng.query_range(q, s, e, 60).grids[0].values_np()
            got = a.engine.query_range(q, s, e, 60).grids[0].values_np()
            np.testing.assert_allclose(got, want, rtol=1e-4)

            # plain selector through B sees all 24 series
            sel = b.engine.query_range("http_requests_total", s, e, 60)
            assert sel.grids and sum(g.n_series for g in sel.grids) == 24
            ga.stop(grace=0)
            gb.stop(grace=0)
        finally:
            a.stop()
            b.stop()


def test_plan_remote_env_token_fallback(monkeypatch):
    """Advisor regression: GrpcPlanRemoteExec must fall back to
    FILODB_REMOTE_TOKEN like PromQlRemoteExec, so token-protected gRPC
    federation authenticates without explicit plumbing."""
    from filodb_tpu.api.grpc_exec import GrpcPlanRemoteExec

    monkeypatch.setenv("FILODB_REMOTE_TOKEN", "env-tok")
    p = GrpcPlanRemoteExec("grpc://h:1", logical_plan=None)
    assert p.auth_token == "env-tok"
    p2 = GrpcPlanRemoteExec("grpc://h:1", logical_plan=None, auth_token="explicit")
    assert p2.auth_token == "explicit"
    monkeypatch.delenv("FILODB_REMOTE_TOKEN")
    assert GrpcPlanRemoteExec("grpc://h:1", logical_plan=None).auth_token is None
