"""Streaming preagg maintenance end-to-end: flush feeds the maintainer,
lpopt rewrites serve sum-by queries from the materialized :agg series."""

import numpy as np
import pytest

from filodb_tpu.coordinator.lpopt import (
    AggRuleProvider,
    IncludeAggRule,
    optimize_with_preagg,
)
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.downsample.preagg import PreaggMaintainer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def test_preagg_pipeline_end_to_end():
    provider = AggRuleProvider([
        IncludeAggRule("heap_usage0", frozenset({"job", "_ws_", "_ns_"}))
    ])
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("ds"), [0])
    # 10 series over ~33 min, all sharing job="machine"
    ms.ingest("ds", 0, machine_metrics(n_series=10, n_samples=200, start_ms=BASE))
    m = PreaggMaintainer(ms, "ds", provider)
    sh = ms.shard("ds", 0)
    for part in list(sh.partitions.values()):
        part.switch_buffers()
        assert m.process_chunks(0, part, part.chunks) > 0
    emitted = m.emit(0)
    assert emitted > 0

    # the :agg series exists with the reduced tag set
    from filodb_tpu.core.filters import equals

    pids = sh.lookup_partitions([equals("_metric_", "heap_usage0:agg")], 0, 2**62)
    assert len(pids) == 1
    agg_part = sh.partition(pids[0])
    assert set(agg_part.tags) == {"_metric_", "job", "_ws_", "_ns_"}

    # the preagg sum matches summing the raw series per period
    ts, vals = agg_part.samples_in_range(0, 2**62, "value")
    raw = machine_metrics(n_series=10, n_samples=200, start_ms=BASE)
    want = {}
    for t, v in zip(raw.timestamps, raw.values["value"]):
        p = int(t) // 60_000
        want[p] = want.get(p, 0.0) + float(v)
    for t, v in zip(ts, vals):
        p = int(t) // 60_000
        np.testing.assert_allclose(v, want[p], rtol=1e-9)

    # lpopt rewrite now serves sum by (job) from the :agg series
    from filodb_tpu.query.promql import query_range_to_logical_plan

    plan = query_range_to_logical_plan(
        "sum by (job) (heap_usage0)", (BASE + 600_000) / 1000, (BASE + 1_500_000) / 1000, 60)
    opt = optimize_with_preagg(plan, provider)
    engine = QueryEngine(ms, "ds")
    res = engine.planner.materialize(opt).execute(engine.context())
    series = list(res.all_series())
    assert len(series) == 1
    assert series[0][0] == {"job": "machine"}


def test_emit_watermark_holds_back_recent_periods():
    provider = AggRuleProvider([IncludeAggRule("m", frozenset())])
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=50))
    ms.setup(Dataset("ds"), [0])
    from filodb_tpu.core.records import gauge_batch

    ms.ingest("ds", 0, gauge_batch("m", [({}, BASE + i * 10_000, 1.0) for i in range(50)]))
    m = PreaggMaintainer(ms, "ds", provider)
    sh = ms.shard("ds", 0)
    part = next(iter(sh.partitions.values()))
    part.switch_buffers()
    m.process_chunks(0, part, part.chunks)
    n_early = m.emit(0, up_to_ms=BASE + 120_000)
    assert n_early == 2  # only the first two full minutes
    n_rest = m.emit(0)
    assert n_rest > 0


def test_server_preagg_config():
    from filodb_tpu.server import FiloServer
    from filodb_tpu.core.filters import equals

    srv = FiloServer({
        "shards": 1,
        "max_chunk_size": 100,
        "preagg_rules": [
            {"metric_regex": "heap_usage0", "include_tags": ["job", "_ws_", "_ns_"]},
        ],
    })
    srv.memstore.ingest("prometheus", 0,
                        machine_metrics(n_series=5, n_samples=200, start_ms=BASE))
    srv.flush_now()
    sh = srv.memstore.shard("prometheus", 0)
    pids = sh.lookup_partitions([equals("_metric_", "heap_usage0:agg")], 0, 2**62)
    assert len(pids) == 1
