"""Streaming preagg maintenance: substitutable semantics (last-per-period
per series, cross-series sums), watermark/replacement discipline, recursion
guard, and the engine-served rewrite end-to-end."""

import numpy as np
import pytest

from filodb_tpu.coordinator.lpopt import (
    AggRuleProvider,
    IncludeAggRule,
    optimize_with_preagg,
)
from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.filters import equals
from filodb_tpu.core.records import gauge_batch
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.downsample.preagg import PreaggMaintainer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000
RULES = AggRuleProvider([
    IncludeAggRule("heap_usage0", frozenset({"job", "_ws_", "_ns_"}))
])


def build_preagg(n_series=10, n_samples=200):
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=n_series, n_samples=n_samples, start_ms=BASE))
    m = PreaggMaintainer(ms, "ds", RULES)
    sh = ms.shard("ds", 0)
    for part in list(sh.partitions.values()):
        part.switch_buffers()
        m.process_chunks(0, part, part.chunks)
    m.emit(0)
    return ms, m, sh


def test_agg_values_are_instant_sums():
    """:agg sample at a period end == cross-series sum of each series' last
    raw sample in that period — the substitutable instant-sum semantics."""
    ms, m, sh = build_preagg()
    pids = sh.lookup_partitions([equals("_metric_", "heap_usage0:agg")], 0, 2**62)
    assert len(pids) == 1
    agg_part = sh.partition(pids[0])
    assert set(agg_part.tags) == {"_metric_", "job", "_ws_", "_ns_"}
    ts, vals = agg_part.samples_in_range(0, 2**62, "value")
    assert len(ts) > 10
    raw = machine_metrics(n_series=10, n_samples=200, start_ms=BASE)
    by_series = {}
    for t, v, tags in zip(raw.timestamps, raw.values["value"], raw.tags):
        by_series.setdefault(id(tags), []).append((int(t), float(v)))
    for t_agg, v_agg in zip(ts[:5], vals[:5]):
        want = 0.0
        for samples in by_series.values():
            prior = [v for (t, v) in samples if t <= t_agg]
            want += prior[-1]
        np.testing.assert_allclose(v_agg, want, rtol=1e-9)


def test_watermark_holds_open_period_and_replacement():
    """A period still receiving data must not emit; later flushes replace a
    series' contribution rather than double counting."""
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=4))
    ms.setup(Dataset("ds"), [0])
    rules = AggRuleProvider([IncludeAggRule("m", frozenset())])
    m = PreaggMaintainer(ms, "ds", rules)
    sh = ms.shard("ds", 0)
    # minute-ALIGNED start; 6 samples: 5 in minute 0, 1 at minute-1 boundary
    t0 = (BASE // 60_000 + 1) * 60_000
    ms.ingest("ds", 0, gauge_batch("m", [({}, t0 + i * 12_000, float(i)) for i in range(6)]))
    part = next(iter(sh.partitions.values()))
    chunks1 = list(part.chunks)  # first sealed chunk (4 samples, minute 0)
    m.process_chunks(0, part, chunks1)
    assert m.emit(0) == 0  # minute 0 not closed: contributor max ts inside it
    part.switch_buffers()
    chunks2 = [c for c in part.chunks if c not in chunks1]
    m.process_chunks(0, part, chunks2)
    assert m.emit(0) == 1  # minute 0 closed by minute-1 data
    pids = sh.lookup_partitions([equals("_metric_", "m:agg")], 0, 2**62)
    agg = sh.partition(pids[0])
    ts, vals = agg.samples_in_range(0, 2**62, "value")
    # last sample of minute 0 is i=4 (t=48s): value 4.0, counted ONCE
    np.testing.assert_allclose(vals, [4.0])


def test_agg_output_not_reaggregated():
    """Broad regexes must not recurse onto :agg series."""
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=50))
    ms.setup(Dataset("ds"), [0])
    rules = AggRuleProvider([IncludeAggRule("heap.*", frozenset({"job"}))])
    m = PreaggMaintainer(ms, "ds", rules)
    sh = ms.shard("ds", 0)
    ms.ingest("ds", 0, machine_metrics(n_series=3, n_samples=120, start_ms=BASE))
    for _ in range(3):  # several flush cycles
        for part in list(sh.partitions.values()):
            part.switch_buffers()
            m.process_chunks(0, part, part.chunks)
        m.emit(0)
    metrics = set(sh.index.label_values([], "_metric_", 0, 2**62))
    assert "heap_usage0:agg" in metrics
    assert not any(x.endswith(":agg:agg") for x in metrics)


def test_server_query_served_from_preagg():
    """The full loop: server config -> flush maintains :agg -> HTTP-path
    query rewrites onto it (verified via plan tree + value sanity)."""
    from filodb_tpu.server import FiloServer

    srv = FiloServer({
        "shards": 1,
        "max_chunk_size": 100,
        "preagg_rules": [
            {"metric_regex": "heap_usage0", "include_tags": ["job", "_ws_", "_ns_"]},
        ],
    })
    srv.memstore.ingest("prometheus", 0,
                        machine_metrics(n_series=10, n_samples=200, start_ms=BASE))
    srv.flush_now()
    start_s = (BASE + 600_000) / 1000
    end_s = (BASE + 1_500_000) / 1000
    res = srv.engine.query_range("sum by (job) (heap_usage0)", start_s, end_s, 60)
    series = list(res.all_series())
    assert len(series) == 1
    # served from ONE :agg series: only one series scanned, not ten
    assert res.stats.series_scanned == 1
    # values approximate the true instant sum (preagg resolution granularity)
    want = srv.engine.query_range("no_optimize(sum by (job) (heap_usage0))", start_s, end_s, 60)
    got_v = series[0][2]
    want_v = list(want.all_series())[0][2]
    n = min(len(got_v), len(want_v))
    # the rewrite answers at preagg resolution: individual steps differ by
    # gauge sampling noise; the level must agree
    np.testing.assert_allclose(np.mean(got_v[:n]), np.mean(want_v[:n]), rtol=0.05)


def test_bad_rule_config_rejected():
    from filodb_tpu.server import FiloServer

    with pytest.raises(ValueError, match="preagg_rules"):
        FiloServer({"shards": 1, "preagg_rules": [{"metric_regex": "m"}]})
    with pytest.raises(ValueError, match="preagg_rules"):
        FiloServer({"shards": 1, "preagg_rules": [
            {"metric_regex": "m", "include_tags": [], "exclude_tags": ["x"]}]})
