"""Pallas fused window-aggregate kernel vs the general kernel (interpret
mode on CPU; the same kernel compiles for TPU with interpret=False)."""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops import pallas_kernels as PK
from filodb_tpu.ops.staging import stage_series

BASE = 1_600_000_000_000


def make_block(n_series=5, n=200, seed=0, counter=False):
    rng = np.random.default_rng(seed)
    series = []
    for i in range(n_series):
        ts = BASE + np.cumsum(rng.integers(5000, 15000, n)).astype(np.int64)
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            k = n // 2
            vals[k:] -= vals[k] - 3.0
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        series.append((ts, vals))
    return stage_series(series, BASE, counter_corrected=counter)


def compare(func, counter=False, seed=0):
    block = make_block(seed=seed, counter=counter)
    params = K.RangeParams(BASE + 400_000, 60_000, 20, 300_000)
    got = np.asarray(
        PK.run_pallas_range_function(func, block, params, is_counter=counter)
    )[:5, :20]
    want = np.asarray(
        K.run_range_function(func, block, params, is_counter=counter)
    )[:5, :20]
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want), err_msg=func)
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m], rtol=2e-4, atol=1e-4, err_msg=func)


@pytest.mark.parametrize("func", sorted(PK.PALLAS_FUNCS - {"rate", "increase", "delta"}))
def test_pallas_matches_general_gauge(func):
    compare(func, counter=False, seed=3)


@pytest.mark.parametrize("func", ["rate", "increase", "delta"])
def test_pallas_matches_general_counter(func):
    compare(func, counter=True, seed=4)


def test_padding_of_series_dimension():
    # 5 series pads to 8 internally; BS=64 tiling pads to 64 — outputs for
    # real rows must be unaffected
    block = make_block(n_series=3, n=100, seed=7)
    params = K.RangeParams(BASE + 400_000, 60_000, 7, 300_000)
    got = np.asarray(PK.run_pallas_range_function("sum_over_time", block, params))[:3, :7]
    want = np.asarray(K.run_range_function("sum_over_time", block, params))[:3, :7]
    np.testing.assert_allclose(got, want, rtol=1e-5, equal_nan=True)


def test_nan_sample_confined_to_its_window():
    """Review regression: one NaN sample must not poison the whole step tile
    (the one-hot accumulation must select, not multiply)."""
    import numpy as np

    from filodb_tpu.ops import kernels as K
    from filodb_tpu.ops import pallas_kernels as PK
    from filodb_tpu.ops import staging as ST

    base = 1_600_000_000_000
    ts = base + np.arange(5, dtype=np.int64) * 1_000
    vals = np.array([1.0, 2.0, np.nan, 4.0, 5.0])
    block = ST.stage_series([(ts, vals)], base)
    params = K.RangeParams(base + 1_000, 1_000, PK.BJ, 1_000)
    out = np.asarray(PK.run_pallas_range_function("sum_over_time", block, params))[0, :5]
    # windows: step k covers (t_k-1s, t_k] = exactly sample k+1
    expect = [2.0, np.nan, 4.0, 5.0]
    np.testing.assert_allclose(out[:4], expect, equal_nan=True)
