"""PromQL semantic edge cases end-to-end (model: reference WindowIteratorSpec
boundary cases + exp-histogram query specs)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.histograms import base2_exp_buckets
from filodb_tpu.core.schemas import OTEL_EXP_DELTA_HISTOGRAM, Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import counter_batch, histogram_batch, machine_metrics

BASE = 1_600_000_000_000


@pytest.fixture(scope="module")
def engine():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus", machine_metrics(n_series=4, n_samples=200, start_ms=BASE), spread=2)
    ms.ingest_routed("prometheus", counter_batch(n_series=4, n_samples=200, start_ms=BASE), spread=2)
    scheme = base2_exp_buckets(scale=1, start_index=-4, num=12)
    ms.ingest_routed(
        "prometheus",
        histogram_batch(n_series=3, n_samples=200, start_ms=BASE, scheme=scheme,
                        metric="exp_latency", schema=OTEL_EXP_DELTA_HISTOGRAM),
        spread=2,
    )
    return QueryEngine(ms, "prometheus")


class TestLookbackBoundaries:
    def test_sample_exactly_at_lookback_edge_excluded(self, engine):
        # samples at BASE, BASE+10s, ... lookback 5m; eval at t: window (t-5m, t]
        # choose t such that t - 5m == BASE exactly -> BASE sample excluded
        t = (BASE + 300_000) / 1000
        res = engine.query_instant("count_over_time(heap_usage0[5m])", t)
        for _, _, vals in res.all_series():
            # samples strictly > BASE and <= BASE+300s: 10s grid -> 30 samples
            assert vals[-1] == 30

    def test_instant_vector_uses_latest_in_lookback(self, engine):
        t = (BASE + 1_000_000) / 1000
        res = engine.query_instant("heap_usage0", t)
        batch = machine_metrics(n_series=4, n_samples=200, start_ms=BASE)
        by_inst = {g.tags["instance"]: g for g in batch.group_by_series()}
        for lbls, ts, vals in res.all_series():
            src = by_inst[lbls["instance"]]
            idx = np.searchsorted(src.timestamps, t * 1000, side="right") - 1
            np.testing.assert_allclose(vals[-1], src.values["value"][idx], rtol=1e-5)

    def test_stale_beyond_lookback_absent(self, engine):
        # evaluate far past the data end: no output points
        t = (BASE + 200 * 10_000 + 600_000) / 1000
        res = engine.query_instant("heap_usage0", t)
        assert not list(res.all_series())


class TestGridShapes:
    def test_step_larger_than_window_leaves_gaps(self, engine):
        res = engine.query_range(
            "sum_over_time(heap_usage0[30s])", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 120.0
        )
        for _, ts, vals in res.all_series():
            assert len(vals) > 0  # sparse but present where data exists

    def test_offset_shifts_results(self, engine):
        r1 = engine.query_range("heap_usage0", (BASE + 900_000) / 1000, (BASE + 1_200_000) / 1000, 60.0)
        r2 = engine.query_range(
            "heap_usage0 offset 5m", (BASE + 1_200_000) / 1000, (BASE + 1_500_000) / 1000, 60.0
        )
        m1 = {tuple(sorted(l.items())): v for l, t, v in r1.all_series()}
        m2 = {tuple(sorted(l.items())): v for l, t, v in r2.all_series()}
        for k, v in m1.items():
            np.testing.assert_allclose(m2[k], v, rtol=1e-5)

    def test_at_modifier_constant_across_steps(self, engine):
        res = engine.query_range(
            f"heap_usage0 @ {(BASE + 1_000_000) / 1000}", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60.0
        )
        for _, _, vals in res.all_series():
            assert len(set(np.round(vals, 5))) == 1


class TestExpHistograms:
    def test_exp_histogram_quantile_e2e(self, engine):
        res = engine.query_range(
            "histogram_quantile(0.9, rate(exp_latency[5m]))",
            (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60.0,
        )
        series = list(res.all_series())
        assert len(series) == 3
        for _, _, vals in series:
            assert np.isfinite(vals).all() and (vals > 0).all()

    def test_exp_histogram_sum_quantile(self, engine):
        res = engine.query_range(
            "histogram_quantile(0.5, sum(rate(exp_latency[5m])))",
            (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60.0,
        )
        assert len(list(res.all_series())) == 1


class TestNameHandling:
    def test_rate_drops_metric_name(self, engine):
        res = engine.query_range(
            "rate(http_requests_total[5m])", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60.0
        )
        for lbls, _, _ in res.all_series():
            assert "_metric_" not in lbls and "__name__" not in lbls

    def test_last_over_time_keeps_metric_name(self, engine):
        res = engine.query_range(
            "last_over_time(heap_usage0[5m])", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60.0
        )
        for lbls, _, _ in res.all_series():
            assert lbls.get("_metric_") == "heap_usage0"

    def test_comparison_keeps_name_without_bool(self, engine):
        res = engine.query_range(
            "heap_usage0 > 0", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60.0
        )
        for lbls, _, _ in res.all_series():
            assert lbls.get("_metric_") == "heap_usage0"


class TestInstantSubquery:
    def test_top_level_subquery_instant(self, engine):
        res = engine.query_instant("heap_usage0[10m:1m]", (BASE + 1_200_000) / 1000)
        series = list(res.all_series())
        assert len(series) == 4
        _, ts, _ = series[0]
        assert len(ts) >= 9  # ~10 substeps

    def test_empty_selector_result(self, engine):
        res = engine.query_range(
            "no_such_metric", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60.0
        )
        assert not list(res.all_series())


@pytest.fixture(scope="module")
def hist_engine():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0, 1])
    ms.ingest_routed("prometheus", histogram_batch(n_series=3, n_samples=200, start_ms=BASE), spread=1)
    return QueryEngine(ms, "prometheus")


HS_START = (BASE + 600_000) / 1000
HS_END = (BASE + 1_500_000) / 1000


class TestHistogramSuffixRewrites:
    """m_sum / m_count / m_bucket classic-histogram compatibility
    (reference MultiSchemaPartitionsExec rewrites :49-80)."""

    def test_sum_suffix_reads_sum_column(self, hist_engine):
        res = hist_engine.query_range(
            "rate(http_request_latency_sum[5m])", HS_START, HS_END, 60.0)
        series = list(res.all_series())
        assert len(series) == 3
        for _, _, vals in series:
            assert (vals >= 0).all()

    def test_count_suffix_reads_count_column(self, hist_engine):
        res = hist_engine.query_range(
            "rate(http_request_latency_count[5m])", HS_START, HS_END, 60.0)
        assert len(list(res.all_series())) == 3

    def test_bucket_suffix_selects_le(self, hist_engine):
        res = hist_engine.query_range(
            'rate(http_request_latency_bucket{le="+Inf"}[5m])', HS_START, HS_END, 60.0)
        series = list(res.all_series())
        assert len(series) == 3
        for lbls, _, vals in series:
            assert lbls["le"] == "+Inf"
            assert (vals >= 0).all()
        # +Inf bucket rate equals the count-column rate
        res2 = hist_engine.query_range(
            "rate(http_request_latency_count[5m])", HS_START, HS_END, 60.0)
        m1 = {l["instance"]: v for l, _, v in series}
        m2 = {l["instance"]: v for l, _, v in res2.all_series()}
        for k in m1:
            np.testing.assert_allclose(m1[k], m2[k], rtol=1e-3)

    def test_unknown_bucket_empty(self, hist_engine):
        res = hist_engine.query_range(
            'rate(http_request_latency_bucket{le="123.456"}[5m])', HS_START, HS_END, 60.0)
        assert not list(res.all_series())


class TestWindowedOffset:
    def test_rate_offset_shifts_window(self, engine):
        r1 = engine.query_range(
            "rate(http_requests_total[5m])", (BASE + 900_000) / 1000, (BASE + 1_200_000) / 1000, 60.0)
        r2 = engine.query_range(
            "rate(http_requests_total[5m] offset 5m)",
            (BASE + 1_200_000) / 1000, (BASE + 1_500_000) / 1000, 60.0)
        m1 = {tuple(sorted(l.items())): v for l, _, v in r1.all_series()}
        m2 = {tuple(sorted(l.items())): v for l, _, v in r2.all_series()}
        assert m1.keys() == m2.keys()
        for k in m1:
            np.testing.assert_allclose(m2[k], m1[k], rtol=1e-4)

    def test_agg_of_offset_window(self, engine):
        res = engine.query_range(
            "sum(rate(http_requests_total[5m] offset 2m))",
            (BASE + 900_000) / 1000, (BASE + 1_200_000) / 1000, 60.0)
        assert len(list(res.all_series())) == 1

    def test_sum_without(self, engine):
        res = engine.query_range(
            "sum without (instance) (rate(http_requests_total[5m]))",
            (BASE + 900_000) / 1000, (BASE + 1_200_000) / 1000, 60.0)
        series = list(res.all_series())
        assert len(series) == 1
        lbls = series[0][0]
        assert "instance" not in lbls and "_metric_" not in lbls
        assert lbls.get("job") == "api"


class TestMoreFunctionsE2E:
    def test_histogram_fraction_e2e(self, hist_engine):
        res = hist_engine.query_range(
            "histogram_fraction(0, 1, rate(http_request_latency[5m]))",
            HS_START, HS_END, 60.0)
        series = list(res.all_series())
        assert len(series) == 3
        for _, _, vals in series:
            assert ((vals >= 0) & (vals <= 1)).all()

    def test_predict_linear_e2e(self, engine):
        res = engine.query_range(
            "predict_linear(heap_usage0[10m], 3600)",
            (BASE + 900_000) / 1000, (BASE + 1_500_000) / 1000, 60.0)
        assert len(list(res.all_series())) == 4

    def test_deriv_e2e(self, engine):
        res = engine.query_range(
            "deriv(heap_usage0[10m])", (BASE + 900_000) / 1000, (BASE + 1_500_000) / 1000, 60.0)
        assert len(list(res.all_series())) == 4

    def test_holt_winters_e2e(self, engine):
        res = engine.query_range(
            "holt_winters(heap_usage0[10m], 0.5, 0.1)",
            (BASE + 900_000) / 1000, (BASE + 1_500_000) / 1000, 60.0)
        assert len(list(res.all_series())) == 4
