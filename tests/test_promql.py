"""PromQL parser golden tests (model: reference prometheus parser specs —
LegacyParser/AntlrParser golden LogicalPlan assertions, Parser.scala:40-52)."""

import math

import pytest

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.query import promql as P
from filodb_tpu.query.logical import (
    Aggregate,
    ApplyAbsentFunction,
    ApplyInstantFunction,
    ApplyMiscellaneousFunction,
    ApplySortFunction,
    BinaryJoin,
    PeriodicSeries,
    PeriodicSeriesWithWindowing,
    RawSeries,
    ScalarBinaryOperation,
    ScalarFixedDoublePlan,
    ScalarTimeBasedPlan,
    ScalarVaryingDoublePlan,
    ScalarVectorBinaryOperation,
    SubqueryWithWindowing,
    TopLevelSubquery,
)

START, END, STEP = 1000.0, 2000.0, 15.0


def plan(q):
    return P.query_range_to_logical_plan(q, START, END, STEP)


class TestDurations:
    @pytest.mark.parametrize(
        "text,ms",
        [("5m", 300_000), ("1h30m", 5_400_000), ("30s", 30_000), ("100ms", 100),
         ("2d", 172_800_000), ("1w", 604_800_000), ("1y", 31_536_000_000)],
    )
    def test_parse_duration(self, text, ms):
        assert P.parse_duration_ms(text) == ms


class TestSelectors:
    def test_simple_metric(self):
        p = plan("http_requests_total")
        assert isinstance(p, PeriodicSeries)
        assert ColumnFilter("_metric_", "=", "http_requests_total") in p.raw.filters
        assert p.start_ms == 1_000_000 and p.end_ms == 2_000_000 and p.step_ms == 15_000

    def test_matchers(self):
        p = plan('cpu{job="api", env!="dev", host=~"h.*", dc!~"us|eu"}')
        ops = {(f.column, f.op) for f in p.raw.filters}
        assert ("job", "=") in ops and ("env", "!=") in ops
        assert ("host", "=~") in ops and ("dc", "!~") in ops

    def test_name_matcher_normalized(self):
        p = plan('{__name__="cpu", job="api"}')
        assert ColumnFilter("_metric_", "=", "cpu") in p.raw.filters

    def test_raw_export(self):
        p = plan("cpu[5m]")
        assert isinstance(p, RawSeries)

    def test_offset(self):
        p = plan("cpu offset 5m")
        assert isinstance(p, PeriodicSeries) and p.offset_ms == 300_000
        assert p.raw.end_ms == 2_000_000 - 300_000

    def test_negative_offset(self):
        p = plan("cpu offset -5m")
        assert p.offset_ms == -300_000

    def test_at_modifier(self):
        p = plan("cpu @ 1500")
        assert p.at_ms == 1_500_000
        p2 = plan("cpu @ start()")
        assert p2.at_ms == 1_000_000
        p3 = plan("cpu @ end()")
        assert p3.at_ms == 2_000_000


class TestRangeFunctions:
    def test_rate(self):
        p = plan("rate(http_requests_total[5m])")
        assert isinstance(p, PeriodicSeriesWithWindowing)
        assert p.function == "rate" and p.window_ms == 300_000
        assert p.raw.start_ms == 1_000_000 - 300_000

    def test_rate_with_offset(self):
        p = plan("rate(cpu[5m] offset 1h)")
        assert p.offset_ms == 3_600_000
        assert p.raw.end_ms == 2_000_000 - 3_600_000

    def test_quantile_over_time_scalar_first(self):
        p = plan("quantile_over_time(0.99, latency[10m])")
        assert p.function == "quantile_over_time" and p.function_args == (0.99,)

    def test_predict_linear(self):
        p = plan("predict_linear(disk_free[1h], 3600)")
        assert p.function == "predict_linear" and p.function_args == (3600.0,)

    def test_holt_winters(self):
        p = plan("holt_winters(cpu[10m], 0.5, 0.1)")
        assert p.function == "double_exponential_smoothing"
        assert p.function_args == (0.5, 0.1)

    @pytest.mark.parametrize("fn", [
        "increase", "delta", "idelta", "irate", "resets", "changes", "deriv",
        "avg_over_time", "min_over_time", "max_over_time", "sum_over_time",
        "count_over_time", "stddev_over_time", "stdvar_over_time",
        "last_over_time", "present_over_time", "absent_over_time",
    ])
    def test_all_simple_range_fns(self, fn):
        p = plan(f"{fn}(m[5m])")
        assert isinstance(p, PeriodicSeriesWithWindowing)


class TestAggregations:
    def test_sum_by(self):
        p = plan("sum by (job) (rate(cpu[5m]))")
        assert isinstance(p, Aggregate) and p.op == "sum" and p.by == ("job",)
        assert isinstance(p.inner, PeriodicSeriesWithWindowing)

    def test_suffix_by(self):
        p = plan("sum(rate(cpu[5m])) by (job, dc)")
        assert p.by == ("job", "dc")

    def test_without(self):
        p = plan("avg without (instance) (cpu)")
        assert p.op == "avg" and p.without == ("instance",)

    def test_topk(self):
        p = plan("topk(5, cpu)")
        assert p.op == "topk" and p.params == (5.0,)

    def test_quantile_agg(self):
        p = plan("quantile(0.9, cpu)")
        assert p.op == "quantile" and p.params == (0.9,)

    def test_count_values(self):
        p = plan('count_values("version", build_info)')
        assert p.op == "count_values" and p.params == ("version",)

    @pytest.mark.parametrize("op", ["sum", "min", "max", "avg", "count", "stddev", "stdvar", "group"])
    def test_all_simple_aggs(self, op):
        p = plan(f"{op}(cpu)")
        assert isinstance(p, Aggregate) and p.op == op


class TestBinary:
    def test_vector_vector(self):
        p = plan("a + b")
        assert isinstance(p, BinaryJoin) and p.op == "+" and p.cardinality == "one-to-one"

    def test_precedence(self):
        p = plan("a + b * c")
        assert p.op == "+" and isinstance(p.rhs, BinaryJoin) and p.rhs.op == "*"

    def test_power_right_assoc(self):
        p = plan("2 ^ 3 ^ 2")
        assert isinstance(p, ScalarBinaryOperation)
        rhs = p.rhs
        assert isinstance(rhs, ScalarBinaryOperation) and rhs.op == "^"

    def test_scalar_vector(self):
        p = plan("cpu * 8")
        assert isinstance(p, ScalarVectorBinaryOperation) and not p.scalar_is_lhs

    def test_comparison_bool(self):
        p = plan("cpu > bool 0.5")
        assert isinstance(p, ScalarVectorBinaryOperation) and p.return_bool

    def test_on_group_left(self):
        p = plan("a * on (job) group_left (extra) b")
        assert p.on == ("job",) and p.cardinality == "many-to-one" and p.include == ("extra",)

    def test_ignoring(self):
        p = plan("a / ignoring (instance) b")
        assert p.ignoring == ("instance",)

    @pytest.mark.parametrize("op", ["and", "or", "unless"])
    def test_set_ops(self, op):
        p = plan(f"a {op} b")
        assert isinstance(p, BinaryJoin) and p.op == op and p.cardinality == "many-to-many"

    def test_unary_minus(self):
        p = plan("-cpu")
        assert isinstance(p, ScalarVectorBinaryOperation) and p.op == "*"


class TestInstantAndMisc:
    def test_instant_fn(self):
        p = plan("abs(cpu)")
        assert isinstance(p, ApplyInstantFunction) and p.function == "abs"

    def test_clamp(self):
        p = plan("clamp(cpu, 0, 100)")
        assert p.function == "clamp" and p.args == (0.0, 100.0)

    def test_histogram_quantile(self):
        p = plan("histogram_quantile(0.9, rate(latency[5m]))")
        assert p.function == "histogram_quantile" and p.args == (0.9,)
        assert isinstance(p.inner, PeriodicSeriesWithWindowing)

    def test_absent(self):
        p = plan('absent(cpu{job="x"})')
        assert isinstance(p, ApplyAbsentFunction)
        assert ColumnFilter("job", "=", "x") in p.filters

    def test_sort(self):
        assert isinstance(plan("sort(cpu)"), ApplySortFunction)
        assert plan("sort_desc(cpu)").descending

    def test_label_replace(self):
        p = plan('label_replace(cpu, "dst", "$1", "src", "(.*)")')
        assert isinstance(p, ApplyMiscellaneousFunction)
        assert p.str_args == ("dst", "$1", "src", "(.*)")

    def test_scalar_vector_wrappers(self):
        assert isinstance(plan("scalar(cpu)"), ScalarVaryingDoublePlan)
        assert isinstance(plan("vector(1)"), ScalarVaryingDoublePlan)

    def test_time(self):
        assert isinstance(plan("time()"), ScalarTimeBasedPlan)

    def test_number_literals(self):
        assert plan("42").value == 42.0
        assert plan("0x1F").value == 31.0
        assert math.isinf(plan("Inf").value)
        assert math.isnan(plan("NaN").value)
        assert plan("1e3").value == 1000.0


class TestSubqueries:
    def test_windowed_subquery(self):
        p = plan("max_over_time(rate(cpu[1m])[30m:1m])")
        assert isinstance(p, SubqueryWithWindowing)
        assert p.function == "max_over_time"
        assert p.window_ms == 1_800_000 and p.sub_step_ms == 60_000
        assert isinstance(p.inner, PeriodicSeriesWithWindowing)

    def test_default_substep(self):
        p = plan("avg_over_time(cpu[10m:])")
        assert p.sub_step_ms == 60_000

    def test_top_level_subquery(self):
        p = plan("cpu[30m:5m]")
        assert isinstance(p, TopLevelSubquery)
        assert isinstance(p.inner, PeriodicSeries)
        assert p.inner.step_ms == 300_000


class TestErrors:
    @pytest.mark.parametrize("q", [
        "cpu{job=api}",          # unquoted value
        "rate(cpu)",             # missing window
        "sum(a, b)",             # too many args
        "cpu[5m",                # unclosed
        "and",                   # bare keyword
        "topk(cpu)",             # missing param
        "1 and 2",               # set op on scalars
    ])
    def test_rejects(self, q):
        with pytest.raises(P.PromQLError):
            plan(q)
