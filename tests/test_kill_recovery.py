"""Crash-recovery end-to-end (reference standalone multi-jvm
IngestionAndRecoverySpec: ingest -> kill -9 -> restart -> query
correctness). A real server process starts on a persistent store, is fed
over HTTP, flushed via /admin/flush, killed with SIGKILL, restarted on the
same store, and must answer the same query with the same values."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time
import urllib.parse
import urllib.request

import numpy as np

BASE = 1_600_000_000_000

SERVER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from filodb_tpu.server import FiloServer
    srv = FiloServer({
        "dataset": "prometheus", "shards": 4,
        "store_root": sys.argv[1],
        "query": {"timeout_s": 300},
    })
    port = srv.start(port=0)
    print(f"PORT={port}", flush=True)
    import threading
    threading.Event().wait()
""")


def _start(store):
    import selectors

    proc = subprocess.Popen(
        [sys.executable, "-c", SERVER, store],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    # readline with a real timeout: a wedged child (the TPU-plugin failure
    # mode) would otherwise block the whole suite on readline forever
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.time() + 120
    buf = ""
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"server died: {proc.stdout.read()[-2000:]}")
        if not sel.select(timeout=1):
            continue
        line = proc.stdout.readline()
        buf += line
        if line.startswith("PORT="):
            sel.close()
            return proc, int(line.strip().split("=")[1])
    proc.kill()
    raise TimeoutError(f"server did not start within 120s: {buf[-2000:]}")


def _get(url):
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _post(url, body=b""):
    req = urllib.request.Request(url, data=body, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def test_kill_dash_nine_then_recover(tmp_path):
    store = str(tmp_path / "store")
    q = urllib.parse.quote("sum(rate(rq_total[5m]))")
    qpath = (f"/api/v1/query_range?query={q}"
             f"&start={(BASE + 400_000) / 1000}&end={(BASE + 3_000_000) / 1000}&step=60")

    proc, port = _start(store)
    try:
        lines = ["# TYPE rq_total counter"]
        for s in range(3):
            for i in range(60):
                lines.append(f'rq_total{{inst="h{s}"}} {100 * s + 10 * i} {BASE + i * 60_000}')
        out = _post(f"http://127.0.0.1:{port}/ingest/prom", "\n".join(lines).encode())
        assert out["data"]["ingested"] == 180
        flushed = _post(f"http://127.0.0.1:{port}/admin/flush")
        assert flushed["data"]["chunks_written"] > 0
        before = _get(f"http://127.0.0.1:{port}{qpath}")
        assert before["data"]["result"], "query empty before kill"
        want = [(t, float(v)) for t, v in before["data"]["result"][0]["values"]]
    finally:
        os.kill(proc.pid, signal.SIGKILL)  # no warning, no cleanup
        proc.wait(timeout=30)

    proc2, port2 = _start(store)
    try:
        after = _get(f"http://127.0.0.1:{port2}{qpath}")
        assert after["data"]["result"], "query empty after recovery"
        got = [(t, float(v)) for t, v in after["data"]["result"][0]["values"]]
        assert [t for t, _ in got] == [t for t, _ in want]
        np.testing.assert_allclose(
            [v for _, v in got], [v for _, v in want], rtol=1e-5
        )
        # series-level metadata also recovered
        m = urllib.parse.quote("rq_total")
        series = _get(f"http://127.0.0.1:{port2}/api/v1/series?match[]={m}")["data"]
        assert len(series) == 3
    finally:
        proc2.kill()
        proc2.wait(timeout=30)
