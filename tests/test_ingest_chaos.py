"""Ingest-chaos tests (doc/robustness.md "superblock consistency model"):
the fused single-dispatch path and the downsample tier must stay CORRECT
and LIVE under sustained concurrent ingest.

Three families, mirroring the failure modes this suite exists to pin:

- staging-cache liveness: a block staged concurrently with DISJOINT-range
  ingest must still be cached (the old version-equality insert guard
  starved the cache under fine-grained ingest), and a warm superblock must
  survive disjoint ingest (revalidate) or absorb overlapping live-edge
  appends in place (extend) — the warm canonical query stays exactly ONE
  kernel dispatch across an overlapping append;
- queries racing fine-grained ingest: threaded soak with a seeded stream,
  checked by invariants (final parity vs the reference tree, a warm
  single-dispatch query after quiesce);
- downsample maintenance: the _release TOCTOU (deterministically
  reproduced via the race hook), claim-steal storms, crash-mid-commit
  redo, and the merge-commit contract (batch output must never wipe
  streaming-downsampled segments).

Everything is seeded; the threaded soak asserts only schedule-independent
invariants, so the suite is tier-1 safe.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.metrics import REGISTRY
from filodb_tpu.testkit import counter_batch

pytestmark = pytest.mark.ingest_chaos

BASE = 1_600_000_000_000
N_SHARDS = 8
N_SERIES = 48
N_SAMPLES = 300
HEAD_MS = BASE + N_SAMPLES * 10_000  # first timestamp past the seed data
START = (BASE + 600_000) / 1000
STEP = 60
Q = "sum by (job) (rate(http_requests_total[5m]))"


def _dispatch_total() -> int:
    total = 0
    with REGISTRY._lock:
        for (name, _labels), m in REGISTRY._metrics.items():
            if name == "filodb_kernel_dispatch_seconds":
                total += m.total
    return total


def _counter_sum(name: str) -> float:
    with REGISTRY._lock:
        return sum(
            m.value for (n, _labels), m in REGISTRY._metrics.items()
            if n == name
        )


def _counter(name: str, **labels) -> float:
    return REGISTRY.counter(name, **labels).value


def _make_store(n_samples: int = N_SAMPLES):
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    ms.ingest_routed(
        "ds",
        counter_batch(n_series=N_SERIES, n_samples=n_samples, start_ms=BASE),
        spread=3,
    )
    return ms


def _append(ms, n_batches: int = 1, start_ms: int = HEAD_MS,
            n_series: int = N_SERIES, seed: int = 7):
    """Live-edge continuation batches: same tag set as the seed data (same
    seed => same series), timestamps past the current head."""
    for b in range(n_batches):
        ms.ingest_routed(
            "ds",
            counter_batch(n_series=n_series, n_samples=1,
                          start_ms=start_ms + b * 10_000, seed=seed),
            spread=3,
        )


def _rows(res):
    out = {}
    for g in res.grids:
        for lbls, vals in zip(g.labels, g.values_np()):
            out[tuple(sorted(lbls.items()))] = np.asarray(vals)
    return out


def _assert_parity(fused_res, ref_res):
    a, b = _rows(fused_res), _rows(ref_res)
    assert a.keys() == b.keys()
    for k in a:
        na, nb = np.isnan(a[k]), np.isnan(b[k])
        assert (na == nb).all(), (k, "NaN masks differ")
        np.testing.assert_allclose(a[k][~na], b[k][~nb], rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# superblock maintenance: extend / revalidate / single-dispatch guarantee


def test_warm_query_single_dispatch_across_overlapping_append():
    """THE acceptance bar: an overlapping live-edge append must be absorbed
    by extending the device superblock in place — the next warm canonical
    query issues exactly ONE kernel dispatch (not a re-stage), and its
    result matches the reference tree bit-for-bit-ish."""
    ms = _make_store()
    fused = QueryEngine(ms, "ds")
    ref = QueryEngine(ms, "ds", PlannerParams(fused_aggregate=False))
    end = (HEAD_MS + 120_000) / 1000  # live-edge range: reaches past head
    fused.query_range(Q, START, end, STEP)  # cold: build + cache
    fused.query_range(Q, START, end, STEP)  # warm hit

    before_ext = _counter("filodb_superblock_maintenance", outcome="extend")
    for i in range(3):  # repeated scrapes: every one must extend, not restage
        _append(ms, start_ms=HEAD_MS + i * 10_000)
        before = _dispatch_total()
        rf = fused.query_range(Q, START, end, STEP)
        assert _dispatch_total() - before == 1, (
            "warm query across an overlapping append must stay ONE dispatch"
        )
    assert _counter("filodb_superblock_maintenance", outcome="extend") \
        == before_ext + 3
    _assert_parity(rf, ref.query_range(Q, START, end, STEP))


def test_superblock_survives_disjoint_ingest():
    """Fine-grained ingest whose effect interval is DISJOINT from a warm
    superblock's range must not evict it: the entry revalidates via the
    effect log and the query stays one dispatch with zero re-staging."""
    import filodb_tpu.query.exec.plans as plans

    ms = _make_store()
    fused = QueryEngine(ms, "ds")
    hist_end = (BASE + (N_SAMPLES - 60) * 10_000) / 1000  # ends before head
    fused.query_range(Q, START, hist_end, STEP)
    fused.query_range(Q, START, hist_end, STEP)

    stages = [0]
    orig = plans.ST.stage_from_shard

    def counting(*a, **kw):
        stages[0] += 1
        return orig(*a, **kw)

    before_rv = _counter("filodb_superblock_maintenance", outcome="revalidate")
    plans.ST.stage_from_shard = counting
    try:
        for i in range(20):  # 20 fine-grained disjoint live-edge batches
            _append(ms, start_ms=HEAD_MS + i * 10_000)
            before = _dispatch_total()
            fused.query_range(Q, START, hist_end, STEP)
            assert _dispatch_total() - before == 1
    finally:
        plans.ST.stage_from_shard = orig
    assert stages[0] == 0, "disjoint ingest must not force any re-stage"
    assert _counter("filodb_superblock_maintenance", outcome="revalidate") \
        == before_rv + 20


def test_extension_aborts_cleanly_on_new_series():
    """An ingest that CREATES a series records a full-clear effect: the
    stale superblock must rebuild (never extend across a row-set change),
    and the rebuilt result includes the new series."""
    ms = _make_store()
    fused = QueryEngine(ms, "ds")
    ref = QueryEngine(ms, "ds", PlannerParams(fused_aggregate=False))
    end = (HEAD_MS + 120_000) / 1000
    fused.query_range(Q, START, end, STEP)
    fused.query_range(Q, START, end, STEP)
    # continuation batch with MORE series: existing ones get a live-edge
    # append, brand-new ones appear in-range
    _append(ms, n_series=N_SERIES + 8)
    rf = fused.query_range(Q, START, end, STEP)
    _assert_parity(rf, ref.query_range(Q, START, end, STEP))


# ---------------------------------------------------------------------------
# staging-cache liveness: the interval-aware insert guard


def _mid_stage_ingest_engine(ms, batch_for_call):
    """Engine whose staging path ingests ``batch_for_call(i)`` into the
    store mid-stage (between version_at_stage and the cache insert) — the
    deterministic reproduction of 'a block staged concurrently with
    ingest'."""
    import filodb_tpu.query.exec.plans as plans

    orig = plans.ST.stage_from_shard
    calls = [0]

    def racing(*a, **kw):
        block = orig(*a, **kw)
        i = calls[0]
        calls[0] += 1
        batch = batch_for_call(i)
        if batch is not None:
            ms.ingest_routed("ds", batch, spread=3)
        return block

    return orig, racing, calls


def test_disjoint_mid_stage_ingest_no_longer_starves_cache():
    """Regression for the round-5 advisor finding (plans.py insert guard):
    sustained fine-grained DISJOINT-range ingest racing every stage used to
    drop every insert — the cache starved and every query re-paid the full
    stage. Now: 100 small batches racing the stages, insert success rate
    stays >0 (all inserts succeed), and the historical query re-stages at
    most once (the first, cold stage)."""
    import filodb_tpu.query.exec.plans as plans

    ms = _make_store()
    fused = QueryEngine(ms, "ds")
    hist_end = (BASE + (N_SAMPLES - 60) * 10_000) / 1000
    drops0 = _counter_sum("filodb_stage_cache_insert_dropped")

    seq = [0]

    def disjoint_batch(_i):
        b = counter_batch(n_series=N_SERIES, n_samples=1,
                          start_ms=HEAD_MS + seq[0] * 10_000)
        seq[0] += 1
        return b

    orig, racing, calls = _mid_stage_ingest_engine(ms, disjoint_batch)
    plans.ST.stage_from_shard = racing
    try:
        fused.query_range(Q, START, hist_end, STEP)  # cold: one stage/shard
        first_stages = calls[0]
        assert first_stages > 0
        # keep the fine-grained stream racing every subsequent operation
        for _ in range(100 // max(first_stages, 1)):
            fused.query_range(Q, START, hist_end, STEP)
    finally:
        plans.ST.stage_from_shard = orig
    assert calls[0] == first_stages, (
        "historical query re-staged under disjoint ingest: cache starved"
    )
    # every staged block was inserted despite the racing version bumps
    assert all(
        len(ms.shard("ds", s).stage_cache) > 0 for s in range(N_SHARDS)
    )
    drops1 = _counter_sum("filodb_stage_cache_insert_dropped")
    assert drops1 == drops0, "disjoint-range ingest must not drop inserts"


def test_overlapping_mid_stage_ingest_still_guards_insert():
    """The flip side: an ingest whose range OVERLAPS the staged block must
    still block the insert (the staged block cannot have seen it) — with
    the drop reason exported."""
    import filodb_tpu.query.exec.plans as plans

    ms = _make_store()
    fused = QueryEngine(ms, "ds")
    hist_end = (BASE + (N_SAMPLES - 60) * 10_000) / 1000
    overlap_ms = BASE + (N_SAMPLES - 100) * 10_000  # inside the query range

    before = _counter("filodb_stage_cache_insert_dropped", reason="overlap")
    orig, racing, calls = _mid_stage_ingest_engine(
        ms,
        lambda i: counter_batch(n_series=4, n_samples=1,
                                start_ms=overlap_ms + i * 10_000)
        if i < N_SHARDS else None,
    )
    plans.ST.stage_from_shard = racing
    try:
        fused.query_range(Q, START, hist_end, STEP)
    finally:
        plans.ST.stage_from_shard = orig
    assert _counter("filodb_stage_cache_insert_dropped", reason="overlap") \
        > before


# ---------------------------------------------------------------------------
# threaded soak: queries racing a seeded fine-grained stream


def test_queries_racing_fine_grained_ingest():
    """Seeded ingest stream (1-sample continuation batches, no sleeps)
    racing a query loop. Schedule-independent invariants: no exceptions
    escape, the final post-quiesce result matches the reference tree over
    the final store contents, and after at most one maintenance query the
    warm query is back to ONE dispatch."""
    ms = _make_store()
    fused = QueryEngine(ms, "ds")
    ref = QueryEngine(ms, "ds", PlannerParams(fused_aggregate=False))
    end = (HEAD_MS + 80 * 10_000) / 1000
    fused.query_range(Q, START, end, STEP)

    errors = []
    n_batches = 60

    def ingester():
        try:
            for b in range(n_batches):
                _append(ms, start_ms=HEAD_MS + b * 10_000)
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    th = threading.Thread(target=ingester)
    th.start()
    try:
        for _ in range(40):
            fused.query_range(Q, START, end, STEP)
    finally:
        th.join()
    assert not errors, errors

    # quiesced: one maintenance query (extend or rebuild), then warm
    rf = fused.query_range(Q, START, end, STEP)
    _assert_parity(rf, ref.query_range(Q, START, end, STEP))
    before = _dispatch_total()
    fused.query_range(Q, START, end, STEP)
    assert _dispatch_total() - before == 1


# ---------------------------------------------------------------------------
# downsample maintenance races


def _seed_raw_store(root, n_shards=2, n_series=6, n_samples=400):
    from filodb_tpu.memstore.shard import StoreConfig
    from filodb_tpu.store.columnstore import LocalColumnStore
    from filodb_tpu.store.flush import FlushCoordinator
    from filodb_tpu.testkit import machine_metrics

    store = LocalColumnStore(str(root))
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("ds"), range(n_shards))
    for s in range(n_shards):
        ms.ingest("ds", s, machine_metrics(
            n_series=n_series, n_samples=n_samples, start_ms=BASE + s,
        ))
    fc = FlushCoordinator(ms, store)
    for s in range(n_shards):
        fc.flush_shard("ds", s)
    return store, ms


def test_release_toctou_reproduced_and_closed(tmp_path):
    """Deterministic reproduction of the old _release read-then-unlink
    TOCTOU: the owner's claim goes stale and is stolen+re-created by a new
    owner INSIDE the release window (via the race hook). The old code
    unlinked the NEW owner's claim, re-opening the shard to a third worker
    mid-redo; the tombstone discipline must detect the steal from the
    renamed file and put the new owner's claim back untouched."""
    from filodb_tpu.downsample import distributed as dd

    job = str(tmp_path / "job")
    os.makedirs(job)
    path = dd._claim_path(job, 0)
    with open(path, "w") as f:
        json.dump({"worker": "w1", "t": 0.0}, f)

    def steal(shard):
        # the interleaved stealer: atomically breaks w1's stale claim and
        # re-creates it as w2 — exactly what _try_claim's steal path does
        os.rename(path, path + ".stolen-w2")
        os.unlink(path + ".stolen-w2")
        with open(path, "w") as f:
            json.dump({"worker": "w2", "t": 1.0}, f)

    before = _counter("filodb_downsample_claims", event="tombstone_restored")
    dd._release_race_hook = steal
    try:
        dd._release(job, 0, "w1")
    finally:
        dd._release_race_hook = None
    # the new owner's claim SURVIVES the racing release (old code: unlinked)
    assert os.path.exists(path), "release deleted the stolen claim"
    with open(path) as f:
        assert json.load(f)["worker"] == "w2"
    assert _counter("filodb_downsample_claims", event="tombstone_restored") \
        == before + 1
    assert not [p for p in os.listdir(job) if ".release-" in p], (
        "tombstone leaked"
    )


def test_release_without_race_removes_own_claim(tmp_path):
    from filodb_tpu.downsample import distributed as dd

    job = str(tmp_path / "job")
    os.makedirs(job)
    path = dd._claim_path(job, 0)
    with open(path, "w") as f:
        json.dump({"worker": "w1", "t": 0.0}, f)
    dd._release(job, 0, "w1")
    assert not os.path.exists(path)
    # releasing someone ELSE's claim is a no-op
    with open(path, "w") as f:
        json.dump({"worker": "w2", "t": 0.0}, f)
    dd._release(job, 0, "w1")
    assert os.path.exists(path)


def test_claim_steal_storm_single_winner(tmp_path):
    """8 workers race to break the same stale claim: the atomic-rename
    steal admits exactly ONE winner, and the surviving claim file names
    that winner."""
    from filodb_tpu.downsample import distributed as dd

    job = str(tmp_path / "job")
    os.makedirs(job)
    path = dd._claim_path(job, 0)
    with open(path, "w") as f:
        json.dump({"worker": "stale", "t": 0.0}, f)
    os.utime(path, (1.0, 1.0))  # ancient heartbeat

    winners = []
    barrier = threading.Barrier(8)

    def racer(i):
        rep = dd.WorkerReport(worker_id=f"w{i}")
        barrier.wait()
        if dd._try_claim(job, 0, f"w{i}", stale_s=5.0, report=rep):
            winners.append(f"w{i}")

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(winners) == 1, winners
    with open(path) as f:
        assert json.load(f)["worker"] == winners[0]


def test_crash_mid_commit_then_redo_recovers(tmp_path):
    """Worker dies BETWEEN committing a shard's downsample output and
    writing the done marker (FILODB_DS_CRASH_MID_COMMIT). The redo by a
    second worker re-commits equivalent output under the same
    deterministic batch segment names (os.replace: last writer wins), so
    the final store equals the single-process oracle — no double-counted
    and no lost samples."""
    from filodb_tpu.downsample.distributed import (
        _claim_path, _job_dir, job_complete, run_worker,
    )
    from test_distributed_downsample import _oracle_totals, _recovered_totals

    store, ms = _seed_raw_store(tmp_path)
    want = _oracle_totals(store, ms, 2)
    env = dict(os.environ, FILODB_DS_CRASH_MID_COMMIT="1",
               JAX_PLATFORMS="cpu", FILODB_PLATFORM="cpu")
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "from filodb_tpu.downsample.distributed import run_worker\n"
        f"run_worker({str(tmp_path)!r}, 'ds', range(2), (300000,), "
        "worker_id='victim')\n"
    )
    p = subprocess.run([sys.executable, "-c", code], env=env, timeout=300,
                       capture_output=True, text=True)
    assert p.returncode == 19, p.stderr[-500:]
    job = _job_dir(str(tmp_path), "ds", "default")
    # crashed post-commit, pre-done: output present, marker absent
    assert not os.path.exists(os.path.join(job, "shard-1.done"))
    assert os.path.exists(_claim_path(job, 1)), "victim died holding claim"
    committed = os.path.join(str(tmp_path), "ds_5m", "shard-1")
    assert any(f.startswith("chunks-batch-") for f in os.listdir(committed))
    old = os.path.getmtime(_claim_path(job, 1)) - 120
    os.utime(_claim_path(job, 1), (old, old))
    r = run_worker(str(tmp_path), "ds", range(2), (300_000,),
                   worker_id="rescuer", stale_s=60.0)
    assert 1 in r.shards_done and 1 in r.claims_broken
    assert job_complete(str(tmp_path), "ds", range(2))
    assert _recovered_totals(tmp_path, 2) == want


def test_batch_commit_preserves_streaming_downsample_segments(tmp_path):
    """The round-5 advisor race: the batch job used to COMMIT by
    rmtree+rename over the live '{ds}_5m/shard-N' dir — wiping newer
    segments flushed there by the ingest-time streaming downsampler. The
    merge commit must leave streaming 'chunks-g*.seg' files in place and
    recovery must still see their samples."""
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.downsample.downsampler import DS_GAUGE
    from filodb_tpu.downsample.distributed import run_worker
    from filodb_tpu.store.columnstore import LocalColumnStore
    from filodb_tpu.store.flush import FlushCoordinator, recover_shard

    store, _ms = _seed_raw_store(tmp_path, n_shards=1)
    # a streaming-downsample flush into the live ds_5m shard dir, with a
    # sentinel series the batch job cannot produce (distinct tags) and
    # timestamps NEWER than anything in the raw store
    dsm = TimeSeriesMemStore()
    dsm.setup(Dataset("ds_5m", schemas=[DS_GAUGE]), [0])
    sent_ts = np.array([BASE + 10**9, BASE + 10**9 + 300_000], dtype=np.int64)
    dsm.shard("ds_5m", 0).ingest_series(SeriesBatch(
        DS_GAUGE, {"__name__": "streamed_only", "src": "live"},
        sent_ts, {"avg": np.array([1.5, 2.5]), "min": np.array([1.0, 2.0]),
                  "max": np.array([2.0, 3.0]), "count": np.array([2.0, 2.0]),
                  "sum": np.array([3.0, 5.0])},
    ))
    FlushCoordinator(dsm, store).flush_shard("ds_5m", 0)
    live = os.path.join(str(tmp_path), "ds_5m", "shard-0")
    streaming_segs = {f for f in os.listdir(live) if f.startswith("chunks-g")}
    assert streaming_segs, "precondition: streaming flush wrote segments"

    r = run_worker(str(tmp_path), "ds", [0], (300_000,), worker_id="batch")
    assert r.shards_done == [0]
    # streaming segments survived the batch commit...
    now = set(os.listdir(live))
    assert streaming_segs <= now, "batch commit wiped streaming segments"
    assert any(f.startswith("chunks-batch-") for f in now)
    # ...and recovery still sees the streaming samples alongside batch ones
    rec = TimeSeriesMemStore()
    rec.setup(Dataset("ds_5m", schemas=[DS_GAUGE]), [0])
    recover_shard(rec, LocalColumnStore(str(tmp_path)), "ds_5m", 0)
    sh = rec.shard("ds_5m", 0)
    from filodb_tpu.core.filters import equals

    pids = sh.lookup_partitions(
        [equals("__name__", "streamed_only")], 0, 2**62
    )
    assert len(pids) == 1, "streaming-downsampled series lost by batch commit"
    ts, vals = sh.partition(int(pids[0])).samples_in_range(0, 2**62, "avg")
    assert list(ts) == list(sent_ts)
    assert list(vals) == [1.5, 2.5]


def test_reconcile_chunks_overlap_later_end_wins():
    """Unit contract of store/flush._reconcile_chunks: per timestamp the
    chunk with the LATER end_ts wins, exact duplicates collapse, and
    non-overlapping chunk sets are untouched."""
    from filodb_tpu.memstore.partition import Chunk
    from filodb_tpu.store.flush import _reconcile_chunks

    class P:  # minimal partition stand-in
        pass

    def chunk(ts, vals):
        ts = np.asarray(ts, dtype=np.int64)
        return Chunk(int(ts[0]), int(ts[-1]), len(ts),
                     {"timestamp": ts, "avg": np.asarray(vals, float)})

    # partial early chunk superseded by a later, more complete one
    p = P()
    p.chunks = [chunk([0, 100], [1.0, 2.0]),
                chunk([0, 100, 200], [10.0, 20.0, 30.0])]
    _reconcile_chunks(p)
    got = {int(t): float(v) for c in p.chunks
           for t, v in zip(c.column("timestamp"), c.column("avg"))}
    assert got == {0: 10.0, 100: 20.0, 200: 30.0}

    # exact duplicates (a redo re-committing the same output) collapse
    p = P()
    p.chunks = [chunk([0, 100], [1.0, 2.0]), chunk([0, 100], [1.0, 2.0])]
    _reconcile_chunks(p)
    assert len(p.chunks) == 1
    assert [int(t) for t in p.chunks[0].column("timestamp")] == [0, 100]

    # disjoint chunks: untouched (the normal raw path)
    p = P()
    before = [chunk([0, 100], [1.0, 2.0]), chunk([200, 300], [3.0, 4.0])]
    p.chunks = list(before)
    _reconcile_chunks(p)
    assert p.chunks == before
