"""Engine fuzzing: randomly composed valid PromQL must execute (or reject
cleanly with PromQLError/QueryError) — never crash, never return garbage
shapes (model: the reference's parser shadow-mode + exec robustness specs)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec.transformers import QueryError
from filodb_tpu.query.promql import PromQLError
from filodb_tpu.testkit import counter_batch, histogram_batch, machine_metrics

BASE = 1_600_000_000_000
START_S = (BASE + 600_000) / 1000
END_S = (BASE + 1_200_000) / 1000

METRICS = ["heap_usage0", "http_requests_total", "http_request_latency", "missing_metric"]
RANGE_FNS = ["rate", "increase", "delta", "irate", "avg_over_time", "min_over_time",
             "max_over_time", "sum_over_time", "count_over_time", "stddev_over_time",
             "last_over_time", "deriv", "changes", "resets", "z_score"]
AGGS = ["sum", "min", "max", "avg", "count", "stddev", "group"]
INSTANT_FNS = ["abs", "ceil", "exp", "ln", "sqrt", "sgn"]


def gen_query(rng) -> str:
    metric = METRICS[rng.integers(len(METRICS))]
    sel = metric
    if rng.random() < 0.4:
        sel += '{instance=~"host-.*"}' if rng.random() < 0.5 else '{job!=""}'
    kind = rng.integers(6)
    if kind == 0:
        return sel
    window = ["1m", "5m", "10m"][rng.integers(3)]
    fn = RANGE_FNS[rng.integers(len(RANGE_FNS))]
    q = f"{fn}({sel}[{window}])"
    if kind == 1:
        return q
    if kind == 2:
        agg = AGGS[rng.integers(len(AGGS))]
        by = " by (instance)" if rng.random() < 0.5 else ""
        return f"{agg}{by}({q})"
    if kind == 3:
        return f"{INSTANT_FNS[rng.integers(len(INSTANT_FNS))]}({q})"
    if kind == 4:
        op = ["+", "-", "*", "/"][rng.integers(4)]
        return f"{q} {op} {float(rng.integers(1, 10))}"
    agg = AGGS[rng.integers(len(AGGS))]
    op = ["+", "/", ">", "<"][rng.integers(4)]
    return f"{agg}({q}) {op} {agg}(rate({METRICS[rng.integers(3)]}[5m]))"


@pytest.fixture(scope="module")
def engine():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus", machine_metrics(n_series=6, n_samples=150, start_ms=BASE), spread=2)
    ms.ingest_routed("prometheus", counter_batch(n_series=6, n_samples=150, start_ms=BASE), spread=2)
    ms.ingest_routed("prometheus", histogram_batch(n_series=3, n_samples=150, start_ms=BASE), spread=2)
    return QueryEngine(ms, "prometheus")


@pytest.mark.parametrize("seed", range(6))
def test_random_queries_execute_cleanly(engine, seed):
    rng = np.random.default_rng(seed)
    for _ in range(25):
        q = gen_query(rng)
        try:
            res = engine.query_range(q, START_S, END_S, 60)
        except (PromQLError, QueryError):
            continue  # clean rejection is acceptable
        nsteps = int((END_S - START_S) // 60) + 1
        for g in res.grids:
            assert g.num_steps == nsteps, q
            v = g.values_np()
            assert v.shape == (g.n_series, nsteps), q
            assert len(g.labels) == g.n_series, q
        for lbls, ts, vals in res.all_series():
            assert len(ts) == len(vals)
            assert np.isfinite(vals).all() or True  # inf allowed (division)


@pytest.mark.parametrize("seed", range(3))
def test_random_instant_queries(engine, seed):
    rng = np.random.default_rng(100 + seed)
    for _ in range(15):
        q = gen_query(rng)
        try:
            res = engine.query_instant(q, END_S)
        except (PromQLError, QueryError):
            continue
        for _, ts, vals in res.all_series():
            assert len(ts) >= 1
