"""Randomized soak for the incremental append-repair path: interleave
live-edge/historical queries with ingest (uniform and divergent appends,
counter resets in the appended region, new data arriving between every
query) and compare EVERY result against a fresh-engine oracle over
identical data. The deterministic unit tests in
test_stage_cache_invalidation.py pin specific behaviors; this pins the
interleaving space. A 200-round version of this loop ran clean in round 5.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import Dataset, GAUGE, METRIC_TAG, PROM_COUNTER
from filodb_tpu.memstore.memstore import TimeSeriesMemStore

BASE = 1_600_000_000_000
STEP = 10_000
QUERIES = [
    "sum(rate(m_ctr[5m]))", "avg(m_g)", "max(m_g)",
    "sum(increase(m_ctr[3m]))", "count(m_g)", "stddev(m_g)",
]


def _tags(i, counter):
    return {METRIC_TAG: "m_ctr" if counter else "m_g", "_ws_": "w",
            "_ns_": "n", "inst": f"h{i}"}


def _ingest(ms, i, counter, ts, vals):
    ms.shard("ds", 0).ingest_series(SeriesBatch(
        PROM_COUNTER if counter else GAUGE, _tags(i, counter),
        np.asarray(ts, np.int64),
        {("count" if counter else "value"): np.asarray(vals, np.float64)},
    ))


@pytest.mark.parametrize("seed", range(8))
def test_append_repair_interleaving_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    n0 = 60
    base_ts = BASE + (1 + np.arange(n0, dtype=np.int64)) * STEP
    data: dict = {}
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    nseries = int(rng.integers(3, 7))
    for i in range(nseries):
        for c in (False, True):
            if c:
                v = np.cumsum(rng.uniform(0, 10, n0)) + 1e9
                if rng.random() < 0.5:
                    k = int(rng.integers(20, 50))
                    v[k:] -= v[k] - rng.uniform(0, 5)
            else:
                v = 50 + 20 * rng.standard_normal(n0)
            data[(i, c)] = (list(base_ts), list(v))
            _ingest(ms, i, c, base_ts, v)
    engine = QueryEngine(ms, "ds")
    head = n0
    for op in range(14):
        if rng.random() < 0.55:
            # append 1-3 scrapes to ALL series (uniform -> repairable) or
            # a SUBSET (divergent -> must fall back and stay correct)
            k = int(rng.integers(1, 4))
            new_ts = BASE + (1 + head + np.arange(k, dtype=np.int64)) * STEP
            subset = (range(nseries) if rng.random() < 0.7
                      else rng.choice(nseries, int(rng.integers(1, nseries)),
                                      replace=False).tolist())
            for i in subset:
                for c in (False, True):
                    if c:
                        # monotone continuation (the common live case)...
                        nv = np.cumsum(rng.uniform(0, 20, k)) + data[(i, c)][1][-1]
                        if rng.random() < 0.1:
                            nv = rng.uniform(0, 5, k)  # ...or a reset in the tail
                    else:
                        nv = 50 + 20 * rng.standard_normal(k)
                    data[(i, c)][0].extend(new_ts.tolist())
                    data[(i, c)][1].extend(np.asarray(nv, float).tolist())
                    _ingest(ms, i, c, new_ts, nv)
            head += k
        q = QUERIES[int(rng.integers(len(QUERIES)))]
        live = rng.random() < 0.6
        s = (BASE + 400_000) / 1000
        e = (BASE + ((head + 10) if live else (n0 - 10)) * STEP) / 1000
        got = engine.query_range(q, s, e, 60)
        ms2 = TimeSeriesMemStore()
        ms2.setup(Dataset("ds"), [0])
        for (i, c), (ts_l, v_l) in data.items():
            _ingest(ms2, i, c, ts_l, v_l)
        want = QueryEngine(ms2, "ds").query_range(q, s, e, 60)
        gv = got.grids[0].values_np() if got.grids else np.zeros((0,))
        wv = want.grids[0].values_np() if want.grids else np.zeros((0,))
        ctx = f"seed={seed} op={op} q={q} live={live}"
        assert gv.shape == wv.shape, ctx
        np.testing.assert_array_equal(np.isnan(gv), np.isnan(wv), err_msg=ctx)
        m = ~np.isnan(wv)
        if m.any():
            np.testing.assert_allclose(gv[m], wv[m], rtol=2e-3, atol=1e-3,
                                       err_msg=ctx)
