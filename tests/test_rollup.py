"""Sketch rollup tier end-to-end (doc/perf.md "Sketch rollup tier"):
planner substitution, parity, fallback, chooser, pinning, debug surfaces.

The contract under test, per ISSUE 16:

- **substitution**: eligible long-range window/aggregate queries serve
  from per-period summary blocks and record querylog ``path=rollup``;
- **parity**: moment functions and reset-corrected counter rate/increase
  are EXACT against a numpy oracle over the rollup's period-mapped
  windows (``[t-w, t)`` period coverage); ``quantile_over_time`` lands
  within the sketch's ``2^(1/32)-1`` bin bound of the sample-rank
  bracket;
- **fallback**: plan-time ineligible shapes (offset, unaligned start,
  non-multiple window) AND runtime invalidation (entry retired between
  plan and execute) produce BIT-IDENTICAL results to the raw path, the
  latter under the ``rollup_ineligible`` fallback taxonomy entry;
- **chooser**: a repeated long-range fingerprint in the query log gets a
  rollup added; an idle chooser-origin rollup gets retired;
- **pinning**: a standing query's superblock survives an ad-hoc eviction
  storm (satellite of the same PR: `filodb_superblock_pinned_bytes`).
"""

import json
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.records import SeriesBatch
from filodb_tpu.core.schemas import (
    GAUGE, METRIC_TAG, PROM_COUNTER, Dataset, shard_for,
)
from filodb_tpu.downsample.chooser import RollupChooser
from filodb_tpu.downsample.rollup import RollupManager
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.metrics import REGISTRY
from filodb_tpu.obs.querylog import QUERY_LOG
from filodb_tpu.query import logical as L
from filodb_tpu.query.promql import query_range_to_logical_plan

pytestmark = pytest.mark.rollup

BASE = 1_600_000_000_000
RES = 60_000          # 1m rollup resolution under test
IVL = 10_000          # scrape interval: 6 samples per period
SPP = RES // IVL
P = 182               # ingested periods per series
T = P * SPP
ALIGN0 = BASE + (RES - BASE % RES)  # BASE itself is NOT minute-aligned
N_SHARDS = 4
S_G, S_C = 6, 4
BOUND = 2.0 ** (1.0 / 32.0) - 1.0

# grid: window == step == resolution, two lead periods (rate needs the
# period BEFORE the first window) -> output step j covers period 1+j
START_MS = ALIGN0 + 2 * RES
END_MS = ALIGN0 + 180 * RES
J = (END_MS - START_MS) // RES + 1


def _corrected(v):
    """Host mirror of the manager's reset correction: cumulative base of
    pre-reset values added back onto the raw counter."""
    prev = np.concatenate([[v[0]], v[:-1]])
    return v + np.cumsum(np.where(v < prev, prev, 0.0))


@pytest.fixture(scope="module")
def stack():
    """One ingested memstore + built 1m rollups + both engines, shared by
    every parity/fallback test in the module (ingest dominates runtime)."""
    rng = np.random.default_rng(99)
    ts = ALIGN0 + np.arange(T, dtype=np.int64) * IVL
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    gvals = 100.0 * np.exp(0.3 * rng.standard_normal((S_G, T)))
    for i in range(S_G):
        tags = {METRIC_TAG: "mem_used", "_ws_": "w", "_ns_": "n",
                "instance": f"host-{i}"}
        ms.shard("ds", shard_for(tags, spread=3, num_shards=N_SHARDS)
                 ).ingest_series(SeriesBatch(GAUGE, tags, ts, {"value": gvals[i]}))
    cvals = np.cumsum(rng.uniform(0, 10, (S_C, T)), axis=1)
    cvals[:, 400:] -= cvals[:, [400]] - 1.0  # a mid-stream counter reset
    for i in range(S_C):
        tags = {METRIC_TAG: "req_total", "_ws_": "w", "_ns_": "n",
                "instance": f"host-{i}"}
        ms.shard("ds", shard_for(tags, spread=3, num_shards=N_SHARDS)
                 ).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts, {"count": cvals[i]}))
    rollups = RollupManager(ms)
    for metric in ("mem_used", "req_total"):
        plan = query_range_to_logical_plan(
            f"sum_over_time({metric}[1m])" if metric == "mem_used"
            else f"rate({metric}[1m])",
            START_MS / 1e3, END_MS / 1e3, RES / 1e3)
        rollups.ensure("ds", plan.raw.filters, RES, build=True)
    eng_ru = QueryEngine(ms, "ds", PlannerParams(rollups=rollups))
    eng_raw = QueryEngine(ms, "ds")
    return ms, rollups, eng_ru, eng_raw, gvals, cvals


def _run(eng, q, start_ms=START_MS, end_ms=END_MS, step_ms=RES):
    res = eng.query_range(q, start_ms / 1e3, end_ms / 1e3, step_ms / 1e3)
    return res, QUERY_LOG.entries(1)[0].get("path")


def _by_instance(grid):
    """values [S, J] reordered by the numeric instance suffix."""
    vals = np.asarray(grid.values_np(), dtype=np.float64)
    order = np.argsort([int(l["instance"].split("-")[1]) for l in grid.labels])
    return vals[order]


# -- substitution + parity ---------------------------------------------------


def test_moment_functions_exact_vs_period_oracle(stack):
    """sum/avg/min/max_over_time from moments == numpy over the SAME
    period-mapped windows (window j covers exactly period 1+j): moments
    are exact per-period sums, so only f32 staging noise remains."""
    _ms, _ru, eng_ru, _raw, gvals, _c = stack
    hours = gvals.reshape(S_G, P, SPP)[:, 1:1 + J]
    oracles = {
        "sum_over_time": hours.sum(-1),
        "avg_over_time": hours.mean(-1),
        "min_over_time": hours.min(-1),
        "max_over_time": hours.max(-1),
    }
    for func, want in oracles.items():
        res, path = _run(eng_ru, f"{func}(mem_used[1m])")
        assert path == "rollup", func
        got = _by_instance(res.grids[0])
        assert got.shape == want.shape, func
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3,
                                   err_msg=func)


def test_counter_rate_and_increase_reset_corrected(stack):
    """rate/increase from the per-period corrected cumulative-last equals
    the host reset-correction mirror exactly: increase over window j is
    clast[period 1+j] - clast[period j] (the lead period's last)."""
    _ms, _ru, eng_ru, _raw, _g, cvals = stack
    clast = np.stack([_corrected(v) for v in cvals]).reshape(
        S_C, P, SPP)[:, :, -1]
    want_inc = clast[:, 1:1 + J] - clast[:, 0:J]
    for q, want in (("increase(req_total[1m])", want_inc),
                    ("rate(req_total[1m])", want_inc / (RES / 1e3))):
        res, path = _run(eng_ru, q)
        assert path == "rollup", q
        got = _by_instance(res.grids[0])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4,
                                   err_msg=q)


def test_quantile_over_time_within_sketch_bound(stack):
    """The sketch read-off lands within the documented relative-error
    bound of the numpy sample-rank bracket over the period windows."""
    _ms, _ru, eng_ru, _raw, gvals, _c = stack
    res, path = _run(eng_ru, "quantile_over_time(0.9, mem_used[1m])")
    assert path == "rollup"
    got = _by_instance(res.grids[0])
    hours = gvals.reshape(S_G, P, SPP)[:, 1:1 + J]
    lo = np.quantile(hours, 0.9, axis=-1, method="lower")
    hi = np.quantile(hours, 0.9, axis=-1, method="higher")
    assert got.shape == lo.shape
    assert np.all(got >= lo * (1 - BOUND) - 1e-9)
    assert np.all(got <= hi * (1 + BOUND) + 1e-9)


def test_aggregate_over_rollup_path_and_parity(stack):
    """sum(sum_over_time(...)) dispatches the fused rollup aggregate
    (path=rollup) and equals the numpy oracle's cross-series sum."""
    _ms, _ru, eng_ru, _raw, gvals, _c = stack
    res, path = _run(eng_ru, "sum(sum_over_time(mem_used[1m]))")
    assert path == "rollup"
    got = np.asarray(res.grids[0].values_np(), dtype=np.float64)[0]
    want = gvals.reshape(S_G, P, SPP)[:, 1:1 + J].sum(-1).sum(0)
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_raw_engine_never_takes_rollup_path(stack):
    _ms, _ru, _eng_ru, eng_raw, _g, _c = stack
    for q in ("avg_over_time(mem_used[1m])", "rate(req_total[1m])"):
        _res, path = _run(eng_raw, q)
        assert path != "rollup"


# -- fallback ----------------------------------------------------------------


def _grid_bytes(res):
    out = []
    for g in res.grids:
        order = np.argsort([json.dumps(l, sort_keys=True) for l in g.labels])
        vals = np.asarray(g.values_np())[order]
        out.append((tuple(json.dumps(g.labels[i], sort_keys=True)
                          for i in order),
                    g.start_ms, g.step_ms, vals.tobytes()))
    return out


@pytest.mark.parametrize("q, start_ms", [
    # offset -> plan-time ineligible
    ("avg_over_time(mem_used[1m] offset 1m)", START_MS),
    # window not a multiple of the 1m resolution
    ("avg_over_time(mem_used[90s])", START_MS),
    # unaligned grid start
    ("avg_over_time(mem_used[1m])", START_MS + 7_000),
])
def test_plan_time_fallback_bit_identical(stack, q, start_ms):
    """Ineligible shapes must not merely be 'close': the rollup-enabled
    engine builds the EXACT raw plan, so results are byte-equal."""
    _ms, _ru, eng_ru, eng_raw, _g, _c = stack
    res_ru, path = _run(eng_ru, q, start_ms=start_ms)
    res_raw, _ = _run(eng_raw, q, start_ms=start_ms)
    assert path != "rollup", q
    assert _grid_bytes(res_ru) == _grid_bytes(res_raw), q


def test_runtime_fallback_bit_identical_and_counted(stack):
    """Entry retired BETWEEN plan and execute: RollupServeExec delegates
    to its fallback under ``rollup_ineligible`` and the result is
    bitwise-equal to the raw plan's."""
    from filodb_tpu.query.exec.plans import RollupServeExec

    _ms, rollups, eng_ru, eng_raw, _g, _c = stack
    q = "max_over_time(mem_used[1m])"
    plan = query_range_to_logical_plan(
        q, START_MS / 1e3, END_MS / 1e3, RES / 1e3)
    ex = eng_ru.planner.materialize(plan)
    assert isinstance(ex, RollupServeExec)
    filters = plan.raw.filters
    entry = rollups.ensure("ds", filters, RES)  # idempotent handle
    assert rollups.retire("ds", filters, RES)
    ctr = REGISTRY.counter("filodb_fused_fallback", reason="rollup_ineligible")
    before = ctr.value
    try:
        res_fb = ex.execute(eng_ru.context())
        assert ctr.value == before + 1
        res_raw = eng_raw.planner.materialize(plan).execute(eng_raw.context())
        assert _grid_bytes(res_fb) == _grid_bytes(res_raw)
    finally:
        # restore the module fixture's entry for later tests
        rollups.ensure("ds", filters, RES, origin=entry.origin, build=True)


# -- chooser -----------------------------------------------------------------


def test_chooser_adds_then_retires_idle_rollup(stack):
    """A fingerprint repeated >= min_count times over >= min_span_ms gets
    a rollup at the coarsest dividing resolution; once idle past idle_s
    the chooser-origin entry is retired again."""
    ms, _ru, _eng_ru, eng_raw, _g, _c = stack
    mgr = RollupManager(ms)
    chooser = RollupChooser(
        mgr, resolutions_ms=(RES,), min_count=3,
        min_span_ms=3_600_000, idle_s=600.0,
    )
    QUERY_LOG.clear()
    q = "quantile_over_time(0.95, mem_used[1m])"
    for _ in range(3):
        _run(eng_raw, q)
    filters = query_range_to_logical_plan(
        q, START_MS / 1e3, END_MS / 1e3, RES / 1e3).raw.filters
    assert not mgr.has("ds", filters, RES)
    added = chooser.tick()
    assert any(d.get("action") == "add" for d in added)
    assert mgr.has("ds", filters, RES)
    # idle past idle_s with no further hits -> retired (created_s /
    # last_hit_s are wall-clock, so advance from real time)
    import time as _time

    QUERY_LOG.clear()
    retired = chooser.tick(now_s=_time.time() + 601.0)
    assert any(d.get("action") == "retire" for d in retired)
    assert not mgr.has("ds", filters, RES)


# -- superblock pinning (satellite) ------------------------------------------


def test_superblock_cache_pin_survives_eviction(stack):
    from filodb_tpu.ops.staging import SuperblockCache

    cache = SuperblockCache(max_entries=2)
    gauge = REGISTRY.gauge("filodb_superblock_pinned_bytes")
    cache.put("k1", (1,), "v1", 100)
    cache.pin("k1", "sq-1")
    assert cache.pinned_bytes() == 100 and gauge.value == 100.0
    for i in range(2, 6):  # eviction storm: k1 must be skipped
        cache.put(f"k{i}", (1,), f"v{i}", 100)
    assert cache.get("k1", (1,)) == "v1"
    snap = {e["key"]: e["pinned"] for e in cache.snapshot()}
    assert snap["'k1'"] is True and sum(snap.values()) == 1
    # pinning an unbuilt key is identity, not storage
    cache.pin("k-future", "sq-1")
    assert cache.pinned_bytes() == 100
    cache.unpin_owner("sq-1")
    assert cache.pinned_bytes() == 0 and gauge.value == 0.0
    cache.put("k9", (1,), "v9", 100)   # evicts the LRU survivor first,
    cache.put("k10", (1,), "v10", 100)  # then k1 once it reaches LRU
    assert cache.get("k1", (1,)) is None  # unpinned -> evictable again


def test_standing_query_pin_survives_adhoc_storm(stack):
    """Full stack: a registered standing query pins its superblock; an
    ad-hoc query storm over distinct ranges (distinct sb keys) cannot
    evict it even from a 2-entry cache; unregister releases the pin."""
    from filodb_tpu.ops.staging import SuperblockCache
    from filodb_tpu.standing import StandingEngine

    ms, _ru, _eng_ru, _raw, _g, _c = stack
    eng = QueryEngine(ms, "ds")
    old_cache = getattr(ms, "_superblock_cache", None)
    ms._superblock_cache = cache = SuperblockCache(max_entries=2)
    try:
        se = StandingEngine(
            eng, {"default_span_ms": 30 * RES},
            clock=lambda: (END_MS + 5_000) / 1e3,
        )
        sq = se.register("sum by (instance) (rate(req_total[5m]))", RES)
        se.refresh(sq)
        pinned = [e for e in cache.snapshot() if e["pinned"]]
        assert len(pinned) == 1
        assert cache.pinned_bytes() > 0
        pinned_key = pinned[0]["key"]
        for k in range(1, 7):  # distinct windows -> distinct sb keys
            eng.query_range(
                f"sum(avg_over_time(mem_used[{k}m]))",
                (START_MS + 30 * RES) / 1e3, END_MS / 1e3, RES / 1e3)
        snap = {e["key"]: e["pinned"] for e in cache.snapshot()}
        assert snap.get(pinned_key) is True, "standing superblock evicted"
        se.refresh(sq)  # still serving after the storm
        se.unregister(sq.qid)
        assert cache.pinned_bytes() == 0
        assert not any(e["pinned"] for e in cache.snapshot())
    finally:
        if old_cache is not None:
            ms._superblock_cache = old_cache


# -- debug surfaces ----------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_debug_rollups_and_querylog_fingerprint_endpoints(stack):
    from filodb_tpu.api.http import serve_background

    _ms, rollups, eng_ru, _raw, _g, _c = stack
    srv, port = serve_background(eng_ru, rollups=rollups)
    try:
        code, body = _get(f"http://127.0.0.1:{port}/debug/rollups")
        assert code == 200 and body["status"] == "success"
        assert body["data"]["count"] >= 2
        assert any(e["resolution_ms"] == RES
                   for e in body["data"]["entries"])
        # fingerprint filter: two shapes in the log, filter keeps one
        QUERY_LOG.clear()
        _run(eng_ru, "avg_over_time(mem_used[1m])")
        _run(eng_ru, "rate(req_total[1m])")
        fp = QUERY_LOG.entries(1)[0]["fingerprint"]
        code, body = _get(
            f"http://127.0.0.1:{port}/debug/querylog?fingerprint={fp}")
        assert code == 200
        entries = body["data"]
        assert entries and all(e["fingerprint"] == fp for e in entries)
        code, _ = _get(f"http://127.0.0.1:{port}/debug/querylog")
        assert code == 200
    finally:
        srv.shutdown()
    srv2, port2 = serve_background(eng_ru)  # no rollup tier wired
    try:
        code, body = _get(f"http://127.0.0.1:{port2}/debug/rollups")
        assert code == 404
    finally:
        srv2.shutdown()


def test_wide_range_time_slicing_past_staged_span():
    """A raw query whose selector span exceeds the staged int32 ms-offset
    representation (ops/staging.MAX_STAGE_SPAN_MS, ~24.8 days) is
    time-sliced by the planner into per-slice staged bases and stitched —
    previously the wrapped offsets silently emptied every window past the
    wrap point (NaN tail / corrupt values on tree and fused paths alike).
    The rollup tier is the FAST path for these spans; this covers the
    raw-serving correctness floor it falls back on."""
    from filodb_tpu.memstore.shard import StoreConfig
    from filodb_tpu.ops import staging as ST
    from filodb_tpu.query.exec.plans import StitchRvsExec

    DAYS = 30
    W_RES = 3_600_000  # 1h windows on a 6h step grid: 120 output steps
    W_IVL = 60_000
    WT = DAYS * 24 * 60
    align0 = BASE + (W_RES - BASE % W_RES)
    ts = align0 + np.arange(WT, dtype=np.int64) * W_IVL
    rng = np.random.default_rng(11)
    g = 100.0 * np.exp(0.4 * rng.standard_normal(WT))
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=WT))
    ms.setup(Dataset("wide"), [0])
    ms.shard("wide", 0).ingest_series(SeriesBatch(
        GAUGE, {METRIC_TAG: "disk_usage", "instance": "h0"}, ts,
        {"value": g}))
    eng = QueryEngine(ms, "wide", PlannerParams())
    STEP = 6 * W_RES
    start_s = (align0 + 2 * W_RES) / 1e3
    end_s = (align0 + DAYS * 24 * W_RES) / 1e3

    # the plan itself is a stitch of >=2 in-representation slices
    plan = query_range_to_logical_plan(
        "sum(avg_over_time(disk_usage[1h]))", start_s, end_s, STEP / 1e3)
    exec_plan = eng.planner.materialize(plan)
    assert isinstance(exec_plan, StitchRvsExec)
    assert len(exec_plan.children()) >= 2

    # window (t-1h, t] oracle at every step, INCLUDING past the old int32
    # wrap point (offset 2^31 ms ~ hour 596)
    nsteps = int((end_s - start_s) * 1e3 // STEP) + 1
    want = np.empty(nsteps)
    for j in range(nsteps):
        k = (2 * W_RES + j * STEP) // W_IVL
        want[j] = np.mean(g[k - 59:k + 1])
    for q in ("avg_over_time(disk_usage[1h])",
              "sum(avg_over_time(disk_usage[1h]))"):
        res = eng.query_range(q, start_s, end_s, STEP / 1e3)
        v = np.asarray(res.grids[0].values_np(), dtype=np.float64)[0]
        assert v.shape == (nsteps,)
        assert not np.isnan(v).any()
        np.testing.assert_allclose(v, want, rtol=1e-5)

    # an in-representation range must NOT stitch (no behavior change)
    narrow = query_range_to_logical_plan(
        "sum(avg_over_time(disk_usage[1h]))", start_s,
        (align0 + 20 * 24 * W_RES) / 1e3, STEP / 1e3)
    assert not isinstance(eng.planner.materialize(narrow), StitchRvsExec)
    assert ST.MAX_STAGE_SPAN_MS == 2**31 - 2
