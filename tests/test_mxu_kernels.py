"""MXU (regular-grid matmul) kernel path vs the general kernel path on the
same data — the fast path must be indistinguishable."""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.mxu_kernels import MXU_FUNCS
from filodb_tpu.ops.staging import stage_series

BASE = 1_600_000_000_000


def regular_series(n_series=6, n=300, seed=0, counter=False):
    rng = np.random.default_rng(seed)
    ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
    out = []
    for i in range(n_series):
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9
            k = n // 2 + i
            vals[k:] -= vals[k] - rng.uniform(0, 5)  # a reset per series
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        out.append((ts.copy(), vals))
    return out


def run_path(func, series, counter, force_general, args=()):
    block = stage_series(series, BASE, counter_corrected=counter)
    assert block.regular_ts is not None
    if force_general:
        block.regular_ts = None  # disable fast path
    params = K.RangeParams(BASE + 400_000, 60_000, 20, 300_000)
    return np.asarray(
        K.run_range_function(func, block, params, is_counter=counter, args=args)
    )[: len(series), :20]


GAUGE_MXU = sorted(MXU_FUNCS - {"rate", "increase", "irate", "timestamp"})


@pytest.mark.parametrize("func", GAUGE_MXU)
def test_mxu_matches_general_gauge(func):
    series = regular_series(seed=3)
    args = (600.0,) if func == "predict_linear" else ()
    fast = run_path(func, series, False, False, args)
    slow = run_path(func, series, False, True, args)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow), err_msg=func)
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=2e-4, atol=1e-3, err_msg=func)


@pytest.mark.parametrize("func", ["rate", "increase", "irate"])
def test_mxu_matches_general_counter(func):
    series = regular_series(seed=4, counter=True)
    fast = run_path(func, series, True, False)
    slow = run_path(func, series, True, True)
    np.testing.assert_array_equal(np.isnan(fast), np.isnan(slow), err_msg=func)
    m = ~np.isnan(slow)
    np.testing.assert_allclose(fast[m], slow[m], rtol=1e-3, atol=1e-3, err_msg=func)


def test_irregular_data_not_regular():
    rng = np.random.default_rng(0)
    series = []
    for i in range(3):
        ts = BASE + np.cumsum(rng.integers(5000, 15000, 100)).astype(np.int64)
        series.append((ts, rng.standard_normal(100)))
    block = stage_series(series, BASE)
    assert block.regular_ts is None


def test_nan_staleness_in_one_series_breaks_regularity():
    ts = BASE + (1 + np.arange(100, dtype=np.int64)) * 10_000
    v1 = np.random.default_rng(0).standard_normal(100)
    v2 = v1.copy()
    v2[10] = np.nan  # dropped at staging -> different length
    block = stage_series([(ts, v1), (ts.copy(), v2)], BASE)
    assert block.regular_ts is None  # must fall back to the general path
