"""TPU range-function kernels vs numpy oracle (the SURVEY §4(f) strategy:
every kernel cross-checked against an independent reference implementation;
model: reference AggrOverTimeFunctionsSpec / RateFunctionsSpec /
WindowIteratorSpec chunked-vs-sliding cross-checks)."""

import numpy as np
import pytest

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops.staging import stage_series

import oracle

BASE = 1_600_000_000_000


def make_series(n_series=7, n=300, seed=0, counter=False, irregular=True, resets=False,
                with_nans=False):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_series):
        if irregular:
            gaps = rng.integers(5_000, 15_000, n)
            ts = BASE + np.cumsum(gaps)
        else:
            ts = BASE + (1 + np.arange(n, dtype=np.int64)) * 10_000
        if counter:
            vals = np.cumsum(rng.uniform(0, 10, n)) + 1e9  # large baseline
            if resets and n > 20:
                k = rng.integers(n // 3, 2 * n // 3)
                vals[k:] -= vals[k] - rng.uniform(0, 5)
        else:
            vals = 50 + 20 * rng.standard_normal(n)
        if with_nans:
            vals[rng.integers(0, n, n // 10)] = np.nan
        out.append((ts.astype(np.int64), vals))
    return out


def run_both(func, series, window_ms=300_000, step_ms=60_000, num_steps=20,
             counter=False, delta=False, args=()):
    from filodb_tpu.query.exec.plans import (
        _CORRECTED_FNS, _DIFF_FNS, _SHIFTED_FNS,
    )

    # stage exactly the way the engine does: counter staging is
    # function-driven (corrected only for rate-family; shifted for
    # shift-invariant functions; diff-encoded for pairwise; raw otherwise)
    mode = "raw"
    if counter and not delta:
        if func in _CORRECTED_FNS:
            mode = "corrected"
        elif func in _SHIFTED_FNS:
            mode = "shifted"
        elif func in _DIFF_FNS:
            mode = "diff"
    start = BASE + window_ms + 60_000
    block = stage_series(
        [(t, v) for t, v in series], BASE,
        counter_corrected=mode == "corrected",
        subtract_baseline=mode == "shifted",
        diff_encode=mode == "diff",
    )
    params = K.RangeParams(start, step_ms, num_steps, window_ms)
    got = np.asarray(
        K.run_range_function(func, block, params, is_counter=counter, is_delta=delta, args=args)
    )[: len(series), :num_steps]
    want = np.stack([
        oracle.range_function(func, t, v, start, step_ms, num_steps, window_ms,
                              is_counter=counter, is_delta=delta, args=args)
        for t, v in series
    ])
    return got, want


def check(func, series, rtol=2e-4, atol=1e-3, **kw):
    got, want = run_both(func, series, **kw)
    assert got.shape == want.shape
    nan_g, nan_w = np.isnan(got), np.isnan(want)
    np.testing.assert_array_equal(nan_g, nan_w, err_msg=f"{func}: NaN pattern differs")
    m = ~nan_w
    np.testing.assert_allclose(got[m], want[m], rtol=rtol, atol=atol, err_msg=func)


GAUGE_FUNCS = [
    "sum_over_time", "count_over_time", "avg_over_time", "min_over_time",
    "max_over_time", "last_over_time", "first_over_time", "present_over_time",
    "stddev_over_time", "stdvar_over_time", "changes", "resets", "idelta",
    "deriv", "z_score",
]


@pytest.mark.parametrize("func", GAUGE_FUNCS)
def test_gauge_functions_match_oracle(func):
    check(func, make_series(n_series=7, n=300, seed=3))


@pytest.mark.parametrize("func", GAUGE_FUNCS)
def test_gauge_functions_regular_interval(func):
    check(func, make_series(n_series=5, n=200, seed=4, irregular=False))


def test_nan_staleness_dropped_before_device():
    check("sum_over_time", make_series(n_series=5, n=200, seed=9, with_nans=True))
    check("count_over_time", make_series(n_series=5, n=200, seed=9, with_nans=True))


@pytest.mark.parametrize("func", ["rate", "increase", "delta", "irate"])
def test_counter_functions_match_oracle(func):
    check(func, make_series(n_series=7, n=300, seed=5, counter=True), counter=True, rtol=1e-3)


@pytest.mark.parametrize("func", ["rate", "increase", "irate"])
def test_counter_resets_corrected(func):
    check(func, make_series(n_series=7, n=300, seed=6, counter=True, resets=True),
          counter=True, rtol=1e-3)


# variance-family functions need small deviations around a large mean; a
# counter reset puts 1e9-magnitude jumps inside one window, beyond what f32
# device math can recenter (Prometheus computes these in f64; stddev of a raw
# counter across a reset is not a meaningful query) — so they are verified on
# reset-free counters, where the shifted staging makes f32 exact
_VARIANCE_FNS = {"stddev_over_time", "stdvar_over_time", "z_score", "deriv"}


@pytest.mark.parametrize("func", [f for f in GAUGE_FUNCS if f not in _VARIANCE_FNS])
def test_non_rate_functions_on_counter_with_resets(func):
    # non-rate reads of a counter must see RAW values (no reset correction,
    # no baseline shift): resets() counts real resets, changes() sees them,
    # last/sum/min/max return raw magnitudes (advisor round-1 high finding)
    check(func, make_series(n_series=5, n=250, seed=15, counter=True, resets=True),
          counter=True, rtol=1e-3, atol=5e-3)


@pytest.mark.parametrize("func", sorted(_VARIANCE_FNS))
def test_variance_functions_on_counter_data(func):
    check(func, make_series(n_series=5, n=250, seed=15, counter=True),
          counter=True, rtol=1e-3, atol=5e-3)


def test_resets_on_counter_is_nonzero():
    series = make_series(n_series=5, n=250, seed=16, counter=True, resets=True)
    got, want = run_both("resets", series, counter=True)
    assert np.nanmax(want) >= 1, "fixture must contain a real reset"
    m = ~np.isnan(want)
    np.testing.assert_allclose(got[m], want[m])


def test_delta_counter_semantics():
    # delta-temporality: rate = sum/window
    series = make_series(n_series=4, n=200, seed=7)
    check("rate", series, counter=True, delta=True, rtol=1e-3)
    check("increase", series, counter=True, delta=True, rtol=1e-3)


def test_quantile_over_time():
    check("quantile_over_time", make_series(n_series=5, n=200, seed=8), args=(0.9,))
    check("quantile_over_time", make_series(n_series=5, n=200, seed=8), args=(0.0,))
    check("quantile_over_time", make_series(n_series=5, n=200, seed=8), args=(1.0,))


def test_mad_over_time():
    check("median_absolute_deviation_over_time", make_series(n_series=4, n=150, seed=10))


def test_predict_linear():
    check("predict_linear", make_series(n_series=5, n=200, seed=11), args=(600.0,), rtol=1e-3, atol=5e-3)


def test_holt_winters():
    check("double_exponential_smoothing", make_series(n_series=5, n=200, seed=12),
          args=(0.3, 0.1), rtol=1e-3)


def test_empty_windows_are_nan():
    # one series with a large gap: windows inside the gap must be NaN
    ts = np.concatenate([BASE + np.arange(10) * 10_000,
                         BASE + 10_000_000 + np.arange(10) * 10_000]).astype(np.int64)
    vals = np.ones(20)
    check("sum_over_time", [(ts, vals)], num_steps=40)


def test_absent_over_time():
    ts = (BASE + np.arange(5) * 10_000).astype(np.int64)
    check("absent_over_time", [(ts, np.ones(5))], num_steps=40)


def test_sparse_vs_window_shorter_than_step():
    check("sum_over_time", make_series(3, 100, seed=13), window_ms=30_000, step_ms=120_000,
          num_steps=10)
    check("rate", make_series(3, 300, seed=14, counter=True), window_ms=60_000,
          step_ms=120_000, num_steps=10, counter=True, rtol=1e-3)
