"""Chaos tests: deterministic fault injection over the scatter-gather path
(query/faults.py retry/breaker/partial-results + testkit.FaultInjector).

Everything here is seeded and clock-injected — no sleeps against real
failure windows, no flaky timing: the same schedule always produces the
same outcomes, so these run inside tier-1.
"""

from __future__ import annotations

import json
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import ShardManager, ShardStatus
from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec.plans import ExecPlan, QueryContext
from filodb_tpu.query.exec.transformers import QueryDeadlineExceeded
from filodb_tpu.query.faults import (
    BreakerRegistry,
    CircuitOpenError,
    RetryPolicy,
    dispatch_child,
)
from filodb_tpu.query.rangevector import QueryResult
from filodb_tpu.testkit import FaultInjector, FaultRule, InjectedFault, counter_batch

pytestmark = pytest.mark.chaos

START = 1_600_000_000_000
Q = "sum(rate(http_requests_total[5m]))"
S, E = START / 1000 + 400, START / 1000 + 900


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FlakyRemoteExec(ExecPlan):
    """Minimal remote leaf: fails its first ``fail_times`` executions (or
    always), then returns an empty result."""

    is_remote = True

    def __init__(self, endpoint: str, fail_times: int | None = None,
                 always_fail: bool = False):
        super().__init__()
        self.endpoint = endpoint
        self.fail_times = fail_times
        self.always_fail = always_fail
        self.calls = 0

    def args_str(self) -> str:
        return f"endpoint={self.endpoint}"

    def do_execute(self, ctx):
        n = self.calls
        self.calls += 1
        if self.always_fail or (self.fail_times is not None and n < self.fail_times):
            raise InjectedFault(f"flaky {self.endpoint} call {n}")
        return QueryResult()


def make_engine(dispatcher=None, **params):
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed(
        "prometheus",
        counter_batch(n_series=16, n_samples=60, start_ms=START),
        spread=2,
    )
    eng = QueryEngine(
        ms, "prometheus",
        PlannerParams(spread=2, num_shards=4, dispatcher=dispatcher, **params),
    )
    return ms, eng


def make_ctx(deadline_s: float = 60.0, **kw) -> QueryContext:
    ctx = QueryContext(None, "ds", deadline_s=deadline_s)
    for k, v in kw.items():
        setattr(ctx, k, v)
    return ctx


# ---------------------------------------------------------------------------
# partial results
# ---------------------------------------------------------------------------


class TestPartialResults:
    def test_aggregation_merges_survivors_and_names_lost_shard(self):
        ms0, full_eng = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=1)
        _, eng = make_engine(dispatcher=inj)
        full = full_eng.query_range(Q, S, E, 60)
        res = eng.query_range(Q, S, E, 60, allow_partial_results=True)
        assert res.partial is True
        assert len(res.warnings) == 1
        w = res.warnings[0]
        assert w["shard"] == victim and w["plan"] == "SelectRawPartitionsExec"
        assert "InjectedFault" in w["error"]
        # survivors merged: same grid shape, strictly less mass than full
        got, want = res.grids[0].values_np(), full.grids[0].values_np()
        assert got.shape == want.shape
        assert 0 < np.nansum(got) < np.nansum(want)

    def test_without_flag_single_wrapped_error(self):
        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=1)
        _, eng = make_engine(dispatcher=inj)
        with pytest.raises(InjectedFault, match=r"child SelectRawPartitionsExec"):
            eng.query_range(Q, S, E, 60)

    def test_all_children_lost_still_raises(self):
        inj = FaultInjector([FaultRule(target="SelectRawPartitionsExec")], seed=1)
        _, eng = make_engine(dispatcher=inj)
        with pytest.raises(InjectedFault):
            eng.query_range(Q, S, E, 60, allow_partial_results=True)

    def test_latency_injection_still_correct(self):
        """A straggler shard (latency spike, no failure) changes nothing in
        the result — the gather absorbs it."""
        slept = []
        inj = FaultInjector(
            [FaultRule(target="shard=", kind="latency", latency_s=0.01, count=2)],
            seed=3, sleep=slept.append,
        )
        _, eng = make_engine(dispatcher=inj)
        _, full_eng = make_engine()
        res = eng.query_range(Q, S, E, 60, allow_partial_results=True)
        full = full_eng.query_range(Q, S, E, 60)
        assert not res.partial and not res.warnings
        np.testing.assert_allclose(
            res.grids[0].values_np(), full.grids[0].values_np(), rtol=1e-6
        )
        assert slept == [0.01, 0.01]

    def test_deterministic_across_runs(self):
        """Same seed + schedule => byte-identical warnings on every run."""
        outs = []
        for _ in range(2):
            inj = FaultInjector([FaultRule(target="shard=1 ")], seed=42)
            _, eng = make_engine(dispatcher=inj)
            res = eng.query_range(Q, S, E, 60, allow_partial_results=True)
            outs.append((json.dumps(res.warnings, sort_keys=True),
                         np.nansum(res.grids[0].values_np())))
        assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# retries
# ---------------------------------------------------------------------------


class TestRetries:
    def test_transient_failure_recovers_with_backoff(self):
        sleeps: list[float] = []
        policy = RetryPolicy(max_attempts=4, base_backoff_s=0.01, seed=7,
                             sleep=sleeps.append)
        child = FlakyRemoteExec("grpc://p:1", fail_times=2)
        ctx = make_ctx(retry_policy=policy, breakers=BreakerRegistry())
        res = dispatch_child(child, ctx)
        assert isinstance(res, QueryResult)
        assert child.calls == 3  # 2 failures + the success
        assert len(sleeps) == 2
        assert sleeps[1] > sleeps[0]  # exponential growth survives jitter

    def test_jitter_is_deterministic_with_seed(self):
        runs = []
        for _ in range(2):
            sleeps: list[float] = []
            policy = RetryPolicy(max_attempts=4, base_backoff_s=0.01, seed=7,
                                 sleep=sleeps.append)
            ctx = make_ctx(retry_policy=policy, breakers=BreakerRegistry())
            dispatch_child(FlakyRemoteExec("grpc://p:1", fail_times=2), ctx)
            runs.append(tuple(sleeps))
        assert runs[0] == runs[1]

    def test_exhausted_attempts_raise_last_error(self):
        sleeps: list[float] = []
        policy = RetryPolicy(max_attempts=3, base_backoff_s=0.01, seed=0,
                             sleep=sleeps.append)
        child = FlakyRemoteExec("grpc://p:1", always_fail=True)
        ctx = make_ctx(retry_policy=policy, breakers=BreakerRegistry())
        with pytest.raises(InjectedFault):
            dispatch_child(child, ctx)
        assert child.calls == 3 and len(sleeps) == 2

    def test_backoff_never_outlives_deadline(self):
        """A backoff that would sleep past the deadline is not taken."""
        sleeps: list[float] = []
        policy = RetryPolicy(max_attempts=50, base_backoff_s=10.0, jitter=0.0,
                             seed=0, sleep=sleeps.append)
        child = FlakyRemoteExec("grpc://p:1", always_fail=True)
        ctx = make_ctx(deadline_s=0.5, retry_policy=policy,
                       breakers=BreakerRegistry())
        with pytest.raises(InjectedFault):
            dispatch_child(child, ctx)
        assert child.calls == 1  # no retry: 10s backoff >= 0.5s budget
        assert sleeps == []

    def test_grpc_unavailable_retries_at_dispatch_layer(self):
        """A real dead gRPC endpoint: the transport (retries disabled for
        plan-scatter children) surfaces UNAVAILABLE marked retryable, and
        the dispatch-layer policy — the one config tunes — retries it."""
        from filodb_tpu.api.grpc_exec import GrpcPlanRemoteExec
        from filodb_tpu.query import logical as L
        from filodb_tpu.query.proto_plan import RemoteExecError

        sleeps: list[float] = []
        policy = RetryPolicy(max_attempts=2, base_backoff_s=0.01, seed=1,
                             sleep=sleeps.append)
        ctx = make_ctx(deadline_s=30.0, retry_policy=policy,
                       breakers=BreakerRegistry())
        child = GrpcPlanRemoteExec("grpc://127.0.0.1:9", L.LabelNames((), 1, 2))
        with pytest.raises(RemoteExecError, match="UNAVAILABLE"):
            dispatch_child(child, ctx)
        assert len(sleeps) == 1  # the dispatch layer retried once

    def test_retry_sequence_bounded_by_deadline_wallclock(self):
        """Real-sleep variant: many fast failures + small backoffs still end
        within the query deadline."""
        deadline = 0.3
        policy = RetryPolicy(max_attempts=1000, base_backoff_s=0.02,
                             max_backoff_s=0.02, jitter=0.0, seed=0)
        child = FlakyRemoteExec("grpc://p:1", always_fail=True)
        # breaker sized to never open: retries, not the breaker, must stop
        ctx = make_ctx(deadline_s=deadline, retry_policy=policy,
                       breakers=BreakerRegistry(min_calls=10_000))
        t0 = time.monotonic()
        with pytest.raises((InjectedFault, QueryDeadlineExceeded)):
            dispatch_child(child, ctx)
        elapsed = time.monotonic() - t0
        assert child.calls > 1  # it did retry
        assert elapsed <= deadline + 0.1


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _ctx(self, clock, **breaker_kw):
        kw = dict(window=8, failure_rate=0.5, min_calls=4, cooldown_s=10.0)
        kw.update(breaker_kw)
        breakers = BreakerRegistry(clock=clock, **kw)
        policy = RetryPolicy(max_attempts=1, seed=0, sleep=lambda s: None)
        return make_ctx(retry_policy=policy, breakers=breakers), breakers

    def test_opens_at_threshold_and_fails_fast(self):
        clock = FakeClock()
        ctx, breakers = self._ctx(clock)
        child = FlakyRemoteExec("grpc://flappy:1", always_fail=True)
        for _ in range(4):
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        br = breakers.breaker_for("grpc://flappy:1")
        assert br.state() == "open"
        with pytest.raises(CircuitOpenError, match="grpc://flappy:1"):
            dispatch_child(child, ctx)
        assert child.calls == 4  # open breaker never dispatched

    def test_recloses_after_cooldown_probe(self):
        clock = FakeClock()
        ctx, breakers = self._ctx(clock)
        child = FlakyRemoteExec("grpc://flappy:1", always_fail=True)
        for _ in range(4):
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        br = breakers.breaker_for("grpc://flappy:1")
        assert br.state() == "open"
        clock.advance(10.0)
        assert br.state() == "half_open"
        child.always_fail = False  # endpoint recovered
        dispatch_child(child, ctx)  # the probe
        assert br.state() == "closed"
        dispatch_child(child, ctx)  # and traffic flows again
        assert child.calls == 6

    def test_failed_probe_reopens(self):
        clock = FakeClock()
        ctx, breakers = self._ctx(clock)
        child = FlakyRemoteExec("grpc://flappy:1", always_fail=True)
        for _ in range(4):
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        clock.advance(10.0)
        with pytest.raises(InjectedFault):
            dispatch_child(child, ctx)  # probe fails
        br = breakers.breaker_for("grpc://flappy:1")
        assert br.state() == "open"
        # fresh cooldown: still open halfway through
        clock.advance(5.0)
        assert br.state() == "open"
        clock.advance(5.0)
        assert br.state() == "half_open"

    def test_flapping_endpoint_converges_via_injector(self):
        """End-to-end convergence: a flapping endpoint (4 bad, 4 good, ...)
        opens its breaker within the threshold, then re-closes after cooldown
        once the probe lands in a healthy phase."""
        clock = FakeClock()
        ctx, breakers = self._ctx(clock)
        inj = FaultInjector(
            [FaultRule(target="grpc://flap:7", kind="flap", period=4)], seed=9,
        )
        ctx.dispatcher = inj
        child = FlakyRemoteExec("grpc://flap:7")  # healthy unless injected
        for _ in range(4):  # failing phase -> breaker opens at min_calls
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        br = breakers.breaker_for("grpc://flap:7")
        assert br.state() == "open"
        with pytest.raises(CircuitOpenError):
            dispatch_child(child, ctx)
        clock.advance(10.0)
        dispatch_child(child, ctx)  # probe: injector now in healthy phase
        assert br.state() == "closed"
        for _ in range(3):
            dispatch_child(child, ctx)  # healthy phase continues

    def test_typed_error_probe_does_not_wedge_half_open(self):
        """Regression: a query-shaped error (peer answered) during the
        half-open probe must release the probe slot — not leave the breaker
        half-open with zero capacity forever."""
        from filodb_tpu.query.exec.transformers import QueryError

        class TypedErrorExec(FlakyRemoteExec):
            typed = False

            def do_execute(self, ctx):
                self.calls += 1
                if self.typed:
                    raise QueryError("bad query per the peer")
                raise InjectedFault("transport down")

        clock = FakeClock()
        ctx, breakers = self._ctx(clock)
        child = TypedErrorExec("grpc://wedge:1")
        for _ in range(4):
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        br = breakers.breaker_for("grpc://wedge:1")
        clock.advance(10.0)
        assert br.state() == "half_open"
        child.typed = True  # probe gets a typed answer, not a transport fail
        with pytest.raises(QueryError):
            dispatch_child(child, ctx)
        assert br.state() == "half_open"  # no transition either way...
        with pytest.raises(QueryError):
            dispatch_child(child, ctx)  # ...but the slot was released
        child.typed = False
        child.always_fail = False
        child.fail_times = 0

        class HealthyExec(FlakyRemoteExec):
            pass

        healthy = HealthyExec("grpc://wedge:1")
        dispatch_child(healthy, ctx)  # successful probe closes it
        assert br.state() == "closed"

    def test_breaker_metrics_exposed(self):
        from filodb_tpu.metrics import REGISTRY

        clock = FakeClock()
        ctx, _ = self._ctx(clock)
        child = FlakyRemoteExec("grpc://metrics-probe:1", always_fail=True)
        for _ in range(4):
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        text = REGISTRY.expose()
        assert ('filodb_breaker_transitions_total{endpoint="grpc://metrics-probe:1",'
                'frm="closed",to="open"}') in text
        assert 'filodb_breaker_state{endpoint="grpc://metrics-probe:1"} 1' in text


# ---------------------------------------------------------------------------
# cross-transport partial results
# ---------------------------------------------------------------------------


class TestPartialOverGrpc:
    def test_warnings_cross_the_wire(self):
        from filodb_tpu.api.grpc_exec import exec_promql, serve_grpc
        from filodb_tpu.query.proto_plan import RemoteExecError

        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=5)
        _, eng = make_engine(dispatcher=inj)
        server, port = serve_grpc(eng, port=0, host="127.0.0.1")
        ep = f"grpc://127.0.0.1:{port}"
        try:
            res = exec_promql(ep, Q, int(S * 1000), int(E * 1000), 60_000,
                              allow_partial=True)
            assert res.partial is True
            assert res.warnings and res.warnings[0]["shard"] == victim
            assert res.grids and res.grids[0].n_series == 1
            # without the flag the same query is an in-band error
            with pytest.raises(RemoteExecError, match="InjectedFault"):
                exec_promql(ep, Q, int(S * 1000), int(E * 1000), 60_000)
        finally:
            server.stop(grace=0)

    def test_explicit_strict_overrides_peer_partial_default(self):
        """allow_partial is tri-state on the wire: absent -> the peer's
        configured default applies; explicit False -> strict even on a peer
        whose default is partial=True."""
        from filodb_tpu.api.grpc_exec import exec_promql, serve_grpc
        from filodb_tpu.query.proto_plan import RemoteExecError

        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=5)
        _, eng = make_engine(dispatcher=inj, allow_partial_results=True)
        server, port = serve_grpc(eng, port=0, host="127.0.0.1")
        ep = f"grpc://127.0.0.1:{port}"
        try:
            # absent flag: peer's default (partial) applies
            res = exec_promql(ep, Q, int(S * 1000), int(E * 1000), 60_000)
            assert res.partial is True and res.warnings
            # explicit strict: overrides the peer's partial default
            with pytest.raises(RemoteExecError, match="InjectedFault"):
                exec_promql(ep, Q, int(S * 1000), int(E * 1000), 60_000,
                            allow_partial=False)
        finally:
            server.stop(grace=0)


class TestPartialOverFlight:
    def test_warnings_ride_schema_metadata(self):
        pytest.importorskip("pyarrow.flight")
        from filodb_tpu.api.arrow_edge import HAVE_FLIGHT

        if not HAVE_FLIGHT:
            pytest.skip("pyarrow.flight unavailable")
        from filodb_tpu.api.arrow_edge import FlightQueryClient, FlightQueryServer

        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=5)
        # Flight tickets carry no per-request flag: the engine default governs
        _, eng = make_engine(dispatcher=inj, allow_partial_results=True)
        server = FlightQueryServer(eng)
        try:
            ep = f"grpc://127.0.0.1:{server.port}"
            res = FlightQueryClient.query_range(ep, Q, S, E, 60)
            assert res.partial is True
            assert res.warnings and res.warnings[0]["shard"] == victim
            assert res.grids
        finally:
            server.shutdown()


class TestQueryDeadline:
    def test_deadline_exceeded_never_degrades_to_partial(self):
        """A query-deadline breach is a query-level condition: even with
        allow_partial_results the query fails instead of returning a 'partial'
        200 missing the shards that never got to run."""
        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)

        class DeadlineBurner:
            """Dispatcher: the first child succeeds, then the budget is
            spent — remaining children all hit the deadline. Pre-fix, the
            one survivor made this a 'partial' success."""

            def dispatch(self, child, ctx):
                out = child.execute(ctx)
                ctx._start_time -= ctx.deadline_s + 1  # burn the budget
                return out

        _, eng = make_engine(dispatcher=DeadlineBurner(), deadline_s=30)
        with pytest.raises(QueryDeadlineExceeded):
            eng.query_range(Q, S, E, 60, allow_partial_results=True)


class TestPartialOverHttp:
    def test_warnings_and_partial_in_json(self):
        from filodb_tpu.api.http import serve_background

        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=5)
        _, eng = make_engine(dispatcher=inj)
        srv, port = serve_background(eng, port=0)
        try:
            url = (
                f"http://127.0.0.1:{port}/api/v1/query_range?query="
                f"{urllib.parse.quote(Q)}&start={S}&end={E}&step=60"
                "&allow_partial_results=true"
            )
            with urllib.request.urlopen(url, timeout=30) as r:
                payload = json.loads(r.read())
            assert payload["status"] == "success"
            assert payload["partial"] is True
            assert payload["warnings"][0]["shard"] == victim
            assert payload["data"]["result"]
            # metrics exposition counts the partial answer
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30
            ) as r:
                text = r.read().decode()
            assert "filodb_partial_results_total" in text
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# trace annotations (metrics.py spans x fault machinery)
# ---------------------------------------------------------------------------


class TestTraceAnnotations:
    def test_retries_annotate_dispatching_span(self):
        from filodb_tpu.metrics import span

        sleeps: list[float] = []
        policy = RetryPolicy(max_attempts=4, base_backoff_s=0.01, seed=7,
                             sleep=sleeps.append)
        child = FlakyRemoteExec("grpc://p:1", fail_times=2)
        ctx = make_ctx(retry_policy=policy, breakers=BreakerRegistry())
        with span("gather") as s:
            dispatch_child(child, ctx)
        assert s.tags["retries"]["grpc://p:1"] == 2
        # each ATTEMPT produced its own child span (3 = 2 failures + success)
        assert [c.name for c in s.children] == ["FlakyRemoteExec"] * 3

    def test_open_breaker_annotates_span(self):
        from filodb_tpu.metrics import span

        clock = FakeClock()
        breakers = BreakerRegistry(clock=clock, window=8, failure_rate=0.5,
                                   min_calls=4, cooldown_s=10.0)
        policy = RetryPolicy(max_attempts=1, seed=0, sleep=lambda s: None)
        ctx = make_ctx(retry_policy=policy, breakers=breakers)
        child = FlakyRemoteExec("grpc://annot:1", always_fail=True)
        for _ in range(4):
            with pytest.raises(InjectedFault):
                dispatch_child(child, ctx)
        with span("gather") as s:
            with pytest.raises(CircuitOpenError):
                dispatch_child(child, ctx)
        assert s.tags["breaker_open"] == ["grpc://annot:1"]
        # half-open probing is annotated as breaker state encountered
        clock.advance(10.0)
        child.always_fail = False
        with span("gather2") as s2:
            dispatch_child(child, ctx)
        assert s2.tags["breaker_state"]["grpc://annot:1"] == "half_open"

    def test_partial_drops_annotate_merge_node_span(self):
        """Chaos-injected partials appear as lost_children annotations on
        the merge node's span in the query's trace tree."""
        from filodb_tpu.metrics import trace_to_dict

        ms0, _ = make_engine()
        victim = next(sh.shard_num for sh in ms0.shards("prometheus")
                      if sh.num_partitions)
        inj = FaultInjector([FaultRule(target=f"shard={victim} ")], seed=1)
        _, eng = make_engine(dispatcher=inj)
        res = eng.query_range(Q, S, E, 60, allow_partial_results=True)
        assert res.partial is True

        def walk(d):
            yield d
            for c in d.get("children", ()):
                yield from walk(c)

        tree = trace_to_dict(res.trace)
        annotated = [
            sp for sp in walk(tree)
            if "lost_children" in sp.get("tags", {})
        ]
        assert len(annotated) == 1
        lost = annotated[0]["tags"]["lost_children"]
        assert lost == res.warnings
        assert lost[0]["shard"] == victim


# ---------------------------------------------------------------------------
# shard reassignment convergence
# ---------------------------------------------------------------------------


class TestReassignmentSettles:
    def test_repeated_ingestion_errors_settle_down_not_bounce(self):
        clock = FakeClock()
        mgr = ShardManager(4, shards_per_node=4, reassignment_damper_s=3600,
                           clock=clock)
        mgr.node_joined("a")
        mgr.node_joined("b")
        events = []
        mgr.mapper.subscribe(events.append)
        for _ in range(6):
            mgr.ingestion_error(0)
            clock.advance(1.0)
        # converged: DOWN, not oscillating between nodes
        assert mgr.mapper.status_of(0) == ShardStatus.DOWN
        assigns = [e for e in events
                   if e.shard == 0 and e.status == ShardStatus.ASSIGNED]
        assert len(assigns) == 1  # exactly one reassignment before the damper
        # damper expiry: the shard is recoverable again
        clock.advance(3600.0)
        assert mgr.ingestion_error(0) is True
        assert mgr.mapper.status_of(0) == ShardStatus.ASSIGNED
