"""Differential parser fuzzing (reference analog: dual LegacyParser/Antlr
shadow mode, Parser.scala:40-52 — two independent readings of every query
cross-checked). We have ONE parser, so the differential pair here is
parse ∘ unparse: for randomly generated expression trees, the unparsed
PromQL must re-parse to a plan whose unparse is a fixpoint, and both plans
must materialize to identical exec trees."""

import random

import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.query.unparse import to_promql

METRICS = ["up", "http_requests_total", "heap_usage0", "node_cpu_seconds_total"]
LABELS = [("job", "api"), ("instance", "h1"), ("_ws_", "demo"), ("code", "500")]
RANGE_FNS = ["rate", "increase", "irate", "delta", "avg_over_time", "sum_over_time",
             "min_over_time", "max_over_time", "count_over_time", "last_over_time",
             "stddev_over_time", "changes", "resets", "deriv", "present_over_time"]
INSTANT_FNS = ["abs", "ceil", "floor", "exp", "ln", "sqrt", "sgn"]
AGG_OPS = ["sum", "min", "max", "avg", "count", "stddev", "group"]
BIN_OPS = ["+", "-", "*", "/", ">", "<", ">=", "<=", "!=", "=="]
WINDOWS = ["1m", "5m", "10m", "1h"]
MATCH_OPS = ["=", "!=", "=~", "!~"]


def gen_selector(rng: random.Random) -> str:
    m = rng.choice(METRICS)
    n = rng.randint(0, 2)
    if n == 0:
        return m
    parts = []
    for k, v in rng.sample(LABELS, n):
        op = rng.choice(MATCH_OPS)
        val = v if op in ("=", "!=") else f"{v}.*"
        parts.append(f'{k}{op}"{val}"')
    return f"{m}{{{','.join(parts)}}}"


def gen_expr(rng: random.Random, depth: int = 0) -> str:
    roll = rng.random()
    if depth >= 3 or roll < 0.25:
        sel = gen_selector(rng)
        if rng.random() < 0.6:
            return f"{rng.choice(RANGE_FNS)}({sel}[{rng.choice(WINDOWS)}])"
        return sel
    if roll < 0.5:
        by = ""
        if rng.random() < 0.5:
            keys = ",".join(k for k, _ in rng.sample(LABELS, rng.randint(1, 2)))
            by = f" by ({keys})"
        return f"{rng.choice(AGG_OPS)}{by}({gen_expr(rng, depth + 1)})"
    if roll < 0.7:
        return f"{rng.choice(INSTANT_FNS)}({gen_expr(rng, depth + 1)})"
    if roll < 0.85:
        op = rng.choice(BIN_OPS)
        b = "bool " if op in (">", "<", ">=", "<=", "!=", "==") and rng.random() < 0.3 else ""
        return f"({gen_expr(rng, depth + 1)}) {op} {b}{rng.random():.1f}"
    return f"({gen_expr(rng, depth + 1)}) {rng.choice(['+', '-', '*', '/'])} ({gen_expr(rng, depth + 1)})"


@pytest.mark.parametrize("seed", range(40))
def test_unparse_differential(seed):
    rng = random.Random(seed)
    q = gen_expr(rng)
    p1 = query_range_to_logical_plan(q, 1_600_000_400, 1_600_000_900, 60)
    s1 = to_promql(p1)
    p2 = query_range_to_logical_plan(s1, 1_600_000_400, 1_600_000_900, 60)
    s2 = to_promql(p2)
    assert s1 == s2, f"unparse not a fixpoint for {q!r}: {s1!r} vs {s2!r}"

    # both plans must materialize to identical exec trees
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0, 1])
    pl = SingleClusterPlanner(ms, "prometheus")
    t1 = pl.materialize(p1).print_tree()
    t2 = pl.materialize(p2).print_tree()
    assert t1 == t2, f"exec divergence for {q!r}"
