"""Planner hierarchy tests with golden plan trees (model: reference
LongTimeRangePlannerSpec, HighAvailabilityPlannerSpec,
MultiPartitionPlannerSpec, ShardKeyRegexPlannerSpec — printTree golden
assertions + execution checks)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine, SingleClusterPlanner
from filodb_tpu.coordinator.planners import (
    DownsampleClusterPlanner,
    FailureTimeRange,
    HighAvailabilityPlanner,
    LongTimeRangePlanner,
    MultiPartitionPlanner,
    PartitionAssignment,
    PromQlRemoteExec,
    ShardKeyRegexPlanner,
    SinglePartitionPlanner,
)
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.downsample.downsampler import DS_GAUGE, ShardDownsampler
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.query.exec.plans import QueryContext, StitchRvsExec
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.query.unparse import to_promql
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def make_ms(n_series=6, n_samples=400):
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("prometheus"), range(2))
    ms.ingest_routed(
        "prometheus", machine_metrics(n_series=n_series, n_samples=n_samples, start_ms=BASE), spread=1
    )
    return ms


class TestUnparse:
    @pytest.mark.parametrize("q", [
        "sum(rate(http_requests_total[5m]))",
        'sum by (job) (rate(cpu{env="prod"}[5m]))',
        "histogram_quantile(0.9,rate(lat[5m]))",
        "(a + b)",
        "topk(5,cpu)",
        "quantile_over_time(0.99,m[10m])",
        "(cpu > bool 10)",
        "max_over_time(rate(cpu[1m])[30m:1m])",
        'count_values("v",build)',
        "avg without (inst) (cpu)",
    ])
    def test_roundtrip_parses_back(self, q):
        plan = query_range_to_logical_plan(q, 1000, 2000, 15)
        s = to_promql(plan)
        plan2 = query_range_to_logical_plan(s, 1000, 2000, 15)
        assert to_promql(plan2) == s  # stable fixpoint


class TestLongTimeRange:
    def setup_method(self):
        self.ms = make_ms()
        # downsample store: 5m resolution of the same data
        self.dsm = TimeSeriesMemStore()
        self.dsm.setup(Dataset("prometheus_5m", schemas=[DS_GAUGE]), range(2))
        d = ShardDownsampler(self.dsm, "prometheus")
        for sh in self.ms.shards("prometheus"):
            for part in sh.partitions.values():
                part.switch_buffers()
                d.downsample_chunks(sh.shard_num, part, part.chunks)
        self.raw = SingleClusterPlanner(self.ms, "prometheus")
        self.ds = DownsampleClusterPlanner(self.dsm, "prometheus_5m")
        # raw data "retained" only after BASE+2000s
        self.boundary = BASE + 2_000_000
        self.planner = LongTimeRangePlanner(self.raw, self.ds, lambda: self.boundary)

    def test_recent_query_goes_raw(self):
        plan = query_range_to_logical_plan(
            "avg_over_time(heap_usage0[5m])", (BASE + 2_500_000) / 1000, (BASE + 3_500_000) / 1000, 60
        )
        exec_plan = self.planner.materialize(plan)
        assert "Stitch" not in exec_plan.print_tree()

    def test_old_query_goes_downsample(self):
        plan = query_range_to_logical_plan(
            "avg_over_time(heap_usage0[5m])", (BASE + 300_000) / 1000, (BASE + 1_200_000) / 1000, 60
        )
        exec_plan = self.planner.materialize(plan)
        tree = exec_plan.print_tree()
        assert "Stitch" not in tree
        ctx = QueryContext(self.dsm, "prometheus_5m")
        res = exec_plan.execute(ctx)
        assert sum(g.n_series for g in res.grids) == 6

    def test_spanning_query_stitches(self):
        plan = query_range_to_logical_plan(
            "avg_over_time(heap_usage0[5m])", (BASE + 600_000) / 1000, (BASE + 3_500_000) / 1000, 60
        )
        exec_plan = self.planner.materialize(plan)
        assert isinstance(exec_plan, StitchRvsExec)


class TestHighAvailability:
    def test_no_failures_local(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")
        ha = HighAvailabilityPlanner(local, "http://buddy:9090", lambda: [])
        plan = query_range_to_logical_plan("heap_usage0", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60)
        assert "Remote" not in ha.materialize(plan).print_tree()

    def test_failure_window_routes_remote(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")
        fail = FailureTimeRange(BASE + 600_000, BASE + 900_000)
        ha = HighAvailabilityPlanner(local, "http://buddy:9090", lambda: [fail])
        plan = query_range_to_logical_plan(
            "sum(rate(heap_usage0[5m]))", (BASE + 300_000) / 1000, (BASE + 1_800_000) / 1000, 60
        )
        exec_plan = ha.materialize(plan)
        tree = exec_plan.print_tree()
        assert "PromQlRemoteExec" in tree and "Stitch" in tree
        remotes = [c for c in exec_plan.child_plans if isinstance(c, PromQlRemoteExec)]
        assert remotes and remotes[0].endpoint == "http://buddy:9090"
        assert "rate(" in remotes[0].promql

    def test_total_failure_all_remote(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")
        fail = FailureTimeRange(BASE, BASE + 10**9)
        ha = HighAvailabilityPlanner(local, "http://buddy:9090", lambda: [fail])
        plan = query_range_to_logical_plan("heap_usage0", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60)
        exec_plan = ha.materialize(plan)
        assert isinstance(exec_plan, PromQlRemoteExec)


class TestMultiPartition:
    def test_local_partition_plans_locally(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")

        def locate(keys):
            return PartitionAssignment("local", None)

        mp = MultiPartitionPlanner(local, locate)
        plan = query_range_to_logical_plan("heap_usage0", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60)
        assert "Remote" not in mp.materialize(plan).print_tree()

    def test_foreign_partition_goes_remote(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")

        def locate(keys):
            if keys.get("_ns_") == "App-2":
                return PartitionAssignment("remote-1", "http://other:9090")
            return PartitionAssignment("local", None)

        mp = MultiPartitionPlanner(local, locate)
        plan = query_range_to_logical_plan(
            'sum(rate(m{_ws_="demo",_ns_="App-2"}[5m]))', (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60
        )
        exec_plan = mp.materialize(plan)
        assert isinstance(exec_plan, PromQlRemoteExec)
        assert "sum" in exec_plan.promql and "rate" in exec_plan.promql

    def test_cross_partition_join(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")

        def locate(keys):
            if keys.get("_ns_") == "other":
                return PartitionAssignment("remote-1", "http://other:9090")
            return PartitionAssignment("local", None)

        mp = MultiPartitionPlanner(local, locate)
        plan = query_range_to_logical_plan(
            'a{_ns_="App-2"} + b{_ns_="other"}', (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60
        )
        tree = mp.materialize(plan).print_tree()
        assert "BinaryJoinExec" in tree and "PromQlRemoteExec" in tree


class TestShardKeyRegex:
    def test_regex_expansion_fans_out(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")
        skr = ShardKeyRegexPlanner(local, lambda key: ["App-0", "App-1", "App-2"])
        plan = query_range_to_logical_plan(
            'sum(rate(heap_usage0{_ws_="demo",_ns_=~"App-1|App-2"}[5m]))',
            (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60,
        )
        exec_plan = skr.materialize(plan)
        tree = exec_plan.print_tree()
        assert "AggregatePresentExec" in tree
        # two concrete _ns_ values -> two subtrees (fused single-dispatch
        # aggregates on the default engine)
        assert tree.count("FusedAggregateExec") == 2

    def test_no_regex_passthrough(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")
        skr = ShardKeyRegexPlanner(local, lambda key: ["App-2"])
        plan = query_range_to_logical_plan(
            "heap_usage0", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60)
        res = skr.materialize(plan).execute(QueryContext(ms, "prometheus"))
        assert sum(g.n_series for g in res.grids) == 6

    def test_regex_execution_correct(self):
        ms = make_ms()
        local = SingleClusterPlanner(ms, "prometheus")
        skr = ShardKeyRegexPlanner(local, lambda key: ["App-2", "App-X"])
        plan = query_range_to_logical_plan(
            'sum(avg_over_time(heap_usage0{_ns_=~"App-.*"}[5m]))',
            (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60,
        )
        res = skr.materialize(plan).execute(QueryContext(ms, "prometheus"))
        # only App-2 has data; result identical to direct query
        want = QueryEngine(ms, "prometheus").query_range(
            "sum(avg_over_time(heap_usage0[5m]))", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60
        )
        np.testing.assert_allclose(
            res.grids[0].values_np(), want.grids[0].values_np(), rtol=1e-5, equal_nan=True
        )


class TestSinglePartitionPlanner:
    def test_picks_by_metric(self):
        ms = make_ms()
        a = SingleClusterPlanner(ms, "prometheus")
        b = SingleClusterPlanner(ms, "prometheus")
        calls = []

        class Spy:
            def __init__(self, name, inner):
                self.name, self.inner = name, inner

            def materialize(self, plan):
                calls.append(self.name)
                return self.inner.materialize(plan)

        spp = SinglePartitionPlanner(
            {"a": Spy("a", a), "b": Spy("b", b)},
            pick=lambda plan: "b" if any(
                f.value == "special" for rs in __import__("filodb_tpu.query.logical", fromlist=["leaf_raw_series"]).leaf_raw_series(plan) for f in rs.filters
            ) else "a",
            default="a",
        )
        plan = query_range_to_logical_plan("special", (BASE + 600_000) / 1000, (BASE + 1_200_000) / 1000, 60)
        spp.materialize(plan)
        assert calls == ["b"]


class TestUnparseMore:
    @pytest.mark.parametrize("q", [
        "last_over_time(m[5m])",
        "predict_linear(m[1h],600)",
        "holt_winters(m[10m],0.5,0.1)",
        'label_replace(m,"d","$1","s","(.*)")',
        "sort_desc(sum by (a) (m))",
        "scalar(sum(m))",
        "vector(1)",
        "absent(m)",
        "(a unless on (x) b)",
        "clamp(m,0,10)",
        "histogram_fraction(0,0.5,rate(h[5m]))",
        "(time() + 100)",
        "stddev without (i) (m)",
    ])
    def test_fixpoint(self, q):
        plan = query_range_to_logical_plan(q, 1000, 2000, 15)
        s = to_promql(plan)
        plan2 = query_range_to_logical_plan(s, 1000, 2000, 15)
        assert to_promql(plan2) == s
