"""Repair jobs + query limit enforcement tests (model: reference
spark-jobs repair/cardbuster specs + QueryContext enforced limits)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine, SingleClusterPlanner
from filodb_tpu.core.filters import equals, regex
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.query.exec.plans import QueryContext
from filodb_tpu.query.exec.transformers import QueryError
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.store.columnstore import LocalColumnStore
from filodb_tpu.store.flush import FlushCoordinator, recover_shard
from filodb_tpu.store.repair import bust_cardinality, copy_chunks, copy_partkeys
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def flushed_store(tmp_path, n_series=6):
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=n_series, n_samples=200, start_ms=BASE))
    store = LocalColumnStore(str(tmp_path / "src"))
    FlushCoordinator(ms, store).flush_shard("ds", 0)
    return ms, store


class TestRepairJobs:
    def test_copy_chunks_and_partkeys(self, tmp_path):
        _, src = flushed_store(tmp_path)
        dst = LocalColumnStore(str(tmp_path / "dst"))
        n_chunks = copy_chunks(src, dst, "ds", [0])
        n_keys = copy_partkeys(src, dst, "ds", [0])
        assert n_chunks == len(list(src.read_chunks("ds", 0)))
        assert n_keys == 6
        # recovered memstore from the copy answers queries
        ms2 = TimeSeriesMemStore()
        ms2.setup(Dataset("ds"), [0])
        recover_shard(ms2, dst, "ds", 0)
        assert ms2.shard("ds", 0).num_partitions == 6

    def test_copy_chunks_time_filtered(self, tmp_path):
        _, src = flushed_store(tmp_path)
        dst = LocalColumnStore(str(tmp_path / "dst2"))
        n = copy_chunks(src, dst, "ds", [0], start_ms=BASE + 150 * 10_000)
        assert 0 < n < len(list(src.read_chunks("ds", 0)))

    def test_bust_cardinality(self, tmp_path):
        _, store = flushed_store(tmp_path)
        deleted = bust_cardinality(store, "ds", [0], [regex("instance", "host-[0-2]")])
        assert deleted == 3
        remaining = {rec["tags"]["instance"] for rec in store.read_partkeys("ds", 0)}
        assert remaining == {"host-3", "host-4", "host-5"}
        for header, _, _ in store.read_chunks("ds", 0):
            assert header["tags"]["instance"] in remaining


class TestQueryLimits:
    def test_series_limit(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=20, n_samples=50, start_ms=BASE))
        planner = SingleClusterPlanner(ms, "ds")
        plan = query_range_to_logical_plan(
            "heap_usage0", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        ep = planner.materialize(plan)
        ctx = QueryContext(ms, "ds", max_series=5)
        with pytest.raises(QueryError, match="series"):
            ep.execute(ctx)

    def test_sample_limit(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=10, n_samples=100, start_ms=BASE))
        planner = SingleClusterPlanner(ms, "ds")
        plan = query_range_to_logical_plan(
            "heap_usage0", (BASE + 600_000) / 1000, (BASE + 900_000) / 1000, 60)
        ep = planner.materialize(plan)
        ctx = QueryContext(ms, "ds", max_samples=100)
        with pytest.raises(QueryError, match="samples"):
            ep.execute(ctx)

    def test_under_limit_ok(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=3, n_samples=50, start_ms=BASE))
        engine = QueryEngine(ms, "ds")
        res = engine.query_range("heap_usage0", (BASE + 300_000) / 1000, (BASE + 400_000) / 1000, 60)
        assert sum(g.n_series for g in res.grids) == 3
        assert res.stats.series_scanned == 3
        assert res.stats.samples_scanned > 0


def test_query_deadline_enforced():
    from filodb_tpu.coordinator.planner import PlannerParams

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=3, n_samples=50, start_ms=BASE))
    engine = QueryEngine(ms, "ds", PlannerParams(deadline_s=0.0))
    with pytest.raises(QueryError, match="deadline"):
        engine.query_range("heap_usage0", (BASE + 300_000) / 1000, (BASE + 400_000) / 1000, 60)


def test_stage_cache_byte_budget():
    ms = TimeSeriesMemStore(StoreConfig(stage_cache_bytes=1))  # evict always
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=3, n_samples=50, start_ms=BASE))
    engine = QueryEngine(ms, "ds")
    for k in range(4):
        engine.query_range("heap_usage0", (BASE + 300_000 + k * 60_000) / 1000,
                           (BASE + 400_000 + k * 60_000) / 1000, 60)
    sh = ms.shard("ds", 0)
    assert len(sh.stage_cache) <= 1  # budget admits at most the newest block

    ms2 = TimeSeriesMemStore(StoreConfig())  # default budget keeps blocks
    ms2.setup(Dataset("ds"), [0])
    ms2.ingest("ds", 0, machine_metrics(n_series=3, n_samples=50, start_ms=BASE))
    engine2 = QueryEngine(ms2, "ds")
    for k in range(3):
        engine2.query_range("heap_usage0", (BASE + 300_000 + k * 60_000) / 1000,
                            (BASE + 400_000 + k * 60_000) / 1000, 60)
    assert len(ms2.shard("ds", 0).stage_cache) == 3
