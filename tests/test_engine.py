"""End-to-end query engine tests: ingest synthetic data, run PromQL, verify
against the oracle (model: reference MultiSchemaPartitionsExecSpec,
AggrOverRangeVectorsSpec, BinaryJoinExecSpec, and the jmh
QueryInMemoryBenchmark workload shape: 8 shards, sum(rate(heap_usage...)))."""

import numpy as np
import pytest

import oracle
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import counter_batch, histogram_batch, machine_metrics

BASE = 1_600_000_000_000
N_SAMPLES = 360  # 1h at 10s
START_S = (BASE + 1_800_000) / 1000  # 30min in
END_S = (BASE + 3_400_000) / 1000
STEP_S = 60.0


@pytest.fixture(scope="module")
def engine():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", machine_metrics(n_series=50, n_samples=N_SAMPLES, start_ms=BASE), spread=3)
    ms.ingest_routed("prometheus", counter_batch(n_series=50, n_samples=N_SAMPLES, start_ms=BASE), spread=3)
    ms.ingest_routed("prometheus", histogram_batch(n_series=10, n_samples=N_SAMPLES, start_ms=BASE), spread=3)
    return QueryEngine(ms, "prometheus")


def series_map(res):
    out = {}
    for lbls, ts, vals in res.all_series():
        key = tuple(sorted((k, v) for k, v in lbls.items()))
        out[key] = (ts, vals)
    return out


class TestGaugeQueries:
    def test_instant_vector_lookback(self, engine):
        res = engine.query_range("heap_usage0", START_S, END_S, STEP_S)
        assert len(res.grids) >= 1
        total = sum(g.n_series for g in res.grids)
        assert total == 50
        # each step should have the latest sample within 5m lookback
        sm = series_map(res)
        assert len(sm) == 50
        for _, (ts, vals) in list(sm.items())[:3]:
            assert len(ts) == int((END_S - START_S) // STEP_S) + 1

    def test_sum_over_time_vs_oracle(self, engine):
        res = engine.query_range("sum_over_time(heap_usage0[5m])", START_S, END_S, STEP_S)
        sm = series_map(res)
        assert len(sm) == 50
        # oracle for one specific series
        batch = machine_metrics(n_series=50, n_samples=N_SAMPLES, start_ms=BASE)
        by_series = {tuple(sorted(g.tags.items())): g for g in batch.group_by_series()}
        nsteps = int((END_S - START_S) // STEP_S) + 1
        for key, (ts, vals) in list(sm.items())[:5]:
            src = by_series[tuple(sorted(dict(key, _metric_="heap_usage0").items()))]
            want = oracle.range_function(
                "sum_over_time", src.timestamps, src.values["value"],
                int(START_S * 1000), int(STEP_S * 1000), nsteps, 300_000)
            want = want[~np.isnan(want)]
            np.testing.assert_allclose(vals, want, rtol=1e-4)

    def test_avg_and_max_aggregate(self, engine):
        res = engine.query_range("avg(heap_usage0)", START_S, END_S, STEP_S)
        assert sum(g.n_series for g in res.grids) == 1
        res2 = engine.query_range("max by (instance) (heap_usage0)", START_S, END_S, STEP_S)
        assert sum(g.n_series for g in res2.grids) == 50


class TestCounterQueries:
    def test_sum_rate_vs_oracle(self, engine):
        """The north-star query shape: distributed sum(rate(...))."""
        res = engine.query_range("sum(rate(http_requests_total[5m]))", START_S, END_S, STEP_S)
        sm = series_map(res)
        assert len(sm) == 1
        (_, (ts, got)) = next(iter(sm.items()))
        # oracle: rate per series, then sum at each step
        batch = counter_batch(n_series=50, n_samples=N_SAMPLES, start_ms=BASE)
        nsteps = int((END_S - START_S) // STEP_S) + 1
        acc = np.zeros(nsteps)
        for g in batch.group_by_series():
            r = oracle.range_function(
                "rate", g.timestamps, g.values["count"],
                int(START_S * 1000), int(STEP_S * 1000), nsteps, 300_000, is_counter=True)
            acc += np.where(np.isnan(r), 0, r)
        np.testing.assert_allclose(got, acc, rtol=1e-3)

    def test_plain_counter_selector_returns_raw_samples(self, engine):
        """Advisor round-1 high finding: a plain selector over a counter must
        return RAW sample values — no reset correction, no baseline shift."""
        res = engine.query_range("http_requests_total", START_S, END_S, STEP_S)
        sm = series_map(res)
        assert len(sm) == 50
        batch = counter_batch(n_series=50, n_samples=N_SAMPLES, start_ms=BASE)
        by_series = {tuple(sorted(g.tags.items())): g for g in batch.group_by_series()}
        for key, (ts, vals) in list(sm.items())[:5]:
            src = by_series[key]
            for t, v in zip(ts[:10], vals[:10]):
                idx = np.searchsorted(src.timestamps, t, side="right") - 1
                assert idx >= 0 and t - src.timestamps[idx] <= 300_000
                np.testing.assert_allclose(v, src.values["count"][idx], rtol=1e-5)

    def test_resets_and_changes_see_raw_counter(self):
        """resets()/changes() must count real counter resets (they were
        computed over corrected values before, always yielding 0 resets)."""
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(2))
        ms.ingest_routed(
            "prometheus",
            counter_batch(n_series=4, n_samples=N_SAMPLES, start_ms=BASE, resets=True),
            spread=1,
        )
        eng = QueryEngine(ms, "prometheus")
        res = eng.query_range(
            "sum(resets(http_requests_total[30m]))", START_S, END_S, STEP_S)
        (_, vals) = next(iter(series_map(res).values()))
        assert np.nanmax(vals) >= 1.0, "resets() must see raw counter resets"
        # oracle cross-check on changes() for one series
        batch = counter_batch(n_series=4, n_samples=N_SAMPLES, start_ms=BASE, resets=True)
        g0 = next(iter(batch.group_by_series()))
        sel = '{instance="%s"}' % g0.tags["instance"]
        res2 = eng.query_range(
            f"changes(http_requests_total{sel}[30m])", START_S, END_S, STEP_S)
        (_, got) = next(iter(series_map(res2).values()))
        nsteps = int((END_S - START_S) // STEP_S) + 1
        want = oracle.range_function(
            "changes", g0.timestamps, g0.values["count"],
            int(START_S * 1000), int(STEP_S * 1000), nsteps, 1_800_000)
        np.testing.assert_allclose(got, want[~np.isnan(want)])

    def test_rate_by_instance(self, engine):
        res = engine.query_range(
            'sum by (instance) (rate(http_requests_total[5m]))', START_S, END_S, STEP_S)
        assert sum(g.n_series for g in res.grids) == 50
        for g in res.grids:
            for l in g.labels:
                assert set(l.keys()) == {"instance"}

    def test_topk(self, engine):
        res = engine.query_range("topk(3, rate(http_requests_total[5m]))", START_S, END_S, STEP_S)
        total = sum(g.n_series for g in res.grids)
        assert total >= 3  # union of per-step top-3 series
        v = res.grids[0].values_np()
        sel_per_step = (~np.isnan(v)).sum(axis=0)
        assert (sel_per_step[1:-1] == 3).all()

    def test_increase_and_irate_run(self, engine):
        for q in ["increase(http_requests_total[5m])", "irate(http_requests_total[5m])"]:
            res = engine.query_range(q, START_S, END_S, STEP_S)
            assert sum(g.n_series for g in res.grids) == 50


class TestBinaryAndScalar:
    def test_scalar_multiply(self, engine):
        r1 = engine.query_range("heap_usage0", START_S, END_S, STEP_S)
        r2 = engine.query_range("heap_usage0 * 2", START_S, END_S, STEP_S)
        m1, m2 = series_map(r1), series_map(r2)
        k1 = next(iter(m1))
        # labels lose the metric name under arithmetic
        k2 = tuple((k, v) for k, v in k1 if k != "_metric_")
        np.testing.assert_allclose(m2[k2][1], m1[k1][1] * 2, rtol=1e-6)

    def test_comparison_filters(self, engine):
        res = engine.query_range("heap_usage0 > 1000", START_S, END_S, STEP_S)
        assert not list(res.all_series())  # values ~50, none above 1000

    def test_comparison_bool(self, engine):
        res = engine.query_range("heap_usage0 > bool 1000", START_S, END_S, STEP_S)
        for _, _, vals in res.all_series():
            assert (vals == 0).all()

    def test_vector_vector_join(self, engine):
        res = engine.query_range(
            "rate(http_requests_total[5m]) / rate(http_requests_total[5m])", START_S, END_S, STEP_S)
        for _, _, vals in res.all_series():
            np.testing.assert_allclose(vals, 1.0, rtol=1e-5)

    def test_set_and(self, engine):
        # full-key matching would be empty (job differs); match on instance
        res = engine.query_range(
            "heap_usage0 and on (instance) http_requests_total", START_S, END_S, STEP_S)
        assert sum(g.n_series for g in res.grids) == 50

    def test_unless(self, engine):
        res = engine.query_range(
            "heap_usage0 unless on (instance) http_requests_total", START_S, END_S, STEP_S)
        assert not list(res.all_series())

    def test_or_keeps_both_sides(self, engine):
        res = engine.query_range("heap_usage0 or http_requests_total", START_S, END_S, STEP_S)
        assert sum(g.n_series for g in res.grids) == 100


class TestHistogramQueries:
    def test_histogram_quantile(self, engine):
        res = engine.query_range(
            "histogram_quantile(0.9, rate(http_request_latency[5m]))", START_S, END_S, STEP_S)
        sm = series_map(res)
        assert len(sm) == 10
        for _, (_, vals) in sm.items():
            assert (vals > 0).all()
            assert np.isfinite(vals).all()

    def test_quantile_monotone_in_q(self, engine):
        r50 = engine.query_range("histogram_quantile(0.5, rate(http_request_latency[5m]))", START_S, END_S, STEP_S)
        r99 = engine.query_range("histogram_quantile(0.99, rate(http_request_latency[5m]))", START_S, END_S, STEP_S)
        m50, m99 = series_map(r50), series_map(r99)
        for k in m50:
            assert (m99[k][1] >= m50[k][1] - 1e-6).all()

    def test_hist_sum_aggregate(self, engine):
        """sum(rate(native_hist)) must aggregate per bucket, then quantile."""
        res = engine.query_range(
            "histogram_quantile(0.9, sum(rate(http_request_latency[5m])))", START_S, END_S, STEP_S)
        series = list(res.all_series())
        assert len(series) == 1
        _, _, vals = series[0]
        assert np.isfinite(vals).all() and (vals > 0).all()


class TestMiscFunctions:
    def test_abs_and_clamp(self, engine):
        res = engine.query_range("clamp(heap_usage0, 0, 10)", START_S, END_S, STEP_S)
        for _, _, vals in res.all_series():
            assert (vals <= 10).all() and (vals >= 0).all()

    def test_absent_on_missing_metric(self, engine):
        res = engine.query_range('absent(nonexistent_metric{job="x"})', START_S, END_S, STEP_S)
        series = list(res.all_series())
        assert len(series) == 1
        lbls, ts, vals = series[0]
        assert (vals == 1.0).all()
        assert lbls.get("job") == "x"

    def test_label_replace(self, engine):
        res = engine.query_range(
            'label_replace(heap_usage0, "host_short", "$1", "instance", "host-(.*)")',
            START_S, END_S, STEP_S)
        for lbls, _, _ in res.all_series():
            assert "host_short" in lbls

    def test_subquery_max_over_time(self, engine):
        res = engine.query_range(
            "max_over_time(rate(http_requests_total[5m])[10m:1m])", START_S, END_S, STEP_S)
        assert sum(g.n_series for g in res.grids) == 50

    def test_scalar_function(self, engine):
        res = engine.query_range("scalar(sum(heap_usage0))", START_S, END_S, STEP_S)
        assert res.scalar is not None
        assert np.isfinite(res.scalar.values).all()

    def test_vector_of_scalar(self, engine):
        res = engine.query_range("vector(42)", START_S, END_S, STEP_S)
        series = list(res.all_series())
        assert len(series) == 1 and (series[0][2] == 42).all()

    def test_time_arithmetic(self, engine):
        res = engine.query_range("time() * 0 + 5", START_S, END_S, STEP_S)
        assert res.scalar is not None
        np.testing.assert_allclose(res.scalar.values, 5.0)


class TestMetadata:
    def test_label_values(self, engine):
        vals = engine.memstore.label_values("prometheus", [], "_metric_", 0, 2**62)
        assert "heap_usage0" in vals and "http_requests_total" in vals

    def test_raw_export(self, engine):
        res = engine.query_range("heap_usage0[5m]", END_S, END_S, 1)
        assert res.raw is not None and len(res.raw) == 50


class TestVectorComparisons:
    def test_vector_vector_bool(self, engine):
        res = engine.query_range(
            "heap_usage0 >= bool on (instance) http_requests_total",
            START_S, END_S, STEP_S)
        series = list(res.all_series())
        assert len(series) == 50
        for _, _, vals in series:
            assert set(np.unique(vals)).issubset({0.0, 1.0})

    def test_vector_vector_filter_comparison(self, engine):
        # gauge (~50) < counter (thousands by START_S): every step passes the
        # filter, and surviving values must be the LHS gauge values
        res = engine.query_range(
            "heap_usage0 < on (instance) http_requests_total", START_S, END_S, STEP_S)
        series = list(res.all_series())
        assert len(series) == 50
        gauge = series_map(engine.query_range("heap_usage0", START_S, END_S, STEP_S))
        for lbls, _, vals in series:
            key = next(k for k in gauge if dict(k)["instance"] == lbls["instance"])
            np.testing.assert_allclose(vals, gauge[key][1], rtol=1e-5)

    def test_arithmetic_on_aggregates(self, engine):
        res = engine.query_range(
            "sum(rate(http_requests_total[5m])) / count(rate(http_requests_total[5m]))",
            START_S, END_S, STEP_S)
        want = engine.query_range(
            "avg(rate(http_requests_total[5m]))", START_S, END_S, STEP_S)
        got_v = list(res.all_series())[0][2]
        want_v = list(want.all_series())[0][2]
        np.testing.assert_allclose(got_v, want_v, rtol=1e-4)
