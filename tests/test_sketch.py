"""Log-linear quantile sketch tests: accuracy bound, mergeability, and the
mesh-distributed quantile(q, rate(...)) vs exact quantiles."""

import numpy as np
import pytest

import jax

from filodb_tpu.ops import kernels as K
from filodb_tpu.ops import sketch as SK
from filodb_tpu.ops.staging import stage_series
from filodb_tpu.parallel import mesh as M

BASE = 1_600_000_000_000
REL = 2 ** (1 / SK.SUB) - 1  # log-linear error bound per half-bin


class TestSketchBasics:
    @pytest.mark.parametrize("seed", range(3))
    def test_quantile_accuracy(self, seed):
        rng = np.random.default_rng(seed)
        vals = np.exp(rng.uniform(-5, 5, (200, 4))).astype(np.float32)
        vals[rng.random(vals.shape) < 0.1] = np.nan
        gids = (np.arange(200) % 3).astype(np.int32)
        sk = np.asarray(SK.build_sketch(vals, gids, 3))
        for q in (0.1, 0.5, 0.9):
            got = SK.sketch_quantile(sk, q)
            for g in range(3):
                for j in range(4):
                    col = np.sort(vals[gids == g][:, j].astype(np.float64))
                    col = col[~np.isnan(col)]
                    # rank-based bound: sketches use "first bin with
                    # cum >= q*n" — the result must sit within the bin error
                    # of a nearby order statistic (rank conventions differ
                    # from np.quantile's interpolation at small n)
                    k = int(np.ceil(q * len(col)))
                    lo = col[max(k - 2, 0)] * (1 - 0.05)
                    hi = col[min(k + 1, len(col) - 1)] * (1 + 0.05)
                    assert lo <= got[g, j] <= hi, (q, g, j, got[g, j], lo, hi)

    def test_negative_and_zero_values(self):
        vals = np.array([[-10.0, -1.0, 0.0, 1.0, 10.0]] * 4, dtype=np.float32).T
        gids = np.zeros(5, dtype=np.int32)
        sk = np.asarray(SK.build_sketch(vals, gids, 1))
        med = SK.sketch_quantile(sk, 0.5)
        np.testing.assert_allclose(med, 0.0, atol=1e-6)
        lo = SK.sketch_quantile(sk, 0.0)
        assert (lo < -9).all()

    def test_merge_is_addition(self):
        rng = np.random.default_rng(7)
        a = np.exp(rng.uniform(0, 4, (100, 2))).astype(np.float32)
        b = np.exp(rng.uniform(0, 4, (100, 2))).astype(np.float32)
        gids = np.zeros(100, dtype=np.int32)
        ska = np.asarray(SK.build_sketch(a, gids, 1))
        skb = np.asarray(SK.build_sketch(b, gids, 1))
        both = np.asarray(SK.build_sketch(np.concatenate([a, b]), np.zeros(200, np.int32), 1))
        np.testing.assert_array_equal(ska + skb, both)

    def test_empty_group_nan(self):
        vals = np.full((10, 3), np.nan, dtype=np.float32)
        sk = np.asarray(SK.build_sketch(vals, np.zeros(10, np.int32), 2))
        q = SK.sketch_quantile(sk, 0.5)
        assert np.isnan(q).all()


class TestDistributedQuantile:
    def test_mesh_quantile_rate(self):
        mesh = M.make_mesh()
        rng = np.random.default_rng(0)
        blocks, gids, all_series = [], [], []
        for s in range(8):
            series = []
            for i in range(4):
                ts = BASE + np.cumsum(rng.integers(8_000, 12_000, 200)).astype(np.int64)
                vals = np.cumsum(rng.uniform(0, 10, 200)) + 1e9
                series.append((ts, vals))
                all_series.append((ts, vals, i % 2))
            blocks.append(stage_series(series, BASE, counter_corrected=True))
            gids.append((np.arange(4) % 2).astype(np.int32))
        arrays = M.stack_blocks_for_mesh(blocks, gids, 8)
        sharded = M.shard_arrays(mesh, *arrays)
        num_steps = K.pad_steps(10)
        start = BASE + 400_000
        sk = np.asarray(SK.distributed_sketch_quantile(
            mesh, "rate", *sharded,
            np.int32(start - BASE), np.int32(60_000), np.int32(300_000),
            num_steps, 2, is_counter=True,
        ))
        got = SK.sketch_quantile(sk, 0.5)[:, :10]
        # exact oracle quantiles
        import oracle

        rates = {0: [], 1: []}
        for ts, vals, g in all_series:
            r = oracle.range_function("rate", ts, vals, start, 60_000, 10, 300_000,
                                      is_counter=True)
            rates[g].append(r)
        for g in (0, 1):
            rows = np.stack(rates[g])
            want = np.nanquantile(rows, 0.5, axis=0)
            err = np.abs(got[g] - want) / np.maximum(np.abs(want), 1e-9)
            assert (err < 0.08).all(), (g, got[g], want)
