"""Log-linear sketch property tests (ops/sketch.py; doc/perf.md "Sketch
rollup tier").

The rollup tier's quantile guarantee rests on two properties, both
verified here against numpy oracles rather than golden values:

- **bin bound**: every finite value bins to a center within relative
  error ``2^(1/SUB) - 1`` (SUB=32 -> ~2.2%), with negatives mirrored,
  NaN excluded, and sub-``2^-24`` magnitudes collapsed to the exact-zero
  bin;
- **mergeability**: sketches merge by ADDITION, so the psum merge across
  a device mesh must read off bit-identically to the single-device
  host-order sum over the same inputs.
"""

from __future__ import annotations

import numpy as np
import pytest

from filodb_tpu.config import force_virtual_devices

force_virtual_devices(8)

import filodb_tpu.ops.sketch as SK  # noqa: E402

pytestmark = pytest.mark.rollup

BOUND = 2.0 ** (1.0 / SK.SUB) - 1.0  # the documented relative error bound
TINY = 2.0 ** SK.E_MIN  # magnitudes below this collapse to the zero bin


MAX_MAG = 2.0 ** (SK.E_MIN + SK.HALF / SK.SUB - 1)  # top representable octave


def _mixed_values(rng, n):
    """Adversarial value mix: lognormal positives across many octaves
    (clamped into the sketch's representable magnitude range — beyond it
    values saturate to the top bin by design), mirrored negatives, exact
    zeros, subnormal-scale magnitudes, and a clump of identical values
    (rank ties)."""
    mag = np.minimum(np.exp(rng.normal(0, 8, 2 * n)), MAX_MAG)
    v = np.concatenate([
        mag[:n],
        -mag[n:],
        np.zeros(n // 4),
        rng.uniform(-1, 1, n // 4) * TINY / 2,  # subnormal collapse
        np.full(n // 4, 42.0),
    ])
    rng.shuffle(v)
    return v


def test_bin_roundtrip_within_bound():
    rng = np.random.default_rng(0)
    v = _mixed_values(rng, 4000)
    bins = SK.bin_of_np(v)
    centers = SK.bin_centers()
    assert bins.min() >= 0 and bins.max() < SK.B
    small = np.abs(v) < TINY
    assert np.all(bins[small] == SK.ZERO_BIN)
    assert np.all(centers[bins[small]] == 0.0)
    big = ~small
    est = centers[bins[big]]
    assert np.all(np.sign(est) == np.sign(v[big]))
    rel = np.abs(est - v[big]) / np.abs(v[big])
    assert rel.max() <= BOUND + 1e-12, rel.max()


def test_bin_of_np_nan_and_device_parity():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    v = _mixed_values(rng, 1000)
    v[::97] = np.nan
    host = SK.bin_of_np(v)
    dev = np.asarray(SK._bin_of(jnp.asarray(v)))
    assert np.all(host[np.isnan(v)] == -1)
    assert np.array_equal(host, dev.astype(np.int64))


def _host_sketch(values_2d):
    """[G, W] samples -> [G, B] counts via the host binning path."""
    G = values_2d.shape[0]
    counts = np.zeros((G, SK.B), np.float64)
    bins = SK.bin_of_np(values_2d)
    for g in range(G):
        b = bins[g][bins[g] >= 0]
        np.add.at(counts[g], b, 1.0)
    return counts


@pytest.mark.parametrize("q", [0.0, 0.1, 0.5, 0.9, 0.99, 1.0])
def test_sketch_quantile_vs_numpy_oracle(q):
    """Read-off quantile lands within the bin bound of the sample-rank
    bracket (numpy ``lower``/``higher`` methods) — negatives, zeros and
    subnormal-collapsed values included. The bracket absorbs the one-rank
    ambiguity between interpolation conventions; the multiplicative bound
    is the sketch's, plus a tiny absolute epsilon for the zero bin."""
    rng = np.random.default_rng(int(q * 100) + 2)
    G, W = 16, 257
    vals = _mixed_values(rng, (G * W) // 2 + G)[: G * W].reshape(G, W)
    counts = _host_sketch(vals)
    est = SK.sketch_quantile(counts[:, None, :], q)[:, 0]
    lo = np.quantile(vals, q, axis=1, method="lower")
    hi = np.quantile(vals, q, axis=1, method="higher")
    lo_b = np.minimum(lo * (1 - BOUND), lo * (1 + BOUND)) - TINY
    hi_b = np.maximum(hi * (1 - BOUND), hi * (1 + BOUND)) + TINY
    assert np.all(est >= lo_b - 1e-12), (est - lo_b).min()
    assert np.all(est <= hi_b + 1e-12), (hi_b - est).min()


def test_rollup_sketch_quantile_windows_match_host():
    """The device windowed read-off (cumsum-gather over periods, compacted
    bin axis) equals the host merge+read-off over the same periods."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    S, P = 6, 20
    win_p, step_p, J = 4, 2, 8
    vals = [
        [_mixed_values(rng, 16)[:23] for _ in range(P)] for _ in range(S)
    ]
    counts = np.zeros((S, P, SK.B), np.float32)
    for s in range(S):
        for p in range(P):
            b = SK.bin_of_np(vals[s][p])
            np.add.at(counts[s, p], b[b >= 0], 1.0)
    pop = np.nonzero(counts.sum((0, 1)) > 0)[0]
    lo_bin, hi_bin = int(pop.min()), int(pop.max()) + 1
    compact = counts[:, :, lo_bin:hi_bin]
    centers = SK.bin_centers()[lo_bin:hi_bin]
    starts = np.arange(J, dtype=np.int32) * step_p
    dev = np.asarray(SK.rollup_sketch_quantile(
        jnp.asarray(compact), jnp.asarray(centers, jnp.float32),
        jnp.asarray(starts), 0.9, win_p,
    ))
    merged = np.stack(
        [compact[:, s0:s0 + win_p].sum(1) for s0 in starts], axis=1
    )  # [S, J, Bc]
    host = np.where(
        merged.sum(-1) > 0,
        centers[np.minimum(
            (np.cumsum(merged, -1)
             < 0.9 * merged.sum(-1, keepdims=True)).sum(-1),
            len(centers) - 1,
        )],
        np.nan,
    )
    assert np.array_equal(dev, host.astype(np.float32), equal_nan=True)


def test_psum_merge_equals_host_add():
    """rollup_agg_sketch_quantile under the forced 8-device CPU mesh ==
    the same program with mesh=None: sketch counts are small integers in
    f32, so psum order cannot lose precision and the read-off must be
    BIT-identical."""
    import jax.numpy as jnp

    from filodb_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    if mesh is None or mesh.devices.size != 8:
        pytest.skip("8-device virtual mesh unavailable")
    rng = np.random.default_rng(4)
    S, Pw, J = 16, 11, 4  # [S, Pw+1]-shaped inputs, win_p=2, step_p=2
    win_p, step_p = 2, 2
    cols = 1 + (J - 1) * step_p + win_p
    assert cols <= Pw + 1
    sm = rng.uniform(-100, 100, (S, Pw + 1))
    cnt = rng.integers(0, 7, (S, Pw + 1)).astype(np.float64)
    mn = sm / np.maximum(cnt, 1) - rng.uniform(0, 5, (S, Pw + 1))
    mx = sm / np.maximum(cnt, 1) + rng.uniform(0, 5, (S, Pw + 1))
    clast = np.cumsum(rng.uniform(0, 10, (S, Pw + 1)), axis=1)
    gids = rng.integers(0, 3, S).astype(np.int32)
    args = [jnp.asarray(a, jnp.float32) for a in (mn, mx, sm, cnt, clast)]
    out_host = np.asarray(SK.rollup_agg_sketch_quantile(
        "avg_over_time", *args, jnp.asarray(gids), 0.9, 3,
        win_p, step_p, float(win_p * 60), mesh=None,
    ))
    out_mesh = np.asarray(SK.rollup_agg_sketch_quantile(
        "avg_over_time", *args, jnp.asarray(gids), 0.9, 3,
        win_p, step_p, float(win_p * 60), mesh=mesh,
    ))
    assert np.array_equal(out_host, out_mesh, equal_nan=True)
