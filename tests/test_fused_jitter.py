"""Jitter-tolerant fused kernels (doc/perf.md "Jitter-tolerant fused path").

Real scrape traffic jitters and drops samples. The fused superblock engine
must keep the single-dispatch guarantee for near-regular (jitter) and holey
(masked) grids: superblock concatenation re-detects the grid class
(staging.detect_shared_grid / _build_masked_grid), the dispatch ladder
(ops/aggregations._grid_variant) selects the jitter/masked kernel variants,
and the mesh twins run the same programs under shard_map. Parity contract:
fused == reference tree across the epilogue families, NaN masks identical,
values within float32 accumulation-order tolerance.

Runs on the conftest-forced 8-device virtual CPU mesh (make test-jitter).
"""

import numpy as np
import pytest

import jax

from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
from filodb_tpu.core.histograms import PROM_DEFAULT
from filodb_tpu.core.records import RecordBatch, SeriesBatch
from filodb_tpu.core.schemas import (
    Dataset,
    METRIC_TAG,
    PROM_COUNTER,
    PROM_HISTOGRAM,
    shard_for,
)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.mesh import make_mesh
from filodb_tpu.testkit import kernel_dispatch_total

pytestmark = [pytest.mark.perf, pytest.mark.fused_jitter]

BASE = 1_600_000_000_000
INTERVAL = 10_000
N_SHARDS = 8
N_SAMPLES = 240
START = (BASE + 600_000) / 1000
END = START + 900
STEP = 60


def _ingest_counters(ms, dataset, metric, n_series, jitter=0.05,
                     hole_frac=0.0, seed=7, n_samples=N_SAMPLES,
                     num_shards=N_SHARDS):
    rng = np.random.default_rng(seed)
    # half-interval phase shift: staging ranges are 5m-aligned and 10s
    # divides 5m, so an unshifted grid puts a slot exactly ON the range
    # boundary — jitter then clips that slot for SOME series and the
    # superblock legitimately classifies as "holes". The shift keeps these
    # fixtures deterministically in the intended grid class (jitter).
    nominal = (BASE + INTERVAL // 2
               + (1 + np.arange(n_samples, dtype=np.int64)) * INTERVAL)
    for i in range(n_series):
        tags = {METRIC_TAG: metric, "_ws_": "w", "_ns_": "n",
                "instance": f"h{i}", "job": f"j{i % 4}"}
        shard = shard_for(tags, spread=3, num_shards=num_shards)
        dev = np.rint(
            rng.uniform(-jitter, jitter, n_samples) * INTERVAL
        ).astype(np.int64) if jitter > 0 else 0
        ts = nominal + dev
        vals = np.cumsum(rng.uniform(0, 10, n_samples)) + 1e9
        keep = np.ones(n_samples, bool)
        if hole_frac > 0:
            # endpoints kept (deterministic grid anchor), different
            # interior slots dropped per series
            drop = rng.choice(np.arange(1, n_samples - 1),
                              max(1, int(hole_frac * n_samples)),
                              replace=False)
            keep[drop] = False
        ms.shard(dataset, shard).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts[keep], {"count": vals[keep]})
        )


def _ingest_jittered_hists(ms, dataset, metric, n_series, seed=11):
    rng = np.random.default_rng(seed)
    les = PROM_DEFAULT.bounds()
    B = len(les)
    nominal = (BASE + INTERVAL // 2
               + (1 + np.arange(N_SAMPLES, dtype=np.int64)) * INTERVAL)
    for i in range(n_series):
        tags = {METRIC_TAG: metric, "_ws_": "w", "_ns_": "n",
                "instance": f"h{i}"}
        shard = shard_for(tags, spread=3, num_shards=N_SHARDS)
        dev = np.rint(
            rng.uniform(-0.05, 0.05, N_SAMPLES) * INTERVAL
        ).astype(np.int64)
        incr = rng.poisson(2.0, size=(N_SAMPLES, B)).astype(np.float64)
        incr[:, -1] = incr.sum(1)
        hist = np.cumsum(np.cumsum(incr, axis=1), axis=0)
        ms.shard(dataset, shard).ingest_series(SeriesBatch(
            PROM_HISTOGRAM, tags, nominal + dev,
            {"sum": np.cumsum(rng.uniform(0, 5, N_SAMPLES)),
             "count": hist[:, -1], "h": hist},
            bucket_les=les,
        ))


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("ds"), list(range(N_SHARDS)))
    _ingest_counters(ms, "ds", "rq_reg", 48, jitter=0.0, seed=3)
    _ingest_counters(ms, "ds", "rq_jit", 48, jitter=0.05, seed=5)
    _ingest_counters(ms, "ds", "rq_holes", 48, jitter=0.05, hole_frac=0.01,
                     seed=9)
    _ingest_jittered_hists(ms, "ds", "lat_jit", 24)
    return ms


@pytest.fixture(scope="module")
def engines(store):
    fused = QueryEngine(store, "ds")
    sharded = QueryEngine(store, "ds", PlannerParams(mesh=make_mesh()))
    ref = QueryEngine(store, "ds", PlannerParams(fused_aggregate=False))
    return fused, sharded, ref


def _rows(res):
    out = {}
    for g in res.grids:
        for i, lbls in enumerate(g.labels):
            h = g.hist_np()
            out[tuple(sorted(lbls.items()))] = (
                np.asarray(g.values_np()[i]),
                None if h is None else np.asarray(h[i]),
            )
    return out


def assert_parity(engines_subset, q, rtol=2e-4, atol=1e-4):
    rows = [_rows(e.query_range(q, START, END, STEP))
            for e in engines_subset]
    a = rows[0]
    for b in rows[1:]:
        assert a.keys() == b.keys(), (q, sorted(a)[:3], sorted(b)[:3])
        for k in a:
            va, ha = a[k]
            vb, hb = b[k]
            na, nb = np.isnan(va), np.isnan(vb)
            assert (na == nb).all(), (q, k, "NaN masks differ")
            np.testing.assert_allclose(
                va[~na], vb[~nb], rtol=rtol, atol=atol, err_msg=f"{q} {k}"
            )
            if ha is not None or hb is not None:
                assert ha is not None and hb is not None, (q, k)
                np.testing.assert_allclose(
                    ha, hb, rtol=rtol, atol=atol, equal_nan=True,
                    err_msg=f"{q} {k} hist",
                )


# -- fused-vs-reference parity on jittered / holey grids ---------------------


OPS = [
    "sum by (job) (rate({m}[5m]))",
    "avg(increase({m}[5m]))",
    "min by (job) (rate({m}[5m]))",
    "max(rate({m}[5m]))",
    "count by (job) (sum_over_time({m}[3m]))",
    "topk(3, rate({m}[5m]))",
    "quantile(0.9, rate({m}[5m]))",
]


@pytest.mark.parametrize("metric", ["rq_jit", "rq_holes"])
@pytest.mark.parametrize("q_tpl", OPS)
def test_fused_parity_jitter_and_holes(engines, metric, q_tpl):
    fused, sharded, ref = engines
    assert_parity((fused, ref), q_tpl.format(m=metric))


@pytest.mark.parametrize("q_tpl", [
    "sum by (job) (rate({m}[5m]))",
    "topk(3, rate({m}[5m]))",
    "quantile(0.9, rate({m}[5m]))",
])
@pytest.mark.parametrize("metric", ["rq_jit", "rq_holes"])
def test_mesh_parity_jitter_and_holes(engines, metric, q_tpl):
    """mesh + jitter no longer drops to the sharded general kernel: the
    shard_map jitter/masked twins must agree with the reference tree."""
    fused, sharded, ref = engines
    assert_parity((sharded, fused, ref), q_tpl.format(m=metric))


def test_hist_quantile_parity_jittered(engines):
    fused, sharded, ref = engines
    q = ("histogram_quantile(0.99, "
         "sum by (le) (rate(lat_jit_bucket[5m])))")
    assert_parity((fused, ref), q)
    assert_parity((sharded, ref), q)


# -- warm single-dispatch guarantee ------------------------------------------


@pytest.mark.parametrize("metric", ["rq_reg", "rq_jit", "rq_holes"])
def test_warm_query_is_single_dispatch(engines, metric):
    fused, _sharded, _ref = engines
    q = f"sum(rate({metric}[5m]))"
    fused.query_range(q, START, END, STEP)  # stage + compile + cache warm
    before = kernel_dispatch_total()
    fused.query_range(q, START, END, STEP)
    assert kernel_dispatch_total() - before == 1, (
        f"warm sum(rate) over a {metric} grid must stay ONE dispatch"
    )


@pytest.mark.parametrize("metric", ["rq_jit", "rq_holes"])
def test_warm_mesh_query_is_single_dispatch(engines, metric):
    """The sharded twin: one dispatch spanning the 8-device mesh even on
    jittered/holey grids (the PR 8 remainder, closed)."""
    _fused, sharded, _ref = engines
    q = f"sum(rate({metric}[5m]))"
    sharded.query_range(q, START, END, STEP)
    before = kernel_dispatch_total()
    sharded.query_range(q, START, END, STEP)
    assert kernel_dispatch_total() - before == 1, (
        f"warm mesh sum(rate) over a {metric} grid must stay ONE dispatch"
    )


def test_warm_jittered_hist_quantile_is_single_dispatch(engines):
    fused, _sharded, _ref = engines
    q = ("histogram_quantile(0.99, "
         "sum by (le) (rate(lat_jit_bucket[5m])))")
    fused.query_range(q, START, END, STEP)
    before = kernel_dispatch_total()
    fused.query_range(q, START, END, STEP)
    assert kernel_dispatch_total() - before == 1


# -- grid classification + degrade taxonomy ----------------------------------


def _fallback_count(reason: str) -> int:
    from filodb_tpu.metrics import REGISTRY

    for line in REGISTRY.expose().splitlines():
        if line.startswith(
            f'filodb_fused_fallback_total{{reason="{reason}"}}'
        ):
            return int(float(line.rsplit(" ", 1)[1]))
    return 0


def test_supported_jitter_query_never_degrades(engines):
    """rate over a jitter5pct grid rides the jitter variant: the
    grid_jitter degrade reason must NOT fire."""
    fused, _sharded, _ref = engines
    before = _fallback_count("grid_jitter")
    fused.query_range("sum(rate(rq_jit[5m]))", START, END, STEP)
    assert _fallback_count("grid_jitter") == before


def test_unsupported_func_on_jitter_grid_counts_grid_jitter(engines):
    """A fused function outside the jitter set (changes) on a jittered
    grid degrades to the general kernel — still fused, still correct —
    and is counted under the grid_jitter taxonomy entry."""
    fused, _sharded, ref = engines
    q = "sum by (job) (changes(rq_jit[5m]))"
    before = _fallback_count("grid_jitter")
    assert_parity((fused, ref), q)
    assert _fallback_count("grid_jitter") > before


def test_superblock_cache_isolates_grid_classes(engines, store):
    """Regular and jittered superblocks coexist as distinct cache entries
    with their own grid classification; a jittered entry never serves a
    regular-grid query (results stay stable across interleaved queries)."""
    fused, _sharded, _ref = engines
    q_reg = "sum(rate(rq_reg[5m]))"
    q_jit = "sum(rate(rq_jit[5m]))"
    first = _rows(fused.query_range(q_reg, START, END, STEP))
    fused.query_range(q_jit, START, END, STEP)
    grids = {e["grid"] for e in store._superblock_cache.snapshot()
             if not e["is_hist"]}
    assert {"regular", "jitter"} <= grids, grids
    again = _rows(fused.query_range(q_reg, START, END, STEP))
    assert first.keys() == again.keys()
    for k in first:
        np.testing.assert_array_equal(first[k][0], again[k][0])


def test_holey_superblock_classified(engines, store):
    fused, _sharded, _ref = engines
    fused.query_range("sum(rate(rq_holes[5m]))", START, END, STEP)
    grids = {e["grid"] for e in store._superblock_cache.snapshot()}
    assert "holes" in grids, grids


# -- extension under ingest on a jittered block ------------------------------


def test_jittered_superblock_extends_under_live_ingest():
    """Live-edge appends with jittered timestamps must EXTEND the cached
    jittered superblock in place (append_to_parts' near-nominal batch
    path) and keep the warm query one dispatch, parity-checked."""
    from filodb_tpu.metrics import REGISTRY

    def maintenance(outcome):
        for line in REGISTRY.expose().splitlines():
            if line.startswith(
                f'filodb_superblock_maintenance_total{{outcome="{outcome}"}}'
            ):
                return int(float(line.rsplit(" ", 1)[1]))
        return 0

    T = N_SAMPLES
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("live"), list(range(4)))
    _ingest_counters(ms, "live", "rq_live", 16, jitter=0.05, seed=21,
                     n_samples=T, num_shards=4)
    eng = QueryEngine(ms, "live")
    ref = QueryEngine(ms, "live", PlannerParams(fused_aggregate=False))
    end = (BASE + (T + 60) * INTERVAL) / 1000  # live edge
    q = "sum(rate(rq_live[5m]))"
    eng.query_range(q, START, end, STEP)
    eng.query_range(q, START, end, STEP)
    rng = np.random.default_rng(33)
    tags = [dict(p.tags) for sh in ms.shards("live")
            for p in sh.partitions.values()]
    # next nominal slot, per-series jitter within the staged bound
    t_new = (BASE + INTERVAL // 2 + (T + 1) * INTERVAL
             + np.rint(rng.uniform(-0.04, 0.04, len(tags)) * INTERVAL
                       ).astype(np.int64))
    ms.ingest_routed("live", RecordBatch(
        PROM_COUNTER, t_new, {"count": np.full(len(tags), 1e12)}, tags,
    ), spread=3)
    ext_before = maintenance("extend")
    before = kernel_dispatch_total()
    r1 = eng.query_range(q, START, end, STEP)
    assert kernel_dispatch_total() - before == 1
    assert maintenance("extend") == ext_before + 1
    r2 = ref.query_range(q, START, end, STEP)
    a = r1.grids[0].values_np()[0]
    c = r2.grids[0].values_np()[0]
    assert (np.isnan(a) == np.isnan(c)).all()
    m = ~np.isnan(c)
    np.testing.assert_allclose(a[m], c[m], rtol=2e-4, atol=1e-4)
    snap = ms._superblock_cache.snapshot()
    assert snap and snap[0]["grid"] == "jitter"
