"""HTTP API tests (model: reference PrometheusApiRouteSpec)."""

import json
import urllib.request
import urllib.parse

import numpy as np
import pytest

from filodb_tpu.api.http import serve_background
from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.testkit import counter_batch, machine_metrics

BASE = 1_600_000_000_000
START_S = (BASE + 1_800_000) / 1000
END_S = (BASE + 3_000_000) / 1000


@pytest.fixture(scope="module")
def api():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus", machine_metrics(n_series=10, n_samples=360, start_ms=BASE), spread=2)
    ms.ingest_routed("prometheus", counter_batch(n_series=10, n_samples=360, start_ms=BASE), spread=2)
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()


def get(url):
    with urllib.request.urlopen(url, timeout=60) as r:
        return json.loads(r.read())


def test_query_range_sum_rate(api):
    q = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
    out = get(f"{api}/api/v1/query_range?query={q}&start={START_S}&end={END_S}&step=60")
    assert out["status"] == "success"
    assert out["data"]["resultType"] == "matrix"
    result = out["data"]["result"]
    assert len(result) == 1
    vals = [float(v) for _, v in result[0]["values"]]
    assert all(v > 0 for v in vals)


def test_query_range_metric_name_restored(api):
    q = urllib.parse.quote("heap_usage0")
    out = get(f"{api}/api/v1/query_range?query={q}&start={START_S}&end={END_S}&step=60")
    assert len(out["data"]["result"]) == 10
    assert out["data"]["result"][0]["metric"]["__name__"] == "heap_usage0"


def test_instant_query_vector(api):
    q = urllib.parse.quote("heap_usage0")
    out = get(f"{api}/api/v1/query?query={q}&time={END_S}")
    assert out["data"]["resultType"] == "vector"
    assert len(out["data"]["result"]) == 10
    for item in out["data"]["result"]:
        t, v = item["value"]
        assert t == END_S
        float(v)


def test_instant_scalar(api):
    out = get(f"{api}/api/v1/query?query=42&time={END_S}")
    assert out["data"]["resultType"] == "scalar"
    assert float(out["data"]["result"][1]) == 42.0


def test_labels(api):
    out = get(f"{api}/api/v1/labels")
    assert "__name__" in out["data"] and "instance" in out["data"]


def test_label_values(api):
    out = get(f"{api}/api/v1/label/__name__/values")
    assert "heap_usage0" in out["data"]
    assert "http_requests_total" in out["data"]


def test_series(api):
    q = urllib.parse.quote('heap_usage0{instance="host-1"}')
    out = get(f"{api}/api/v1/series?match[]={q}")
    assert len(out["data"]) == 1
    assert out["data"][0]["__name__"] == "heap_usage0"


def test_bad_query_is_400(api):
    q = urllib.parse.quote("sum(")
    try:
        get(f"{api}/api/v1/query_range?query={q}&start=1&end=2&step=1")
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
        body = json.loads(e.read())
        assert body["status"] == "error"


def test_health(api):
    out = get(f"{api}/admin/health")
    assert out["status"] == "healthy"


def test_ingest_endpoint(api):
    lines = "\n".join(
        json.dumps({"tags": {"__name__": "pushed_metric", "src": "test"}, "ts_ms": BASE + i * 10_000, "value": float(i)})
        for i in range(10)
    )
    req = urllib.request.Request(f"{api}/ingest", data=lines.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    assert out["data"]["ingested"] == 10
    q = urllib.parse.quote("pushed_metric")
    res = get(f"{api}/api/v1/query?query={q}&time={(BASE + 100_000) / 1000}")
    assert len(res["data"]["result"]) == 1


def test_ingest_prom_text(api):
    text = """# TYPE pushed_counter counter
pushed_counter{src="push"} 100 1600000000000
pushed_counter{src="push"} 110 1600000015000
pushed_gauge 3.5 1600000000000
"""
    req = urllib.request.Request(f"{api}/ingest/prom", data=text.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    assert out["data"]["ingested"] == 3
    q = urllib.parse.quote("pushed_counter")
    res = get(f"{api}/api/v1/query?query={q}&time={1600000100}")
    assert len(res["data"]["result"]) == 1


def test_ingest_influx_http(api):
    lines = "httpm,host=a value=1.5 1600000000000000000\nhttpm,host=b value=2.5 1600000000000000000\n"
    req = urllib.request.Request(f"{api}/ingest/influx", data=lines.encode(), method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    assert out["data"]["ingested"] == 2
    q = urllib.parse.quote("httpm")
    res = get(f"{api}/api/v1/query?query={q}&time={1600000100}")
    assert len(res["data"]["result"]) == 2


class TestPromJsonFormat:
    def test_value_formatting(self):
        from filodb_tpu.api.promjson import _fmt

        assert _fmt(float("nan")) == "NaN"
        assert _fmt(float("inf")) == "+Inf"
        assert _fmt(float("-inf")) == "-Inf"
        assert _fmt(1.5) == "1.5"
        assert _fmt(2.0) == "2.0"

    def test_matrix_rendering_skips_nan_and_restores_name(self):
        from filodb_tpu.api.promjson import render_matrix
        from filodb_tpu.query.rangevector import Grid, QueryResult

        vals = np.array([[1.0, np.nan, 3.0]], dtype=np.float32)
        g = Grid([{"_metric_": "m", "a": "b"}], 1_600_000_000_000, 60_000, 3, vals)
        out = render_matrix(QueryResult(grids=[g]))
        assert out["resultType"] == "matrix"
        series = out["result"][0]
        assert series["metric"] == {"__name__": "m", "a": "b"}
        assert [t for t, _ in series["values"]] == [1_600_000_000.0, 1_600_000_120.0]


def test_label_values_limit_param(api):
    out = get(f"{api}/api/v1/label/instance/values?limit=3")
    assert len(out["data"]) == 3


class TestRenderShapes:
    def test_vector_render_uses_last_nonnan(self):
        from filodb_tpu.api.promjson import render_vector
        from filodb_tpu.query.rangevector import Grid, QueryResult

        vals = np.array([[1.0, 7.0, np.nan]], dtype=np.float32)
        g = Grid([{"_metric_": "m"}], 1_600_000_000_000, 60_000, 3, vals)
        out = render_vector(QueryResult(grids=[g]), 1_600_000_180.0)
        assert out["result"][0]["value"] == [1_600_000_180.0, "7.0"]

    def test_scalar_render(self):
        from filodb_tpu.api.promjson import render_scalar
        from filodb_tpu.query.rangevector import QueryResult, ScalarResult

        res = QueryResult(scalar=ScalarResult(0, 1, 3, np.array([1.0, 2.0, 3.5])))
        out = render_scalar(res, 42.0)
        assert out == {"resultType": "scalar", "result": [42.0, "3.5"]}


def test_duration_step_and_rfc3339_times(api):
    q = urllib.parse.quote("heap_usage0")
    # RFC3339 timestamps (Z form; '+00:00' would need URL-encoding) + "1m" step
    start = "2020-09-13T12:36:40Z"  # 1600000600
    end = "2020-09-13T12:53:20Z"    # 1600001600
    out = get(f"{api}/api/v1/query_range?query={q}&start={start}&end={end}&step=1m")
    assert out["status"] == "success"
    assert len(out["data"]["result"]) == 10
    times = [t for t, _ in out["data"]["result"][0]["values"]]
    assert times[1] - times[0] == 60.0


def test_scalar_arithmetic_instant(api):
    out = get(f"{api}/api/v1/query?query={urllib.parse.quote('2*3+1')}&time=1000")
    assert out["data"]["resultType"] == "scalar"
    assert float(out["data"]["result"][1]) == 7.0


def test_scalar_range_renders_matrix(api):
    out = get(f"{api}/api/v1/query_range?query=5&start=1000&end=1120&step=60")
    res = out["data"]["result"]
    assert out["data"]["resultType"] == "matrix"
    assert len(res) == 1 and len(res[0]["values"]) == 3
    assert all(float(v) == 5.0 for _, v in res[0]["values"])


def test_overload_returns_503():
    """Saturated bounded scheduler -> 503 (reference: query-sched rejection)."""
    import threading
    import urllib.error

    from filodb_tpu.coordinator.planner import PlannerParams
    from filodb_tpu.coordinator.scheduler import QueryScheduler

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0])
    ms.ingest("prometheus", 0, machine_metrics(n_series=2, n_samples=60, start_ms=BASE))
    sched = QueryScheduler(parallelism=1, max_queued=0)
    engine = QueryEngine(ms, "prometheus", PlannerParams(scheduler=sched))
    srv, port = serve_background(engine)
    try:
        release = threading.Event()
        # occupy the single slot directly through the scheduler
        t = threading.Thread(target=lambda: sched.run(lambda: release.wait(10), deadline_s=30))
        t.start()
        import time as _t

        _t.sleep(0.1)
        q = urllib.parse.quote("heap_usage0")
        url = f"http://127.0.0.1:{port}/api/v1/query_range?query={q}&start={START_S}&end={END_S}&step=60"
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(url)
        assert ei.value.code == 503
        release.set()
        t.join()
        # slot free again: the same query now succeeds
        out = get(url)
        assert out["status"] == "success"
    finally:
        srv.shutdown()


def test_metadata_from_schemas(api):
    out = get(f"{api}/api/v1/metadata")
    data = out["data"]
    assert data["heap_usage0"][0]["type"] == "gauge"
    assert data["http_requests_total"][0]["type"] == "counter"


def test_exemplars_roundtrip():
    """OpenMetrics exemplars: ingested alongside samples via /ingest/prom,
    served by /api/v1/query_exemplars (Prometheus response shape)."""
    import urllib.request

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine)
    try:
        body = (
            "# TYPE http_requests_total counter\n"
            'http_requests_total{job="api"} 42 1600000000000 '
            '# {trace_id="abc123"} 0.67 1600000000.0\n'
            'http_requests_total{job="api"} 99 1600000060000\n'
        ).encode()
        req = urllib.request.Request(f"http://127.0.0.1:{port}/ingest/prom", data=body)
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["data"]["ingested"] == 2
        q = urllib.parse.quote('http_requests_total{job="api"}')
        out = get(
            f"http://127.0.0.1:{port}/api/v1/query_exemplars?query={q}"
            f"&start=1599999000&end=1600001000"
        )
        assert out["status"] == "success"
        assert len(out["data"]) == 1
        ex = out["data"][0]["exemplars"][0]
        assert ex["labels"] == {"trace_id": "abc123"}
        assert float(ex["value"]) == 0.67
        assert out["data"][0]["seriesLabels"]["job"] == "api"
    finally:
        srv.shutdown()


def test_bearer_auth_and_gzip():
    """Remote-exec hardening: optional bearer auth (401 without it; health
    stays open) and gzip responses for big payloads."""
    import gzip
    import urllib.error

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0])
    ms.ingest("prometheus", 0, machine_metrics(n_series=30, n_samples=120, start_ms=BASE))
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine, auth_token="s3cret")
    try:
        base_url = f"http://127.0.0.1:{port}"
        # health open, api closed
        assert get(f"{base_url}/admin/health")["status"] == "healthy"
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(f"{base_url}/api/v1/labels")
        assert ei.value.code == 401
        # with token + gzip accepted: compressed matrix response
        q = urllib.parse.quote("heap_usage0")
        req = urllib.request.Request(
            f"{base_url}/api/v1/query_range?query={q}&start={(BASE+400_000)/1000}"
            f"&end={(BASE+1_100_000)/1000}&step=60",
            headers={"Authorization": "Bearer s3cret", "Accept-Encoding": "gzip"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            raw = r.read()
            assert r.headers.get("Content-Encoding") == "gzip"
            out = json.loads(gzip.decompress(raw))
        assert len(out["data"]["result"]) == 30
    finally:
        srv.shutdown()


def test_remote_exec_retries_then_succeeds():
    """PromQlRemoteExec retries transient failures with backoff."""
    from filodb_tpu.coordinator.planners import PromQlRemoteExec

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0])
    ms.ingest("prometheus", 0, machine_metrics(n_series=3, n_samples=120, start_ms=BASE))
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine)
    try:
        ep = PromQlRemoteExec(
            f"http://127.0.0.1:{port}", "heap_usage0",
            BASE + 400_000, BASE + 1_100_000, 60_000,
        )
        calls = {"n": 0}
        # exercise the retry loop itself (first attempt raises inside _fetch)
        import urllib.error
        real_urlopen = urllib.request.urlopen

        def fail_once(*a, **kw):
            if calls["n"] == 0:
                calls["n"] += 1
                raise urllib.error.URLError("transient")
            return real_urlopen(*a, **kw)

        urllib.request.urlopen = fail_once
        try:
            res = ep.execute(engine.context())
        finally:
            urllib.request.urlopen = real_urlopen
        assert sum(g.n_series for g in res.grids) == 3
        assert calls["n"] == 1  # one failure, then success
    finally:
        srv.shutdown()


def test_auth_401_drains_post_body_keepalive():
    """Review regression: a 401 on a keep-alive connection must drain the
    POST body, or the next request on the socket desyncs."""
    import http.client

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0])
    engine = QueryEngine(ms, "prometheus")
    srv, port = serve_background(engine, auth_token="tok")
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        body = b"x" * 10_000
        conn.request("POST", "/ingest", body=body)  # no token
        r1 = conn.getresponse()
        assert r1.status == 401
        r1.read()
        # SAME socket: a correctly-drained connection serves the next request
        conn.request("GET", "/admin/health")
        r2 = conn.getresponse()
        assert r2.status == 200
        conn.close()
    finally:
        srv.shutdown()
