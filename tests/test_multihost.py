"""Multi-host runtime tests (reference: multi-jvm specs run N JVMs on one
box — coordinator/src/multi-jvm. Here: N OS processes join one JAX
distributed coordination service on localhost, CPU backend)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

from filodb_tpu.parallel.multihost import shards_for_process


class TestShardOwnership:
    def test_contiguous_split(self):
        assert shards_for_process(8, 2, 0) == [0, 1, 2, 3]
        assert shards_for_process(8, 2, 1) == [4, 5, 6, 7]

    def test_uneven_split(self):
        assert shards_for_process(7, 2, 0) == [0, 1, 2, 3]
        assert shards_for_process(7, 2, 1) == [4, 5, 6]

    def test_single_process_owns_all(self):
        assert shards_for_process(4, 1, 0) == [0, 1, 2, 3]


WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from filodb_tpu.parallel.multihost import init_distributed, make_multihost_mesh, shards_for_process
    ok = init_distributed(sys.argv[1], 2, int(sys.argv[2]))
    assert ok
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4  # 2 procs x 2 local cpu devices
    mesh = make_multihost_mesh()
    assert mesh.devices.size == 4
    # one global psum across both processes
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax.numpy as jnp
    x = jax.device_put(
        np.ones((4, 8), np.float32),
        NamedSharding(mesh, P("shard", None)),
    )
    out = jax.jit(
        jax.shard_map(
            lambda a: jax.lax.psum(a.sum(), "shard"),
            mesh=mesh, in_specs=P("shard", None), out_specs=P()
        )
    )(x)
    assert float(np.asarray(out)) == 32.0
    assert shards_for_process(8) in ([0,1,2,3],[4,5,6,7])
    print("MULTIHOST_OK", jax.process_index())
""")


def test_two_process_psum():
    """Two real processes, one coordination service, one global mesh, one
    cross-process psum. Skips when the sandbox forbids the coordination
    service's TCP listener."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items() if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coord, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append((p.returncode, out))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("distributed coordination service timed out in this sandbox")
    for rc, out in outs:
        if rc != 0 and ("UNAVAILABLE" in out or "Failed to connect" in out or "barrier" in out.lower()):
            pytest.skip(f"sandbox blocks the coordination service: {out[-300:]}")
        if rc != 0 and "Multiprocess computations aren't implemented" in out:
            # capability probe, not an env failure: this jaxlib's CPU
            # backend has no multiprocess collectives (cross-process psum
            # needs a real TPU/GPU backend or a newer CPU collectives
            # build) — the workers DID join the coordination service and
            # build the global mesh before the psum dispatch refused
            pytest.skip(
                "jax CPU backend lacks multiprocess collectives "
                "(XlaRuntimeError: 'Multiprocess computations aren't "
                "implemented on the CPU backend') — needs TPU/GPU or a "
                "CPU build with cross-process collectives"
            )
        assert rc == 0, out[-2000:]
        assert "MULTIHOST_OK" in out


class TestMultiHostServing:
    """Two FiloServer processes (in-process here), each owning half the
    shards, scattering queries to each other over HTTP (the reference's
    cross-node scatter-gather; multi-jvm IngestionAndRecoverySpec shape)."""

    def _start_pair(self):
        from filodb_tpu.server import FiloServer
        from filodb_tpu.testkit import counter_batch

        base_cfg = {"dataset": "prometheus", "shards": 8, "query": {"timeout_s": 300}}
        a = FiloServer({**base_cfg, "distributed": {"owned_shards": [0, 1, 2, 3]}})
        b = FiloServer({**base_cfg, "distributed": {"owned_shards": [4, 5, 6, 7]}})
        pa = a.start(port=0)
        pb = b.start(port=0)
        # wire peers post-start (ports are dynamic in tests)
        from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine

        def add_peer(srv, peer_port):
            srv.engine.planner.params.peer_endpoints = (f"http://127.0.0.1:{peer_port}",)

        add_peer(a, pb)
        add_peer(b, pa)
        # local engines for the X-FiloDB-Local path
        for srv in (a, b):
            srv.local_engine = QueryEngine(
                srv.memstore, srv.dataset,
                PlannerParams(num_shards=8, deadline_s=300),
            )
            srv._http.RequestHandlerClass.local_engine = srv.local_engine
        batch = counter_batch(n_series=24, n_samples=120, start_ms=1_600_000_000_000)
        na = a.memstore.ingest_routed("prometheus", batch, spread=3)
        nb = b.memstore.ingest_routed("prometheus", batch, spread=3)
        return a, b, pa, pb, na, nb

    def test_sharded_ingest_and_scattered_query(self):
        import json as _json
        import urllib.parse
        import urllib.request

        import numpy as np

        from filodb_tpu.coordinator.planner import QueryEngine
        from filodb_tpu.core.schemas import Dataset
        from filodb_tpu.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.testkit import counter_batch

        a = b = None
        try:
            a, b, pa, pb, na, nb = self._start_pair()
            # ingest routing split the batch across BOTH hosts, no overlap
            total_rows = 24 * 120
            assert na + nb == total_rows and na > 0 and nb > 0

            # baseline: one single-host store with everything
            ms = TimeSeriesMemStore()
            ms.setup(Dataset("prometheus"), range(8))
            ms.ingest_routed(
                "prometheus",
                counter_batch(n_series=24, n_samples=120, start_ms=1_600_000_000_000),
                spread=3,
            )
            eng = QueryEngine(ms, "prometheus")
            start_s, end_s = 1_600_000_400.0, 1_600_001_100.0
            want = eng.query_range(
                "sum(rate(http_requests_total[5m]))", start_s, end_s, 60
            ).grids[0].values_np()

            q = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
            url = (f"http://127.0.0.1:{pa}/api/v1/query_range?query={q}"
                   f"&start={start_s}&end={end_s}&step=60")
            with urllib.request.urlopen(url, timeout=300) as r:
                out = _json.loads(r.read())
            assert out["status"] == "success"
            vals = out["data"]["result"][0]["values"]
            got = np.array([float(v) for _, v in vals])
            np.testing.assert_allclose(got, want[0][: len(got)], rtol=1e-4)

            # plain selector through host B returns ALL 24 series
            q2 = urllib.parse.quote("http_requests_total")
            url2 = (f"http://127.0.0.1:{pb}/api/v1/query_range?query={q2}"
                    f"&start={start_s}&end={end_s}&step=60")
            with urllib.request.urlopen(url2, timeout=300) as r:
                out2 = _json.loads(r.read())
            assert len(out2["data"]["result"]) == 24
        finally:
            for srv in (a, b):
                if srv is not None:
                    srv.stop()


class TestMultiHostMetadataAndPushdown:
    def test_metadata_scatter_and_aggregate_pushdown(self):
        import json as _json
        import urllib.parse
        import urllib.request

        from filodb_tpu.query.promql import query_range_to_logical_plan

        pair = TestMultiHostServing()
        a = b = None
        try:
            a, b, pa, pb, na, nb = pair._start_pair()
            # label values scatter: host A must see instances living on B
            url = f"http://127.0.0.1:{pa}/api/v1/label/instance/values"
            with urllib.request.urlopen(url, timeout=300) as r:
                vals = _json.loads(r.read())["data"]
            assert len(vals) == 24  # every series' instance, both hosts
            # series scatter
            m = urllib.parse.quote("http_requests_total")
            url2 = f"http://127.0.0.1:{pb}/api/v1/series?match[]={m}"
            with urllib.request.urlopen(url2, timeout=300) as r:
                series = _json.loads(r.read())["data"]
            assert len(series) == 24

            # aggregate pushdown: the peer leaf ships sum by, not the selector
            plan = query_range_to_logical_plan(
                "sum(rate(http_requests_total[5m]))", 1_600_000_400, 1_600_001_100, 60)
            ep = a.engine.planner.materialize(plan)
            tree = ep.print_tree()
            assert "PromQlRemoteExec" in tree
            assert "promql=sum(rate(http_requests_total[5m]))" in tree
        finally:
            for srv in (a, b):
                if srv is not None:
                    srv.stop()
