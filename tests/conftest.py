"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on host devices (the driver separately dry-runs __graft_entry__.dryrun_multichip).
Must run before any jax import.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
