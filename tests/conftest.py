"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated
on host devices (the driver separately dry-runs __graft_entry__.dryrun_multichip).

Note: the environment may preload jax with a TPU platform plugin via
sitecustomize, so setting env vars is not enough — override the live jax
config before any backend initializes.
"""

import os

# keep grpc-core/absl INFO chatter (GOAWAY notices on server stop, etc.) off
# stderr: it interleaves with pytest's progress lines and corrupts them
os.environ.setdefault("GRPC_VERBOSITY", "ERROR")
os.environ.setdefault("ABSL_MIN_LOG_LEVEL", "2")

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, "tests expect an 8-device virtual CPU mesh"
