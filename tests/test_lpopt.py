"""Pre-aggregation rewrite tests (model: reference AggLpOptimizationSpec /
HierarchicalQueryExperience specs)."""

import pytest

from filodb_tpu.coordinator.lpopt import (
    AggRuleProvider,
    ExcludeAggRule,
    IncludeAggRule,
    optimize_with_preagg,
)
from filodb_tpu.query import logical as L
from filodb_tpu.query.promql import query_range_to_logical_plan
from filodb_tpu.query.unparse import to_promql


def plan(q):
    return query_range_to_logical_plan(q, 1000, 2000, 15)


def metric_of(p):
    leaves = L.leaf_raw_series(p)
    for f in leaves[0].filters:
        if f.column == "_metric_" and f.op == "=":
            return f.value


PROVIDER = AggRuleProvider([
    IncludeAggRule("http_requests_total", frozenset({"job", "code", "_ws_", "_ns_"})),
    ExcludeAggRule("node_.*", frozenset({"instance", "pod"})),
])


class TestIncludeRule:
    def test_covered_by_labels_rewrites(self):
        p = optimize_with_preagg(plan("sum by (job) (rate(http_requests_total[5m]))"), PROVIDER)
        assert metric_of(p) == "http_requests_total:agg"

    def test_uncovered_label_no_rewrite(self):
        p = optimize_with_preagg(plan("sum by (instance) (rate(http_requests_total[5m]))"), PROVIDER)
        assert metric_of(p) == "http_requests_total"

    def test_uncovered_filter_no_rewrite(self):
        p = optimize_with_preagg(
            plan('sum by (job) (rate(http_requests_total{instance="x"}[5m]))'), PROVIDER
        )
        assert metric_of(p) == "http_requests_total"

    def test_covered_filter_rewrites(self):
        p = optimize_with_preagg(
            plan('sum by (job) (rate(http_requests_total{code="500"}[5m]))'), PROVIDER
        )
        assert metric_of(p) == "http_requests_total:agg"


class TestExcludeRule:
    def test_excluded_label_no_rewrite(self):
        p = optimize_with_preagg(plan("sum by (instance) (node_cpu)"), PROVIDER)
        assert metric_of(p) == "node_cpu"

    def test_other_labels_rewrite(self):
        p = optimize_with_preagg(plan("sum by (mode) (node_cpu)"), PROVIDER)
        assert metric_of(p) == "node_cpu:agg"


class TestScope:
    def test_no_rule_no_rewrite(self):
        p = optimize_with_preagg(plan("sum by (a) (other_metric)"), PROVIDER)
        assert metric_of(p) == "other_metric"

    def test_topk_not_rewritten(self):
        p = optimize_with_preagg(plan("topk(3, http_requests_total)"), PROVIDER)
        assert metric_of(p) == "http_requests_total"

    def test_global_sum_not_rewritten(self):
        # sum without by-clause could rewrite, but reference requires explicit
        # grouping; keep parity
        p = optimize_with_preagg(plan("sum(http_requests_total)"), PROVIDER)
        assert metric_of(p) == "http_requests_total"

    def test_nested_in_binary_join(self):
        p = optimize_with_preagg(
            plan("sum by (job) (rate(http_requests_total[5m])) / sum by (job) (rate(other[5m]))"),
            PROVIDER,
        )
        metrics = set()
        for rs in L.leaf_raw_series(p):
            for f in rs.filters:
                if f.column == "_metric_":
                    metrics.add(f.value)
        assert metrics == {"http_requests_total:agg", "other"}


class TestMarkers:
    def test_disabled_provider_skips_unless_forced(self):
        from filodb_tpu.coordinator.lpopt import AggRuleProvider, IncludeAggRule
        disabled = AggRuleProvider(
            [IncludeAggRule("http_requests_total", frozenset({"job"}))], enabled=False)
        p = optimize_with_preagg(plan("sum by (job) (http_requests_total)"), disabled)
        assert metric_of(p) == "http_requests_total"
        p2 = optimize_with_preagg(
            plan("optimize_with_agg(sum by (job) (http_requests_total))"), disabled)
        assert metric_of(p2) == "http_requests_total:agg"
