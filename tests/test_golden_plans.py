"""Golden plan-tree tests (reference planner specs assert printTree string
equality — e.g. SingleClusterPlannerSpec, PlannerHierarchySpec)."""

import re

import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.promql import query_range_to_logical_plan


@pytest.fixture()
def planner():
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0, 1])
    return SingleClusterPlanner(ms, "prometheus")


def tree(planner, q, start=1000, end=2000, step=60):
    plan = query_range_to_logical_plan(q, start, end, step)
    return planner.materialize(plan).print_tree()


def normalize(t):
    return re.sub(r"-+", "-", t)


def test_golden_sum_rate(planner):
    # default engine: single-dispatch fused aggregate (doc/perf.md)
    got = tree(planner, "sum(rate(http_requests_total[5m]))")
    want = (
        "E~FusedAggregateExec(op=sum fn=rate by=None without=None "
        "shards=[0, 1] filters=[_metric_=http_requests_total])"
    )
    assert normalize(got) == normalize(want)


def test_golden_sum_rate_reference_tree():
    # fused disabled: the reference scatter/partial-merge tree (also the
    # shape FusedAggregateExec holds as its runtime fallback)
    from filodb_tpu.coordinator.planner import PlannerParams

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), [0, 1])
    planner = SingleClusterPlanner(
        ms, "prometheus", params=PlannerParams(fused_aggregate=False)
    )
    got = tree(planner, "sum(rate(http_requests_total[5m]))")
    want = """\
E~ReduceAggregateExec(op=sum by=None without=None)
-T~AggregateMapReduce()
-T~PeriodicSamplesMapper(fn=rate window=300000 step=60000)
-E~SelectRawPartitionsExec(shard=0 filters=[_metric_=http_requests_total] range=[700000,2000000])
-T~AggregateMapReduce()
-T~PeriodicSamplesMapper(fn=rate window=300000 step=60000)
-E~SelectRawPartitionsExec(shard=1 filters=[_metric_=http_requests_total] range=[700000,2000000])"""
    assert normalize(got) == normalize(want)


def test_golden_instant_selector(planner):
    got = tree(planner, "up")
    want = normalize("""\
E~DistConcatExec()
-T~PeriodicSamplesMapper(fn=None window=None step=60000)
-E~SelectRawPartitionsExec(shard=0 filters=[_metric_=up] range=[700000,2000000])
-T~PeriodicSamplesMapper(fn=None window=None step=60000)
-E~SelectRawPartitionsExec(shard=1 filters=[_metric_=up] range=[700000,2000000])""")
    assert normalize(got) == want


def test_golden_binary_join(planner):
    got = normalize(tree(planner, "a / b"))
    assert got.startswith("E~BinaryJoinExec(op=/ card=one-to-one")
    assert got.count("SelectRawPartitionsExec") == 4  # 2 shards x 2 sides


def test_golden_topk(planner):
    # global topk fuses its epilogue into the single-dispatch program
    got = normalize(tree(planner, "topk(3, rate(m[1m]))"))
    assert got.startswith("E~FusedAggregateExec(op=topk fn=rate")
    assert "params=(3.0,)" in got


def test_golden_topk_grouped_reference_tree(planner):
    # grouped topk keeps the per-shard candidate pre-reduction tree
    got = normalize(tree(planner, "topk by (job) (3, rate(m[1m]))"))
    assert got.startswith("E~AggregatePresentExec(op=topk params=(3.0,)")
    assert "PeriodicSamplesMapper(fn=rate window=60000" in got
    assert "TopkCandidateFilter" in got


def test_golden_scalar_op(planner):
    got = normalize(tree(planner, "m * 2"))
    assert got.startswith("E~ScalarVectorOpExec(op=* scalar_is_lhs=False)")
    assert "ScalarPlanExec" in got


def test_golden_long_time_range_stitch(planner):
    """Golden tree for the stitch shape (reference LongTimeRangePlannerSpec)."""
    from filodb_tpu.coordinator.planners import DownsampleClusterPlanner, LongTimeRangePlanner
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore

    dsm = TimeSeriesMemStore()
    dsm.setup(Dataset("prometheus_5m"), [0, 1])
    lp = LongTimeRangePlanner(
        planner, DownsampleClusterPlanner(dsm, "prometheus_5m"), lambda: 1_500_000)
    plan = query_range_to_logical_plan("avg_over_time(m[5m])", 1000, 2000, 60)
    t = normalize(lp.materialize(plan).print_tree())
    assert t.startswith("E~StitchRvsExec()")
    assert t.count("DistConcatExec") == 2  # one per cluster half
