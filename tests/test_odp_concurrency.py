"""On-demand paging + ingest/query concurrency tests (model: reference
QueryOnDemandBenchmark workload + PageAlignedBlockManagerConcurrentSpec
discipline: queries racing eviction/ingest must stay correct)."""

import threading

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import StoreConfig
from filodb_tpu.store.columnstore import LocalColumnStore
from filodb_tpu.store.flush import FlushCoordinator
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


class TestOnDemandPaging:
    def test_evicted_chunks_paged_back(self, tmp_path):
        store = LocalColumnStore(str(tmp_path))
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100, retention_ms=1_000_000))
        ms.setup(Dataset("ds"), [0])
        sh = ms.shard("ds", 0)
        sh.odp_store = store
        # 300 samples @10s = 50min of data
        ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=300, start_ms=BASE))
        FlushCoordinator(ms, store).flush_shard("ds", 0)
        engine = QueryEngine(ms, "ds")
        full_start, full_end = (BASE + 600_000) / 1000, (BASE + 2_400_000) / 1000
        want = engine.query_range("avg(heap_usage0)", full_start, full_end, 60.0)
        want_vals = want.grids[0].values_np().copy()

        # evict everything older than the last ~16 minutes
        dropped = sh.evict_for_retention(now_ms=BASE + 300 * 10_000)
        assert dropped > 0
        # same query: ODP must page evicted chunks back in
        got = engine.query_range("avg(heap_usage0)", full_start, full_end, 60.0)
        assert sh.odp_stats_pages > 0
        np.testing.assert_allclose(got.grids[0].values_np(), want_vals, rtol=1e-5, equal_nan=True)

    def test_no_store_no_paging(self):
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=2, n_samples=100, start_ms=BASE))
        sh = ms.shard("ds", 0)
        assert sh.odp_page_in([0], 0, 2**62) == 0


class TestIngestQueryConcurrency:
    def test_concurrent_ingest_and_query(self):
        """reference QueryAndIngestBenchmark shape: queries racing ingest
        must neither crash nor return garbage."""
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=100))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=5, n_samples=100, start_ms=BASE))
        engine = QueryEngine(ms, "ds")
        errors = []
        stop = threading.Event()

        def ingester():
            i = 1
            while not stop.is_set() and i < 20:
                batch = machine_metrics(n_series=5, n_samples=50, start_ms=BASE + i * 500_000)
                try:
                    ms.ingest("ds", 0, batch)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                i += 1

        def querier():
            for _ in range(15):
                if stop.is_set():
                    return
                try:
                    res = engine.query_range(
                        "sum(heap_usage0)", (BASE + 300_000) / 1000, (BASE + 9_000_000) / 1000, 120.0
                    )
                    for g in res.grids:
                        v = g.values_np()
                        m = ~np.isnan(v)
                        if m.any():
                            assert np.isfinite(v[m]).all()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=ingester)] + [
            threading.Thread(target=querier) for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        assert not errors, errors[:3]

    def test_concurrent_eviction_and_query(self):
        ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=50, retention_ms=600_000))
        ms.setup(Dataset("ds"), [0])
        ms.ingest("ds", 0, machine_metrics(n_series=5, n_samples=400, start_ms=BASE))
        engine = QueryEngine(ms, "ds")
        sh = ms.shard("ds", 0)
        errors = []

        def evicter():
            for k in range(10):
                try:
                    sh.evict_for_retention(now_ms=BASE + 4_000_000 + k * 50_000)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        def querier():
            for _ in range(10):
                try:
                    engine.query_range(
                        "avg(heap_usage0)", (BASE + 1_000_000) / 1000, (BASE + 4_000_000) / 1000, 60.0
                    )
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=evicter)] + [threading.Thread(target=querier) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:3]


def test_concurrent_flush_and_query(tmp_path):
    """Flush (seals buffers, persists, downsamples) racing queries must stay
    correct — the reference's flush-vs-query lock discipline, here via
    immutable chunk snapshots."""
    import threading

    from filodb_tpu.store.flush import FlushCoordinator

    store = LocalColumnStore(str(tmp_path))
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=60))
    ms.setup(Dataset("ds"), [0])
    ms.ingest("ds", 0, machine_metrics(n_series=4, n_samples=240, start_ms=BASE))
    engine = QueryEngine(ms, "ds")
    fc = FlushCoordinator(ms, store)
    errors = []

    def flusher():
        for _ in range(5):
            try:
                fc.flush_shard("ds", 0)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    def querier():
        for _ in range(8):
            try:
                res = engine.query_range(
                    "sum(heap_usage0)", (BASE + 600_000) / 1000, (BASE + 2_000_000) / 1000, 60)
                assert sum(g.n_series for g in res.grids) == 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=flusher)] + [threading.Thread(target=querier) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[:3]
