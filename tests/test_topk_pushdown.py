"""Per-shard topk/bottomk candidate pre-reduction (reference
TopBottomKRowAggregator k-heap spill: root sees O(k) rows per node, not the
full series set)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import QueryEngine
from filodb_tpu.core.schemas import Dataset
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec.transformers import TopkCandidateFilter
from filodb_tpu.query.rangevector import Grid
from filodb_tpu.testkit import machine_metrics

BASE = 1_600_000_000_000


def _grid(vals, labels=None):
    vals = np.asarray(vals, np.float32)
    labels = labels or [{"i": str(i)} for i in range(vals.shape[0])]
    return Grid(labels, BASE, 60_000, vals.shape[1], vals)


class TestTopkCandidateFilter:
    def test_keeps_exactly_the_per_step_winners_union(self):
        # series 0 wins step 0, series 3 wins step 1, series 1 is runner-up
        # both steps; series 2 never reaches top-2
        g = _grid([[9.0, 1.0], [8.0, 7.0], [1.0, 2.0], [2.0, 8.0]])
        out = TopkCandidateFilter(k=2).apply([g])[0]
        assert [l["i"] for l in out.labels] == ["0", "1", "3"]

    def test_bottomk(self):
        g = _grid([[9.0, 1.0], [8.0, 7.0], [1.0, 2.0], [2.0, 8.0]])
        out = TopkCandidateFilter(k=1, bottom=True).apply([g])[0]
        assert [l["i"] for l in out.labels] == ["0", "2"]  # step-1 / step-0 minima

    def test_ties_kept_superset_is_exact(self):
        g = _grid([[5.0], [5.0], [5.0], [1.0]])
        out = TopkCandidateFilter(k=1).apply([g])[0]
        # all three tied series survive (superset) — the root decides
        assert [l["i"] for l in out.labels] == ["0", "1", "2"]

    def test_grouping_is_per_group(self):
        labels = [{"job": "a", "i": "0"}, {"job": "a", "i": "1"},
                  {"job": "b", "i": "2"}, {"job": "b", "i": "3"}]
        g = _grid([[9.0], [1.0], [2.0], [8.0]], labels)
        out = TopkCandidateFilter(k=1, by=("job",)).apply([g])[0]
        # one winner PER job group, even though job=b values are all lower
        # than job=a's winner
        assert [l["i"] for l in out.labels] == ["0", "3"]

    def test_nan_rows_dropped(self):
        g = _grid([[np.nan, np.nan], [1.0, 2.0], [3.0, 4.0]])
        out = TopkCandidateFilter(k=2).apply([g])[0]
        assert [l["i"] for l in out.labels] == ["1", "2"]

    def test_small_grid_passthrough(self):
        g = _grid([[1.0], [2.0]])
        assert TopkCandidateFilter(k=5).apply([g])[0] is g


class TestEngineTopkParity:
    @pytest.fixture(scope="class")
    def engine(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(8))
        ms.ingest_routed(
            "prometheus",
            machine_metrics(n_series=40, n_samples=60, start_ms=BASE),
            spread=3,
        )
        return QueryEngine(ms, "prometheus")

    def test_pushdown_filter_is_planned_per_shard(self, engine):
        # global topk fuses (FusedAggregateExec) on the default engine; the
        # per-shard candidate pre-reduction is the reference-tree shape, so
        # plan with the fused path disabled (it is also what grouped topk
        # and the fused node's own runtime fallback use)
        from filodb_tpu.coordinator.planner import (
            PlannerParams, SingleClusterPlanner,
        )
        from filodb_tpu.query.promql import query_range_to_logical_plan

        planner = SingleClusterPlanner(
            engine.memstore, "prometheus",
            params=PlannerParams(fused_aggregate=False),
        )
        plan = query_range_to_logical_plan(
            "topk(3, heap_usage0)", (BASE + 400_000) / 1000, (BASE + 900_000) / 1000, 60)
        tree = planner.materialize(plan)
        assert "TopkCandidateFilter" in tree.print_tree()

    def test_topk_equals_full_matrix_oracle(self, engine):
        s, e = (BASE + 400_000) / 1000, (BASE + 900_000) / 1000
        full = engine.query_range("heap_usage0", s, e, 60)
        fv = np.vstack([g.values_np() for g in full.grids])
        fl = [l for g in full.grids for l in g.labels]
        k = 3
        res = engine.query_range(f"topk({k}, heap_usage0)", s, e, 60)
        got = {}
        for g in res.grids:
            vals = g.values_np()
            for i, lbl in enumerate(g.labels):
                got[str(sorted(lbl.items()))] = vals[i]
        # oracle: per step, k highest finite values survive with own labels
        J = fv.shape[1]
        want = {str(sorted(l.items())): np.full(J, np.nan, np.float32) for l in fl}
        for j in range(J):
            col = fv[:, j]
            finite = np.nonzero(np.isfinite(col))[0]
            top = finite[np.argsort(-col[finite], kind="stable")][:k]
            for i in top:
                want[str(sorted(fl[i].items()))][j] = col[i]
        want = {kk: v for kk, v in want.items() if np.isfinite(v).any()}
        assert set(got) == set(want)
        for kk in want:
            np.testing.assert_allclose(got[kk], want[kk], rtol=1e-5, equal_nan=True)

    def test_bottomk_through_engine(self, engine):
        s, e = (BASE + 400_000) / 1000, (BASE + 900_000) / 1000
        res = engine.query_range("bottomk(2, heap_usage0)", s, e, 60)
        vals = np.vstack([g.values_np() for g in res.grids])
        # at most k finite values per step
        assert (np.isfinite(vals).sum(axis=0) <= 2).all()


class TestCountValuesPushdown:
    @pytest.fixture(scope="class")
    def engine(self):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(8))
        ms.ingest_routed(
            "prometheus",
            machine_metrics(n_series=30, n_samples=40, start_ms=BASE),
            spread=3,
        )
        return QueryEngine(ms, "prometheus")

    def test_planned_as_per_shard_count_plus_merge(self, engine):
        from filodb_tpu.query.promql import query_range_to_logical_plan

        plan = query_range_to_logical_plan(
            'count_values("v", heap_usage0)',
            (BASE + 400_000) / 1000, (BASE + 900_000) / 1000, 60)
        tree = engine.planner.materialize(plan)
        printed = tree.print_tree()
        assert "CountValuesMergeExec" in printed
        assert "CountValuesMapReduce" in printed

    def test_counts_match_full_matrix_oracle(self, engine):
        s, e = (BASE + 400_000) / 1000, (BASE + 900_000) / 1000
        full = engine.query_range("heap_usage0", s, e, 60)
        fv = np.vstack([g.values_np() for g in full.grids])
        res = engine.query_range('count_values("v", heap_usage0)', s, e, 60)
        # total counted samples per step must equal finite samples per step
        got_total = np.zeros(fv.shape[1])
        for g in res.grids:
            v = g.values_np()
            got_total += np.where(np.isfinite(v), v, 0.0).sum(axis=0)
        np.testing.assert_array_equal(got_total, np.isfinite(fv).sum(axis=0))
        # and each reported (value, step) count matches a direct tally
        for g in res.grids:
            v = g.values_np()
            for i, lbl in enumerate(g.labels):
                x = float(lbl["v"])
                for j in range(v.shape[1]):
                    if np.isfinite(v[i, j]):
                        want = np.sum(np.isclose(fv[:, j], x, rtol=1e-9, atol=0))
                        assert v[i, j] == want, (lbl, j)


class TestPeerPushdown:
    """Multi-host: peers ship the topk/count_values themselves — O(k) /
    O(values) rows cross the wire, not the peer's full series set."""

    def _planner(self):
        from filodb_tpu.coordinator.planner import PlannerParams, SingleClusterPlanner

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        return SingleClusterPlanner(
            ms, "prometheus",
            params=PlannerParams(num_shards=4, peer_endpoints=("grpc://peer:7",)),
        )

    def test_topk_shipped_to_peer(self):
        from filodb_tpu.api.grpc_exec import GrpcPlanRemoteExec
        from filodb_tpu.query import logical as L
        from filodb_tpu.query.promql import query_range_to_logical_plan

        pl = self._planner()
        plan = query_range_to_logical_plan(
            "topk(3, rate(http_requests_total[5m]))", 1_600_000_400, 1_600_000_900, 60)
        tree = pl.materialize(plan)
        remotes = [p for p in _walk(tree) if isinstance(p, GrpcPlanRemoteExec)]
        assert len(remotes) == 1
        shipped = remotes[0].logical_plan
        assert isinstance(shipped, L.Aggregate) and shipped.op == "topk"
        assert shipped.params == (3.0,)
        assert not remotes[0].transformers  # nothing applied post-fetch

    def test_count_values_shipped_to_peer_and_merged(self):
        from filodb_tpu.api.grpc_exec import GrpcPlanRemoteExec
        from filodb_tpu.query import logical as L
        from filodb_tpu.query.promql import query_range_to_logical_plan

        pl = self._planner()
        plan = query_range_to_logical_plan(
            'count_values("v", http_requests_total)', 1_600_000_400, 1_600_000_900, 60)
        tree = pl.materialize(plan)
        assert type(tree).__name__ == "CountValuesMergeExec"
        remotes = [p for p in _walk(tree) if isinstance(p, GrpcPlanRemoteExec)]
        assert len(remotes) == 1
        shipped = remotes[0].logical_plan
        assert isinstance(shipped, L.Aggregate) and shipped.op == "count_values"

    def test_http_peer_gets_unparsed_topk(self):
        from filodb_tpu.coordinator.planner import PlannerParams, SingleClusterPlanner
        from filodb_tpu.coordinator.planners import PromQlRemoteExec
        from filodb_tpu.query.promql import query_range_to_logical_plan

        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(4))
        pl = SingleClusterPlanner(
            ms, "prometheus",
            params=PlannerParams(num_shards=4, peer_endpoints=("http://peer:9",)),
        )
        plan = query_range_to_logical_plan(
            "topk(2, rate(http_requests_total[5m]))", 1_600_000_400, 1_600_000_900, 60)
        tree = pl.materialize(plan)
        remotes = [p for p in _walk(tree) if isinstance(p, PromQlRemoteExec)]
        assert len(remotes) == 1
        assert remotes[0].promql.startswith("topk(2,")


def _walk(plan):
    yield plan
    for c in plan.children():
        yield from _walk(c)
