"""North-star benchmark (BASELINE.md): p50 latency of a 100k-series
``sum(rate(http_requests_total[5m]))`` range query, TPU engine vs a strong
vectorized-numpy CPU implementation of the identical computation (stand-in
for the reference's JVM+SIMD path — QueryInMemoryBenchmark.scala workload
shape scaled to the driver's 100k-series target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = TPU p50 latency (ms) of the full query path (PromQL parse -> plan ->
exec -> kernels -> result) with warm HBM-staged windows; vs_baseline =
CPU_p50 / TPU_p50 (higher is better).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_SERIES = int(os.environ.get("FILODB_BENCH_SERIES", 100_000))
# workload: "sum_rate" (the north-star scalar query), "hist_quantile"
# (the fused histogram/epilogue pipeline: histogram_quantile(0.99,
# sum by (le) (rate(..._bucket[5m]))) over native [T, B] histograms),
# "ingest_impact" (warm canonical query p50 under a live 10-batches/s
# ingest stream vs its own idle baseline — the ratio the incremental
# superblock extension exists to hold near 1.0), or "fused_mesh"
# (single-device vs mesh-sharded fused p50 on a forced 8-device mesh:
# the sharded superblock's one-dispatch path, doc/perf.md "Mesh-sharded
# fused path"; value = sharded p50, vs_baseline = scaling ratio), or
# "standing_refresh" (registered standing query's delta-maintained
# live-edge refresh vs the pre-standing cold dashboard poll of the same
# sliding grid, both under live ingest — doc/operations.md "Standing
# queries & recording rules"; value = cold_p50 / standing_p50), or
# "failover_storm" (16-client query storm over an RF=2 replica cluster
# with one node killed mid-window — doc/robustness.md "Replicated shard
# plane"; value = during-kill qps, match = zero failures + bit-equal)
WORKLOAD = os.environ.get("FILODB_BENCH_WORKLOAD", "sum_rate")
# the ONE metric name per workload — emitted by both the success and error
# JSON paths, and matched against benchmarks/bench_smoke_floor.json entries
METRIC = {
    "hist_quantile": "hist_quantile_range_query_p50",
    "ingest_impact": "ingest_impact_on_query",
    "fused_mesh": "fused_mesh_sharded_query_p50",
    "concurrent_qps": "concurrent_qps_16clients_20k",
    "fused_jitter": "fused_jitter_holes_ratio",
    "standing_refresh": "standing_refresh_speedup",
    "index_regex": "index_regex_lookups_1000k",
    "query_hicard": "query_hicard_2000_of_8000_qps",
    "long_range_quantile": "long_range_quantile_30d_p50",
    "failover_storm": "failover_storm_qps_2k",
    "render_2m": "render_2m_stream_msamples",
    "mixed_cost_storm": "mixed_cost_storm_cheap_retained",
}.get(WORKLOAD, "sum_rate_100k_series_range_query_p50")
# concurrent_qps: client thread count, per-mode measurement window, and the
# batching window handed to the batched engine (the knob under test)
QPS_CLIENTS = int(os.environ.get("FILODB_BENCH_CLIENTS", 16))
QPS_DURATION_S = float(os.environ.get("FILODB_BENCH_QPS_DURATION_S", 6.0))
QPS_BATCH_WINDOW_MS = float(os.environ.get("FILODB_BENCH_BATCH_WINDOW_MS", 200.0))
# fused_mesh: virtual mesh width on the CPU backend (real accelerators use
# every visible device)
MESH_DEVICES = int(os.environ.get("FILODB_BENCH_MESH_DEVICES", 8))
# per-sample scrape-timestamp jitter as a fraction of the interval (e.g. 0.05
# = +/-5%): exercises the near-regular MXU path (ops/mxu_jitter.py) instead
# of the exact-shared-grid path
JITTER = float(os.environ.get("FILODB_BENCH_JITTER", 0.0))
N_SAMPLES = 720  # 2h @ 10s
INTERVAL_MS = 10_000
BASE = 1_600_000_000_000
WINDOW_MS = 300_000
STEP_S = 60.0
START_S = (BASE + 400_000) / 1000
# ingest_impact queries the LIVE EDGE: the range reaches past the newest
# sample so the streamed appends land inside it (the superblock must
# extend, not restage); other workloads keep the fully-covered range
MAX_APPEND_BATCHES = 600  # ingest_impact: 1 sample/series per batch
END_S = (
    (BASE + (N_SAMPLES + MAX_APPEND_BATCHES + 20) * INTERVAL_MS) / 1000
    if WORKLOAD == "ingest_impact"
    else (BASE + N_SAMPLES * INTERVAL_MS - 200_000) / 1000
)
N_SHARDS = 8
# the watchdog (tools/tpu_watch.py) shrinks this in quick mode to minimize
# tunnel exposure while a healthy window lasts
TIMED_RUNS = int(os.environ.get("FILODB_BENCH_RUNS", 15))


def build_memstore(jitter=None, hole_frac=0.0, phase_ms=0):
    """100k counter series across 8 shards, ingested through the normal path
    (bulk per-series ingestion; generation is vectorized). ``jitter``
    overrides the FILODB_BENCH_JITTER env fraction; ``hole_frac`` drops
    that fraction of interior scrapes per series (different slots per
    series — the missing-scrape grid); ``phase_ms`` shifts the nominal grid
    so it never lands a slot exactly on the 5m-aligned staging boundary
    (where jitter would clip it for SOME series and flip the grid class)."""
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import (
        Dataset, METRIC_TAG, PROM_COUNTER, shard_for,
    )
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.memstore.shard import StoreConfig

    jit = JITTER if jitter is None else jitter
    rng = np.random.default_rng(42)
    ts = BASE + phase_ms + np.arange(N_SAMPLES, dtype=np.int64) * INTERVAL_MS
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=N_SAMPLES))
    ms.setup(Dataset("prometheus"), range(N_SHARDS))
    t0 = time.time()
    # vectorized value generation in blocks to bound memory
    blk = 10_000
    for b0 in range(0, N_SERIES, blk):
        n = min(blk, N_SERIES - b0)
        incr = rng.uniform(0, 10, size=(n, N_SAMPLES))
        vals = np.cumsum(incr, axis=1) + 1e9
        if jit > 0:
            dev = np.rint(
                rng.uniform(-jit, jit, size=(n, N_SAMPLES)) * INTERVAL_MS
            ).astype(np.int64)
        for i in range(n):
            tags = {
                METRIC_TAG: "http_requests_total",
                "_ws_": "demo",
                "_ns_": "App-2",
                "instance": f"host-{b0 + i}",
                # medium-cardinality dimension for grouped dashboard panels
                # (the concurrent_qps workload's by-variants)
                "zone": f"z{(b0 + i) % 8}",
            }
            shard = shard_for(tags, spread=3, num_shards=N_SHARDS)
            row_ts = ts + dev[i] if jit > 0 else ts
            row_vals = vals[i]
            if hole_frac > 0:
                keep = np.ones(N_SAMPLES, bool)
                keep[rng.choice(
                    np.arange(1, N_SAMPLES - 1),
                    max(1, int(hole_frac * N_SAMPLES)), replace=False,
                )] = False
                row_ts, row_vals = row_ts[keep], row_vals[keep]
            ms.shard("prometheus", shard).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, row_ts, {"count": row_vals})
            )
    sys.stderr.write(
        f"ingest: {N_SERIES} series x {N_SAMPLES} samples in {time.time()-t0:.1f}s"
        + (f" (jitter +/-{jit:.0%}, holes {hole_frac:.1%})\n"
           if jit > 0 or hole_frac > 0 else "\n")
    )
    return ms, ts


N_BUCKETS = 12  # PROM_DEFAULT scheme width (11 finite bounds + Inf)


def build_memstore_hist():
    """Native cumulative histograms (N_SERIES series x N_SAMPLES x
    N_BUCKETS) across 8 shards — the canonical SRE latency workload."""
    from filodb_tpu.core.histograms import PROM_DEFAULT
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import (
        Dataset, METRIC_TAG, PROM_HISTOGRAM, shard_for,
    )
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.memstore.shard import StoreConfig

    rng = np.random.default_rng(42)
    ts = BASE + np.arange(N_SAMPLES, dtype=np.int64) * INTERVAL_MS
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=N_SAMPLES))
    ms.setup(Dataset("prometheus"), range(N_SHARDS))
    les = PROM_DEFAULT.bounds()
    t0 = time.time()
    blk = 2_000
    for b0 in range(0, N_SERIES, blk):
        n = min(blk, N_SERIES - b0)
        incr = rng.poisson(2.0, size=(n, N_SAMPLES, N_BUCKETS)).astype(np.float64)
        incr[..., -1] = incr.sum(-1)  # +Inf bucket grows with everything
        hist = np.cumsum(np.cumsum(incr, axis=2), axis=1)
        count = hist[..., -1]
        total = np.cumsum(rng.uniform(0, 5, size=(n, N_SAMPLES)), axis=1)
        for i in range(n):
            tags = {
                METRIC_TAG: "http_request_latency",
                "_ws_": "demo",
                "_ns_": "App-2",
                "instance": f"host-{b0 + i}",
            }
            shard = shard_for(tags, spread=3, num_shards=N_SHARDS)
            ms.shard("prometheus", shard).ingest_series(SeriesBatch(
                PROM_HISTOGRAM, tags, ts,
                {"sum": total[i], "count": count[i], "h": hist[i]},
                bucket_les=les,
            ))
    sys.stderr.write(
        f"ingest: {N_SERIES} hist series x {N_SAMPLES} samples x "
        f"{N_BUCKETS} buckets in {time.time()-t0:.1f}s\n"
    )
    return ms, ts


def cpu_baseline_hist(ms, ts):
    """Strong CPU oracle for the hist_quantile workload: vectorized f64
    numpy per-bucket extrapolated rate -> bucket-wise sum across series ->
    histogram_quantile interpolation, identical semantics to
    ops/hist_kernels (per-bucket extrapolation, no zero cap; quantile
    interpolation with the +Inf top-bucket rule). Series are processed in
    blocks accumulating the [J, B] bucket sums, so memory stays bounded at
    100k-series scale."""
    from filodb_tpu.core.histograms import PROM_DEFAULT

    Q = 0.99
    les = PROM_DEFAULT.bounds()
    num_steps = int((END_S - START_S) // STEP_S) + 1
    out_t = (np.int64(START_S * 1000)
             + np.arange(num_steps, dtype=np.int64) * int(STEP_S * 1000))
    t0g = ts
    hi1 = np.searchsorted(t0g, out_t, side="right")
    lo1 = np.searchsorted(t0g, out_t - WINDOW_MS, side="right")
    cnt = hi1 - lo1
    T = len(t0g)
    lo_c = np.minimum(lo1, T - 1)
    hi_c = np.minimum(hi1 - 1, T - 1)
    tf = t0g[lo_c].astype(np.float64) / 1e3
    tl = t0g[hi_c].astype(np.float64) / 1e3
    sampled = tl - tf
    dur_start = tf - (out_t / 1e3 - WINDOW_MS / 1e3)
    dur_end = out_t / 1e3 - tl
    avg_dur = sampled / np.maximum(cnt - 1, 1)
    thresh = avg_dur * 1.1
    ds = np.where(dur_start >= thresh, avg_dur / 2, dur_start)
    de = np.where(dur_end >= thresh, avg_dur / 2, dur_end)
    factor = np.where(
        cnt >= 2, (sampled + ds + de) / np.maximum(sampled, 1e-30), np.nan
    )  # [J], shared by every series/bucket (shared regular grid)

    parts = [
        p for sh in ms.shards("prometheus") for p in sh.partitions.values()
    ]

    def run():
        bucket_sum = np.zeros((num_steps, len(les)), dtype=np.float64)
        blk = 4_000
        for b0 in range(0, len(parts), blk):
            H = np.stack([
                parts[i].samples_in_range(
                    int(t0g[0]), int(t0g[-1]), "h")[1]
                for i in range(b0, min(b0 + blk, len(parts)))
            ])  # [s, T, B] cumulative
            dlt = H[:, hi_c] - H[:, lo_c]  # [s, J, B]
            bucket_sum += np.nansum(
                dlt * factor[None, :, None] / (WINDOW_MS / 1e3), axis=0
            )
        # histogram_quantile interpolation over the summed buckets
        total = bucket_sum[:, -1]
        rank = Q * total
        meets = bucket_sum >= rank[:, None]
        idx = np.argmax(meets, axis=1)
        idx = np.where(meets.any(1), idx, len(les) - 1)
        c_hi = np.take_along_axis(bucket_sum, idx[:, None], axis=1)[:, 0]
        c_lo = np.where(
            idx > 0,
            np.take_along_axis(
                bucket_sum, np.maximum(idx - 1, 0)[:, None], axis=1)[:, 0],
            0.0,
        )
        le_hi = les[idx]
        le_lo = np.where(idx > 0, les[np.maximum(idx - 1, 0)],
                         0.0 if les[0] > 0 else -np.inf)
        frac = (rank - c_lo) / np.maximum(c_hi - c_lo, 1e-30)
        val = le_lo + (le_hi - le_lo) * frac
        val = np.where(idx == len(les) - 1, les[-2], val)
        return np.where((total > 0) & np.isfinite(total), val, np.nan)

    ref = run()
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    return float(np.median(times) * 1e3), ref


def cpu_baseline(ms, ts):
    """Strong CPU implementation: vectorized f64 numpy sum(rate) over the
    same data — a best-case stand-in for the reference's chunked-iterator +
    Rust SIMD CPU path. Handles per-series (jittered) timestamps with
    row-offset batched searchsorted; the shared-grid case uses one
    searchsorted for all series."""
    series_ts, series_v = [], []
    for sh in ms.shards("prometheus"):
        for part in sh.partitions.values():
            t, v = part.samples_in_range(int(ts[0] - INTERVAL_MS), int(ts[-1] + INTERVAL_MS), "count")
            series_ts.append(t)
            series_v.append(v)
    vals = np.stack(series_v)  # [S, T] f64
    tmat = np.stack(series_ts)  # [S, T] i64
    shared = not (tmat != tmat[0]).any()
    num_steps = int((END_S - START_S) // STEP_S) + 1
    out_t = (np.int64(START_S * 1000) + np.arange(num_steps, dtype=np.int64) * int(STEP_S * 1000))
    S, T = vals.shape

    def run():
        # reset correction (vectorized prefix)
        drops = np.where(vals[:, 1:] < vals[:, :-1], vals[:, :-1], 0.0)
        corr = np.concatenate([np.zeros((vals.shape[0], 1)), np.cumsum(drops, axis=1)], axis=1)
        cv = vals + corr
        if shared:
            # one 1-D searchsorted + column fancy-indexing for ALL series:
            # the strongest CPU form of the shared-grid workload (r02 form —
            # benchmark-integrity contract, VERDICT r3 weak #3: the baseline
            # must not silently pay the per-row gather cost here)
            t0 = tmat[0]
            hi1 = np.searchsorted(t0, out_t, side="right")
            lo1 = np.searchsorted(t0, out_t - WINDOW_MS, side="right")
            cnt = (hi1 - lo1)[None, :]
            lo_c = np.minimum(lo1, T - 1)
            hi_c = np.minimum(hi1 - 1, T - 1)
            tf = (t0[lo_c].astype(np.float64) / 1e3)[None, :]
            tl = (t0[hi_c].astype(np.float64) / 1e3)[None, :]
            vf = cv[:, lo_c]
            vl = cv[:, hi_c]
            raw_f = vals[:, lo_c]
        else:
            stride = np.int64(1) << 42
            row_off = (np.arange(S, dtype=np.int64) * stride)[:, None]
            flat = (tmat + row_off).ravel()
            hi = np.searchsorted(flat, (out_t[None, :] + row_off).ravel(), side="right")
            lo = np.searchsorted(flat, ((out_t - WINDOW_MS)[None, :] + row_off).ravel(), side="right")
            hi = hi.reshape(S, -1) - np.arange(S)[:, None] * T
            lo = lo.reshape(S, -1) - np.arange(S)[:, None] * T
            cnt = hi - lo
            tf = np.take_along_axis(tmat, np.minimum(lo, T - 1), 1).astype(np.float64) / 1e3
            tl = np.take_along_axis(tmat, np.minimum(hi - 1, T - 1), 1).astype(np.float64) / 1e3
            vf = np.take_along_axis(cv, np.minimum(lo, T - 1), 1)
            vl = np.take_along_axis(cv, np.minimum(hi - 1, T - 1), 1)
            raw_f = np.take_along_axis(vals, np.minimum(lo, T - 1), 1)
        dlt = vl - vf
        sampled = tl - tf
        dur_start = tf - (out_t / 1e3 - WINDOW_MS / 1e3)[None, :]
        dur_end = (out_t / 1e3)[None, :] - tl
        avg_dur = sampled / np.maximum(cnt - 1, 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            dur_zero = np.where(dlt > 0, sampled * (raw_f / np.maximum(dlt, 1e-30)), np.inf)
            ds = np.minimum(dur_start, np.where(raw_f >= 0, dur_zero, np.inf))
            thresh = avg_dur * 1.1
            ds = np.where(ds >= thresh, avg_dur / 2, ds)
            de = np.where(dur_end >= thresh, avg_dur / 2, dur_end)
            factor = (sampled + ds + de) / np.maximum(sampled, 1e-30)
            rate = np.where(cnt >= 2, dlt * factor / (WINDOW_MS / 1e3), np.nan)
        return np.nansum(rate, axis=0)

    ref = run()
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    return float(np.median(times) * 1e3), ref


def _span_phase_ms(trace, out: dict) -> None:
    """Accumulate per-phase durations from the query's span tree.

    Phases (doc/perf.md): lookup/stage under ``fused:stage`` (index lookup +
    superblock build, split out as fused:lookup when present), ``dispatch``
    from the fused/kernel spans, ``merge`` from the partial-merge root when
    the reference tree ran. ``transfer`` is measured by the caller around
    the device->host fetch."""
    if trace is None:
        return

    def kernel_ms(sp) -> float:
        own = sp.duration_ms if sp.name.startswith("kernel:") else 0.0
        return own + sum(kernel_ms(c) for c in sp.children)

    name = trace.name
    if name.startswith("fused:lookup"):
        out["lookup"] = out.get("lookup", 0.0) + trace.duration_ms
    elif name.startswith("fused:stage"):
        out["stage"] = out.get("stage", 0.0) + trace.duration_ms
    elif name.startswith("fused:dispatch") or name.startswith("kernel:"):
        out["dispatch"] = out.get("dispatch", 0.0) + trace.duration_ms
    elif name in ("ReduceAggregateExec", "AggregatePresentExec"):
        child_ms = sum(c.duration_ms for c in trace.children)
        out["merge"] = out.get("merge", 0.0) + max(
            trace.duration_ms - child_ms, 0.0
        )
    elif name == "SelectRawPartitionsExec":
        # the leaf span covers staging AND its folded transformers' kernel
        # dispatches; attribute the kernel subtree to dispatch (handled by
        # the kernel: branch when recursion reaches it), not to stage
        out["stage"] = out.get("stage", 0.0) + max(
            trace.duration_ms - kernel_ms(trace), 0.0
        )
    for c in trace.children:
        _span_phase_ms(c, out)


def _enable_compile_cache():
    # persistent compile cache: the cold stage+compile warmup survives
    # process restarts (FILODB_COMPILE_CACHE=0 disables; dir overridable)
    from filodb_tpu.ops.compile_cache import enable_compile_cache

    if os.environ.get("FILODB_COMPILE_CACHE", "1") != "0":
        enable_compile_cache(os.environ.get(
            "FILODB_COMPILE_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax-compile-cache"),
        ))


def tpu_query(ms):
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine

    _enable_compile_cache()
    # default engine: the planner fuses the multi-shard query into ONE
    # compiled dispatch over a device-resident superblock
    # (FusedAggregateExec; doc/perf.md) — for hist_quantile that one program
    # is hist rate -> per-bucket segment-sum -> quantile interpolation
    engine = QueryEngine(ms, "prometheus", PlannerParams())
    q = (
        "histogram_quantile(0.99, "
        "sum by (le) (rate(http_request_latency_bucket[5m])))"
        if WORKLOAD == "hist_quantile"
        else "sum(rate(http_requests_total[5m]))"
    )

    def run():
        res = engine.query_range(q, START_S, END_S, STEP_S)
        # force full materialization to host (honest end-to-end latency)
        t_f = time.perf_counter()
        out = [np.asarray(g.values_np()) for g in res.grids]
        return res, out, time.perf_counter() - t_f

    t0 = time.perf_counter()
    res, out, _tf = run()  # compile + stage + cache warm
    warmup_s = time.perf_counter() - t0
    sys.stderr.write(f"warmup (stage+compile): {warmup_s:.1f}s\n")
    # deadline-aware: on a degraded tunnel each run can take seconds — trim
    # the run count (min 3) so the worker still reports a REAL accelerator
    # p50 inside its budget instead of being killed mid-loop
    deadline = float(os.environ.get("FILODB_BENCH_WORKER_DEADLINE", 0)) or None
    times = []
    phases: dict = {}
    for i in range(TIMED_RUNS):
        t0 = time.perf_counter()
        res, out, transfer_s = run()
        times.append(time.perf_counter() - t0)
        # steady-state attribution from the LAST warm run's trace
        phases = {}
        _span_phase_ms(res.trace, phases)
        phases["transfer"] = transfer_s * 1e3
        if (deadline and len(times) >= 3
                and time.time() + np.median(times) * 2 > deadline):
            sys.stderr.write(f"deadline near: stopping after {len(times)} runs\n")
            break
    vals = res.grids[0].values_np()[0]
    phases = {k: round(v, 3) for k, v in sorted(phases.items())}
    sys.stderr.write(f"phases_ms={json.dumps(phases)}\n")
    return float(np.median(times) * 1e3), vals, res, warmup_s, phases


def cpu_oracle_ragged(ms):
    """numpy f64 sum(rate) oracle that tolerates RAGGED per-series sample
    counts (dropped scrapes) — the per-series form of cpu_baseline's math,
    used by the fused_jitter workload's match check."""
    num_steps = int((END_S - START_S) // STEP_S) + 1
    out_t = (np.int64(START_S * 1000)
             + np.arange(num_steps, dtype=np.int64) * int(STEP_S * 1000))
    acc = np.zeros(num_steps, dtype=np.float64)
    for sh in ms.shards("prometheus"):
        for part in sh.partitions.values():
            ts, v = part.samples_in_range(
                int(out_t[0] - WINDOW_MS), int(out_t[-1]), "count"
            )
            if not len(ts):
                continue
            v = v.astype(np.float64)
            drops = np.where(v[1:] < v[:-1], v[:-1], 0.0)
            cv = v + np.concatenate([[0.0], np.cumsum(drops)])
            T = len(ts)
            hi = np.searchsorted(ts, out_t, side="right")
            lo = np.searchsorted(ts, out_t - WINDOW_MS, side="right")
            cnt = hi - lo
            lo_c = np.minimum(lo, T - 1)
            hi_c = np.minimum(hi - 1, T - 1)
            tf = ts[lo_c].astype(np.float64) / 1e3
            tl = ts[hi_c].astype(np.float64) / 1e3
            vf, vl, raw_f = cv[lo_c], cv[hi_c], v[lo_c]
            dlt = vl - vf
            sampled = tl - tf
            dur_start = tf - (out_t / 1e3 - WINDOW_MS / 1e3)
            dur_end = out_t / 1e3 - tl
            avg_dur = sampled / np.maximum(cnt - 1, 1)
            with np.errstate(divide="ignore", invalid="ignore"):
                dur_zero = np.where(
                    dlt > 0, sampled * (raw_f / np.maximum(dlt, 1e-30)),
                    np.inf,
                )
                ds = np.minimum(
                    dur_start, np.where(raw_f >= 0, dur_zero, np.inf)
                )
                thresh = avg_dur * 1.1
                ds = np.where(ds >= thresh, avg_dur / 2, ds)
                de = np.where(dur_end >= thresh, avg_dur / 2, dur_end)
                factor = (sampled + ds + de) / np.maximum(sampled, 1e-30)
                rate = np.where(
                    cnt >= 2, dlt * factor / (WINDOW_MS / 1e3), np.nan
                )
            acc += np.nan_to_num(rate, nan=0.0)
    return acc


def run_benchmark_fused_jitter():
    """Warm canonical-query p50 on jitter5pct and jitter+holes grids vs the
    regular-grid fused path — the jitter-tolerant fused kernels
    (doc/perf.md "Jitter-tolerant fused path") exist to hold these ratios
    near 1.0x (they measured 1.70x / 4.85x on the multi-pass general path).

    value = p50(jitter+holes) / p50(regular) (unit "x", LOWER is better —
    the smoke floor gates it); vs_baseline = the inverse; phases_ms carries
    all three p50s and both ratios. match = each variant agrees with the
    ragged numpy oracle, the superblock classifies into the EXPECTED grid
    class, AND the warm query stays exactly ONE kernel dispatch on the
    jittered variants (losing the jitter/masked fused variants flips
    match before it shows as latency)."""
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.testkit import kernel_dispatch_total

    _enable_compile_cache()
    q = "sum(rate(http_requests_total[5m]))"
    variants = (
        ("regular", 0.0, 0.0),
        ("jitter5pct", 0.05, 0.0),
        ("jitter_holes", 0.05, 0.01),
    )
    expected_grid = {"regular": "regular", "jitter5pct": "jitter",
                     "jitter_holes": "holes"}
    ok = True
    warmup_s = 0.0
    engines = {}
    for label, jit, holes in variants:
        ms, _ts = build_memstore(
            jitter=jit, hole_frac=holes, phase_ms=INTERVAL_MS // 2
        )
        engine = QueryEngine(ms, "prometheus", PlannerParams())

        def run(engine=engine):
            res = engine.query_range(q, START_S, END_S, STEP_S)
            for g in res.grids:
                np.asarray(g.values_np())
            return res

        t0 = time.perf_counter()
        run()  # stage + compile + cache warm
        warmup_s += time.perf_counter() - t0
        before = kernel_dispatch_total()
        res = run()
        single = kernel_dispatch_total() - before == 1
        grid = {e.get("grid") for e in ms._superblock_cache.snapshot()}
        grid_ok = expected_grid[label] in grid
        oracle = cpu_oracle_ragged(ms)
        vals = res.grids[0].values_np()[0]
        n = min(len(vals), len(oracle))
        with np.errstate(invalid="ignore"):
            match = bool(np.allclose(vals[:n], oracle[:n], rtol=5e-3))
        ok = ok and match and single and grid_ok
        sys.stderr.write(
            f"{label}: single_dispatch={single} grid={sorted(grid)} "
            f"(want {expected_grid[label]}) match={match}\n"
        )
        engines[label] = (ms, run)
    # timed rounds INTERLEAVE the three variants so container noise hits
    # all of them equally, and the reported ratios are MEDIANS OF PER-ROUND
    # ratios: a noise burst inflates every variant of its round, so the
    # round's ratio stays honest, where a ratio of across-round medians
    # swings 2x with scheduler luck on a shared 2-vCPU box
    times: dict = {label: [] for label, _, _ in variants}
    for _ in range(TIMED_RUNS):
        for label, _, _ in variants:
            t0 = time.perf_counter()
            engines[label][1]()
            times[label].append(time.perf_counter() - t0)
    p50 = {label: float(np.median(ts) * 1e3) for label, ts in times.items()}
    for label in p50:
        sys.stderr.write(f"{label}: p50={p50[label]:.2f}ms\n")
    del engines
    reg = np.asarray(times["regular"])
    jitter_ratio = float(np.median(np.asarray(times["jitter5pct"]) / reg))
    holes_ratio = float(np.median(np.asarray(times["jitter_holes"]) / reg))
    import jax

    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"jitter5pct={jitter_ratio:.2f}x jitter+holes={holes_ratio:.2f}x "
        f"vs regular (match={ok})\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(holes_ratio, 3),
        "unit": "x",
        "vs_baseline": round(1.0 / holes_ratio, 3) if holes_ratio else 0.0,
        "backend": backend,
        "series": N_SERIES,
        "match": bool(ok),
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {
            "regular_p50": round(p50["regular"], 3),
            "jitter_p50": round(p50["jitter5pct"], 3),
            "holes_p50": round(p50["jitter_holes"], 3),
            "jitter_ratio_x": round(jitter_ratio, 3),
            "holes_ratio_x": round(holes_ratio, 3),
        },
    }))


def run_benchmark_ingest_impact():
    """Warm canonical query p50 under a live ingest stream vs idle.

    One 1-sample-per-series batch every 100 ms (the benchmarks/run.py
    QueryAndIngest cadence) lands INSIDE the query's live-edge range, so
    every batch overlaps the cached superblock: the interval-aware
    maintenance path must EXTEND it in place for the ratio to stay near
    1.0x (invalidate-and-restage measured 2.07x). value = busy_p50 /
    idle_p50 (unit "x"); match = final post-stream query vs the numpy
    oracle over the final store contents."""
    import threading

    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.core.schemas import METRIC_TAG, PROM_COUNTER

    ms, ts = build_memstore()
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine

    _enable_compile_cache()
    engine = QueryEngine(ms, "prometheus", PlannerParams())
    q = "sum(rate(http_requests_total[5m]))"

    def run_query():
        res = engine.query_range(q, START_S, END_S, STEP_S)
        return res, [np.asarray(g.values_np()) for g in res.grids]

    t0 = time.perf_counter()
    run_query()  # compile + stage + cache warm
    warmup_s = time.perf_counter() - t0
    idle = []
    for _ in range(TIMED_RUNS):
        t0 = time.perf_counter()
        run_query()
        idle.append(time.perf_counter() - t0)
    # MEAN, not median (same as benchmarks/run.py's dt_busy/dt_idle): the
    # maintenance cost under ingest lands on the one query per batch that
    # absorbs the append — a median over many runs hides it entirely,
    # while the mean is exactly "amortized query cost under the stream"
    idle_ms = float(np.mean(idle) * 1e3)

    # the ingest stream: deterministic, pre-derived tags, values monotone
    # above every series' build-time maximum (no artificial resets)
    # tag sets must match build_memstore EXACTLY (zone included): a differing
    # set would mint NEW series instead of appending to the existing ones
    tags_list = [
        {METRIC_TAG: "http_requests_total", "_ws_": "demo", "_ns_": "App-2",
         "instance": f"host-{i}", "zone": f"z{i % 8}"}
        for i in range(N_SERIES)
    ]
    stop = threading.Event()
    ingested = [0]

    def ingester():
        b = 0
        while not stop.is_set() and b < MAX_APPEND_BATCHES:
            t = BASE + (N_SAMPLES + b) * INTERVAL_MS
            vals = np.full(N_SERIES, 1e9 + 10.0 * (N_SAMPLES + b + 1))
            batch = RecordBatch(
                PROM_COUNTER, np.full(N_SERIES, t, np.int64),
                {"count": vals}, tags_list,
            )
            ingested[0] += ms.ingest_routed("prometheus", batch, spread=3)
            b += 1
            stop.wait(0.1)

    th = threading.Thread(target=ingester)
    th.start()
    busy = []
    try:
        for _ in range(TIMED_RUNS):
            t0 = time.perf_counter()
            run_query()
            busy.append(time.perf_counter() - t0)
    finally:
        stop.set()
        th.join()
    assert ingested[0] > 0, "ingester must actually run during the window"
    busy_ms = float(np.mean(busy) * 1e3)

    # correctness of the maintained superblock: final query vs the numpy
    # oracle over the FINAL store (appended region included). Steps whose
    # windows reach past the final head have no samples: the query side is
    # rate()-NaN there while the oracle's nansum over an all-NaN window
    # collapses to 0.0, so the comparison is restricted to steps at or
    # before the head (where both are finite).
    res, _out = run_query()
    n_appended = ingested[0] // N_SERIES
    ts_full = BASE + np.arange(N_SAMPLES + n_appended, dtype=np.int64) * INTERVAL_MS
    _cpu_ms, cpu_vals = cpu_baseline(ms, ts_full)
    tpu_vals = res.grids[0].values_np()[0]
    n = min(len(tpu_vals), len(cpu_vals))
    step_ts = (np.int64(START_S * 1000)
               + np.arange(n, dtype=np.int64) * int(STEP_S * 1000))
    ok_steps = np.isfinite(cpu_vals[:n]) & (step_ts <= ts_full[-1])
    with np.errstate(invalid="ignore"):
        ok = bool(ok_steps.any()) and bool(np.allclose(
            tpu_vals[:n][ok_steps], cpu_vals[:n][ok_steps], rtol=5e-3
        ))
    import jax

    backend = jax.devices()[0].platform
    ratio = busy_ms / idle_ms
    sys.stderr.write(
        f"idle_mean={idle_ms:.2f}ms busy_mean={busy_ms:.2f}ms "
        f"impact={ratio:.2f}x ingested={ingested[0]} match={ok}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": round(idle_ms / busy_ms, 2),
        "backend": backend,
        "series": N_SERIES,
        "match": bool(ok),
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {"idle_mean": round(idle_ms, 3),
                      "busy_mean": round(busy_ms, 3)},
    }))


def run_benchmark_fused_mesh():
    """Single-device fused vs mesh-sharded fused p50 of the canonical query.

    On the CPU backend this forces an 8-virtual-device mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count, the MULTICHIP dryrun
    contract) — the scaling ratio there measures sharding OVERHEAD (8
    virtual devices time-slice the same cores), so the smoke floor gates
    the sharded p50, not the ratio; on real multi-chip hardware the same
    workload reports the near-linear scaling number. Also asserts the warm
    sharded query stays exactly ONE dispatch and matches the numpy oracle."""
    # force the virtual mesh BEFORE the first jax backend init (same
    # defense as __graft_entry__.dryrun_multichip — shared helper)
    from filodb_tpu.config import force_virtual_devices

    force_virtual_devices(MESH_DEVICES)
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    ms, ts = build_memstore()
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.parallel.mesh import make_mesh

    _enable_compile_cache()
    n_dev = min(MESH_DEVICES, len(jax.devices()))
    single = QueryEngine(ms, "prometheus", PlannerParams())
    sharded = QueryEngine(
        ms, "prometheus", PlannerParams(mesh=make_mesh(jax.devices()[:n_dev]))
    )
    q = "sum(rate(http_requests_total[5m]))"

    def p50_of(engine):
        def run():
            res = engine.query_range(q, START_S, END_S, STEP_S)
            out = [np.asarray(g.values_np()) for g in res.grids]
            return res, out

        t0 = time.perf_counter()
        run()  # stage + compile + cache warm
        warm_s = time.perf_counter() - t0
        times = []
        res = None
        for _ in range(TIMED_RUNS):
            t0 = time.perf_counter()
            res, _out = run()
            times.append(time.perf_counter() - t0)
        return float(np.median(times) * 1e3), res, warm_s

    from filodb_tpu.testkit import kernel_dispatch_total

    single_ms, _res_s, warm_single = p50_of(single)
    sharded_ms, res, warm_sharded = p50_of(sharded)
    before = kernel_dispatch_total()
    res = sharded.query_range(q, START_S, END_S, STEP_S)
    single_dispatch = kernel_dispatch_total() - before == 1
    cpu_ms, cpu_vals = cpu_baseline(ms, ts)
    tpu_vals = res.grids[0].values_np()[0]
    n = min(len(tpu_vals), len(cpu_vals))
    ok = bool(np.allclose(tpu_vals[:n], cpu_vals[:n], rtol=5e-3))
    scaling = single_ms / sharded_ms if sharded_ms > 0 else 0.0
    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"single_p50={single_ms:.2f}ms sharded_p50={sharded_ms:.2f}ms "
        f"({n_dev} devices) scaling={scaling:.2f}x match={ok} "
        f"single_dispatch={single_dispatch}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(sharded_ms, 3),
        "unit": "ms",
        "vs_baseline": round(scaling, 3),
        "backend": backend,
        "devices": n_dev,
        "series": N_SERIES,
        "match": bool(ok and single_dispatch),
        "warmup_s": round(warm_single + warm_sharded, 2),
        "phases_ms": {"single_p50": round(single_ms, 3),
                      "sharded_p50": round(sharded_ms, 3),
                      "scaling_x": round(scaling, 3)},
    }))


def run_benchmark_concurrent_qps():
    """N client threads hammering ONE hot superblock with VARIED dashboard
    queries (windows 2-5m x group-by variants over the same selector — the
    shape the engine-level identical-query single-flight can NOT collapse),
    cross-query batching on vs off. This is the workload the ROADMAP's
    ~222 qps / flat-beyond-16-clients number describes; the dispatch
    scheduler (query/scheduler.py) exists to move it.

    value = batched-mode throughput (qps, HIGHER is better — the smoke
    floor gates it via qps_floor_min); vs_baseline = batched/unbatched
    throughput ratio; phases_ms carries both modes' p50/p99 per-query
    latency and raw qps. match = per-variant batched results agree with
    the unbatched engine (allclose; the batched engine's plans stage an
    aligned superblock range, so counter-correction f32 rounding may
    differ in ulps from the unbatched engine's narrower block)."""
    import threading

    ms, _ts = build_memstore()
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine

    _enable_compile_cache()
    batched = QueryEngine(
        ms, "prometheus",
        PlannerParams(batch_window_ms=QPS_BATCH_WINDOW_MS,
                      batch_max=max(QPS_CLIENTS, 2)),
    )
    unbatched = QueryEngine(ms, "prometheus", PlannerParams())
    # the 16 panels of one dashboard: same selector, varied group-bys (all
    # landing in one pow2 group-count bucket so they coalesce) x varied
    # windows — distinct PromQL strings, so the engine-level identical-query
    # single-flight cannot collapse them; only cross-query batching can
    bys = [" by (zone)", " by (zone,_ns_)", " by (zone,_ws_)",
           " by (zone,_ns_,_ws_)"]
    wins = ["5m", "4m", "3m", "2m"]
    variants = [
        f"sum{bys[i % len(bys)]} "
        f"(rate(http_requests_total[{wins[(i // len(bys)) % len(wins)]}]))"
        for i in range(QPS_CLIENTS)
    ]

    def rows(res):
        return {
            tuple(sorted(l.items())): np.asarray(v)
            for g in res.grids for l, v in zip(g.labels, g.values_np())
        }

    # warmup + parity: every variant once per engine (stage + compile the
    # per-variant programs), then one full-width concurrent batched round
    # so the pow2-padded batched executable is compiled before timing
    ok = True
    for q in variants:
        ru = rows(unbatched.query_range(q, START_S, END_S, STEP_S))
        rb = rows(batched.query_range(q, START_S, END_S, STEP_S))
        if ru.keys() != rb.keys():
            ok = False
            continue
        for k in ru:
            na, nb = np.isnan(ru[k]), np.isnan(rb[k])
            if not (na == nb).all() or not np.allclose(
                ru[k][~na], rb[k][~nb], rtol=5e-3
            ):
                ok = False

    def measure(engine):
        lat: list[list[float]] = [[] for _ in range(QPS_CLIENTS)]
        start_gate = threading.Barrier(QPS_CLIENTS + 1)
        stop_at = [0.0]

        def client(i):
            q = variants[i]
            start_gate.wait()
            while time.perf_counter() < stop_at[0]:
                t0 = time.perf_counter()
                res = engine.query_range(q, START_S, END_S, STEP_S)
                # force materialization: latency must include the device
                # work, not just the async enqueue
                for g in res.grids:
                    np.asarray(g.values_np())
                lat[i].append(time.perf_counter() - t0)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(QPS_CLIENTS)
        ]
        for t in threads:
            t.start()
        stop_at[0] = time.perf_counter() + QPS_DURATION_S
        t_begin = time.perf_counter()
        start_gate.wait()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t_begin
        flat = [x for l in lat for x in l]
        if not flat:
            return 0.0, 0.0, 0.0
        return (
            len(flat) / elapsed,
            float(np.percentile(flat, 50) * 1e3),
            float(np.percentile(flat, 99) * 1e3),
        )

    # pre-compile the pow2 batch widths the run will see (group sizes
    # fluctuate as clients desync; a mid-measurement XLA compile would
    # poison p99 and qps) by running fixed-width concurrent rounds, then
    # one full free-running round
    def width_round(n, offset=0):
        gate = threading.Barrier(n)

        def one(i):
            gate.wait()
            batched.query_range(variants[offset + i], START_S, END_S, STEP_S)

        ths = [threading.Thread(target=one, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()

    for n in (2, 3, 4):
        width_round(min(n, QPS_CLIENTS))
    pre = measure(batched)
    sys.stderr.write(f"batched warm round: {pre[0]:.0f} qps\n")
    un_qps, un_p50, un_p99 = measure(unbatched)
    b_qps, b_p50, b_p99 = measure(batched)
    import jax

    backend = jax.devices()[0].platform
    speedup = b_qps / un_qps if un_qps > 0 else 0.0
    sys.stderr.write(
        f"clients={QPS_CLIENTS} unbatched={un_qps:.0f}qps "
        f"(p50={un_p50:.1f}ms p99={un_p99:.1f}ms) batched={b_qps:.0f}qps "
        f"(p50={b_p50:.1f}ms p99={b_p99:.1f}ms) speedup={speedup:.2f}x "
        f"match={ok}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(b_qps, 1),
        "unit": "qps",
        "vs_baseline": round(speedup, 3),
        "backend": backend,
        "series": N_SERIES,
        "clients": QPS_CLIENTS,
        "match": bool(ok and b_qps > 0),
        "phases_ms": {
            "batched_qps": round(b_qps, 1),
            "unbatched_qps": round(un_qps, 1),
            "batched_p50": round(b_p50, 2),
            "batched_p99": round(b_p99, 2),
            "unbatched_p50": round(un_p50, 2),
            "unbatched_p99": round(un_p99, 2),
        },
    }))


def run_benchmark_mixed_cost_storm():
    """Device-second admission under a mixed-cost tenant storm
    (doc/operations.md "Admission control"): a cheap tenant (demo/App-1,
    64 series, 5m sum(rate)) shares the node with a monster tenant
    (demo/App-2, the full series set, 30m high-cardinality group-by).
    The monster floods; its tight device-second quota must shed it with a
    cost-derived Retry-After while the cheap tenant keeps its throughput.

    value = cheap-tenant qps during the flood / cheap-tenant solo qps
    (retained fraction, HIGHER is better — the smoke floor gates >= 0.8);
    match = cheap tenant saw zero sheds/errors, the monster was admitted
    at least once (it has SOME budget) and shed repeatedly, and every
    shed carried a positive predicted cost and a drain-derived
    Retry-After."""
    import threading

    ms, ts = build_memstore()
    # the cheap tenant's 64 series ride in the same memstore under its own
    # namespace — metering.tenant_of_plan resolves ws/ns from the selector
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import METRIC_TAG, PROM_COUNTER, shard_for
    rng = np.random.default_rng(7)
    for i in range(64):
        tags = {
            METRIC_TAG: "http_requests_total",
            "_ws_": "demo",
            "_ns_": "App-1",
            "instance": f"cheap-host-{i}",
            "zone": f"z{i % 8}",
        }
        shard = shard_for(tags, spread=3, num_shards=N_SHARDS)
        vals = np.cumsum(rng.uniform(0, 10, size=N_SAMPLES)) + 1e9
        ms.shard("prometheus", shard).ingest_series(
            SeriesBatch(PROM_COUNTER, tags, ts, {"count": vals})
        )
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.query.scheduler import (
        AdmissionController, AdmissionRejected,
    )

    _enable_compile_cache()
    cheap_q = ('sum(rate(http_requests_total'
               '{_ws_="demo",_ns_="App-1"}[5m]))')
    monster_q = ('sum by (instance) (rate(http_requests_total'
                 '{_ws_="demo",_ns_="App-2"}[30m]))')
    # cheap tenant: effectively unmetered; monster: ~one full-burst query
    # per flood window, everything past that sheds on predicted cost
    ctl = AdmissionController({
        "demo/App-1": {"rate_device_s": 50.0, "burst_device_s": 50.0},
        "demo/App-2": {"rate_device_s": 0.005, "burst_device_s": 0.05},
    })
    # warm engine (no admission): compiles both shapes and teaches the
    # cost model each fingerprint's realized device-seconds WITHOUT
    # draining the gated buckets, so the flood starts from a full burst
    warm = QueryEngine(ms, "prometheus", PlannerParams())
    gated = QueryEngine(ms, "prometheus", PlannerParams(admission=ctl))
    for _ in range(3):
        warm.query_range(cheap_q, START_S, END_S, STEP_S)
    for _ in range(2):
        warm.query_range(monster_q, START_S, END_S, STEP_S)

    cheap_errors = [0]

    def cheap_phase(duration_s):
        n = [0]
        stop_at = time.perf_counter() + duration_s

        def client():
            while time.perf_counter() < stop_at:
                try:
                    res = gated.query_range(cheap_q, START_S, END_S, STEP_S)
                    for g in res.grids:
                        np.asarray(g.values_np())
                    n[0] += 1
                except Exception:
                    cheap_errors[0] += 1

        t0 = time.perf_counter()
        th = threading.Thread(target=client)
        th.start()
        th.join()
        return n[0] / (time.perf_counter() - t0)

    sheds: list[tuple[float, float, str]] = []
    admits = [0]

    def monster_client(stop_evt):
        while not stop_evt.is_set():
            try:
                gated.query_range(monster_q, START_S, END_S, STEP_S)
                admits[0] += 1
            except AdmissionRejected as e:
                sheds.append((
                    float(getattr(e, "retry_after_s", 0.0)),
                    float(getattr(e, "predicted_cost_s", 0.0)),
                    str(getattr(e, "outcome", "")),
                ))
                time.sleep(0.02)  # the flood ignores Retry-After
            except Exception:
                admits[0] += 0  # engine errors count as neither

    # interleaved solo/flood rounds: container qps drifts between phases,
    # so a single before/after pair is noise-bound — medians over
    # alternating rounds compare like with like (the fused_jitter idiom)
    rounds = 3
    dur = max(QPS_DURATION_S / rounds, 1.0)
    solo_rounds, flood_rounds = [], []
    for _ in range(rounds):
        solo_rounds.append(cheap_phase(dur))
        stop_evt = threading.Event()
        monsters = [
            threading.Thread(target=monster_client, args=(stop_evt,))
            for _ in range(2)
        ]
        for t in monsters:
            t.start()
        flood_rounds.append(cheap_phase(dur))
        stop_evt.set()
        for t in monsters:
            t.join()

    solo_qps = float(np.median(solo_rounds))
    flood_qps = float(np.median(flood_rounds))
    retained = flood_qps / solo_qps if solo_qps > 0 else 0.0
    cost_derived = bool(sheds) and all(
        r > 0 and c > 0 and o == "shed_rate" for r, c, o in sheds
    )
    ok = (
        cheap_errors[0] == 0 and admits[0] >= 1 and len(sheds) > 0
        and cost_derived and retained > 0
    )
    import jax

    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"solo={solo_qps:.0f}qps flood={flood_qps:.0f}qps "
        f"retained={retained:.2f} monster_admits={admits[0]} "
        f"sheds={len(sheds)} cost_derived={cost_derived} "
        f"cheap_errors={cheap_errors[0]}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(retained, 3),
        "unit": "ratio",
        "vs_baseline": round(retained, 3),
        "backend": backend,
        "series": N_SERIES,
        "match": ok,
        "phases_ms": {
            "solo_qps": round(solo_qps, 1),
            "flood_qps": round(flood_qps, 1),
            "monster_admits": admits[0],
            "monster_sheds": len(sheds),
            "shed_retry_after_max_s": round(
                max((r for r, _, _ in sheds), default=0.0), 3),
            "shed_predicted_cost_max_s": round(
                max((c for _, c, _ in sheds), default=0.0), 4),
        },
    }))


def run_benchmark_standing_refresh():
    """Standing-query live-edge refresh cost: the delta path vs a forced
    full re-dispatch of the same grid, under a live ingest stream
    (doc/operations.md "Standing queries & recording rules").

    A registered standing query refreshes through the delta path
    (aligned pinned staging range -> the ONE superblock entry extends in
    place under the append; suffix-only re-dispatch + retained-partial
    splice) while a 1-sample/series/100ms stream lands at the live edge
    (the ingest_impact cadence). The baseline is what the same dashboard
    panel pays TODAY without the standing engine: a plain query_range
    poll of the same sliding grid, whose moving end resolves to a NEW
    superblock cache key every refresh — full restage + full-grid
    dispatch (cross-query batching off, the default). value =
    cold_poll_p50 / standing_refresh_p50 (unit "x", HIGHER is better).
    match = after the stream quiesces, the delta-maintained partials are
    BIT-EQUAL to a forced full re-evaluation of the same grid AND the
    delta path actually ran (falling back to full re-dispatch per refresh
    collapses the ratio toward the warm-full line and flips match)."""
    import threading

    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.core.schemas import METRIC_TAG, PROM_COUNTER
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.standing import StandingEngine

    ms, _ts = build_memstore()
    _enable_compile_cache()
    engine = QueryEngine(ms, "prometheus", PlannerParams())
    q = "sum by (zone) (rate(http_requests_total[5m]))"
    step_ms = 15_000
    span_ms = 5_400_000  # the "last 90m" dashboard panel (J = 361 steps)
    batches = [0]
    edge_clock = lambda: (  # noqa: E731 — tracks the ingest head
        BASE + (N_SAMPLES + batches[0]) * INTERVAL_MS + 5_000
    ) / 1e3
    se = StandingEngine(engine, {"default_span_ms": span_ms},
                        clock=edge_clock)
    sq = se.register(q, step_ms)
    twin = se.register(q, step_ms)
    assert sq.mode == "delta", sq.mode_reason
    t0 = time.perf_counter()
    se.refresh(sq)  # compile + stage + superblock warm
    se.refresh(twin, force_full=True)
    warmup_s = time.perf_counter() - t0

    tags_list = [
        {METRIC_TAG: "http_requests_total", "_ws_": "demo", "_ns_": "App-2",
         "instance": f"host-{i}", "zone": f"z{i % 8}"}
        for i in range(N_SERIES)
    ]
    stop = threading.Event()

    def ingester():
        while not stop.is_set() and batches[0] < MAX_APPEND_BATCHES:
            b = batches[0]
            t = BASE + (N_SAMPLES + b) * INTERVAL_MS
            vals = np.full(N_SERIES, 1e9 + 10.0 * (N_SAMPLES + b + 1))
            ms.ingest_routed("prometheus", RecordBatch(
                PROM_COUNTER, np.full(N_SERIES, t, np.int64),
                {"count": vals}, tags_list,
            ), spread=3)
            batches[0] = b + 1
            stop.wait(0.1)

    # the cold-poll baseline warms its jit/compile state once; its
    # superblock can never stay warm (that is the point being measured)
    engine.query_range(q, (BASE + 600_000) / 1e3,
                       (BASE + 600_000 + span_ms) / 1e3, step_ms / 1e3)
    th = threading.Thread(target=ingester)
    th.start()
    delta_s, cold_s = [], []

    def paced(measure, out, last_b):
        """One measurement per fresh append, so every round absorbs real
        live-edge work (never a free already-warm repeat)."""
        for _ in range(TIMED_RUNS):
            deadline = time.time() + 2.0
            while batches[0] == last_b and time.time() < deadline:
                time.sleep(0.005)
            last_b = batches[0]
            t0 = time.perf_counter()
            measure()
            out.append(time.perf_counter() - t0)
        return last_b

    try:
        # phase A: the standing engine serving the panel alone (extension
        # + suffix dispatch + render per append)
        last_b = paced(lambda: se.refresh(sq), delta_s, batches[0])
        # phase B: the same panel served the pre-standing way, alone under
        # the same stream — each poll's moving end is a new superblock
        # cache key, so every refresh restages + dispatches the full grid
        paced(
            lambda: engine.query_range(
                q, edge_clock() - span_ms / 1e3, edge_clock(),
                step_ms / 1e3,
            ),
            cold_s, last_b,
        )
    finally:
        stop.set()
        th.join()
    # quiesced parity: the delta-maintained partials vs a forced full
    # re-evaluation of the same grid over the same aligned superblock
    se.refresh(sq)
    t0 = time.perf_counter()
    se.refresh(twin, force_full=True)
    warmfull_ms = (time.perf_counter() - t0) * 1e3
    biteq = (sq.grid_end_ms == twin.grid_end_ms
             and sq.labels == twin.labels
             and sq.retained.tobytes() == twin.retained.tobytes())
    delta_p50 = float(np.median(delta_s) * 1e3)
    cold_p50 = float(np.median(cold_s) * 1e3)
    ratio = cold_p50 / delta_p50 if delta_p50 > 0 else 0.0
    ok = bool(biteq) and sq.stats["delta"] > 0 and sq.stats["errors"] == 0
    import jax

    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"standing_p50={delta_p50:.2f}ms cold_poll_p50={cold_p50:.2f}ms "
        f"warmfull={warmfull_ms:.2f}ms speedup={ratio:.2f}x "
        f"delta={sq.stats['delta']} retained={sq.stats['retained']} "
        f"reset={sq.stats['reset']} biteq={biteq}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": round(ratio, 2),
        "backend": backend,
        "series": N_SERIES,
        "match": ok,
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {
            "standing_p50": round(delta_p50, 3),
            "cold_poll_p50": round(cold_p50, 3),
            "warm_full_ms": round(warmfull_ms, 3),
            "delta_refreshes": sq.stats["delta"],
            "retained_refreshes": sq.stats["retained"],
            "steps_computed": sq.stats["steps_computed"],
            "steps_retained": sq.stats["steps_retained"],
        },
    }))


def run_benchmark_index_regex():
    """General anchored-regex selector resolution at 1M part keys on the
    vectorized posting-bitmap index (doc/perf.md "Vectorized part-key
    index") — the workload the set-arithmetic index measured at ~6.8k
    lookups/s (BENCH_LOCAL index_regex_lookups_1000k; ISSUE 14 bar: >=5x).

    Probe shape matches benchmarks/run.py bench_index_1m: the 5-tag
    schema, general anchored regexes with a literal prefix + tail class
    over the 10k-value host dictionary, full-retention range, a 64-pattern
    Grafana-storm pool (repeated selectors — the per-label match cache is
    part of the path under test, invalidated by any ingest to the label).
    match = every pool pattern's id set identical to the retained
    set-based oracle, plus eq + literal-alt + negative spot probes."""
    from filodb_tpu.core.filters import ColumnFilter, equals, regex
    from filodb_tpu.memstore.index import PartKeyIndex, SetBasedPartKeyIndex

    n = N_SERIES
    t0 = time.perf_counter()
    idx = PartKeyIndex()
    oracle = SetBasedPartKeyIndex()
    for i in range(n):
        tags = {
            "_metric_": f"metric_{i % 1000}", "host": f"h{i % 10_000}",
            "dc": f"dc{i % 10}", "_ws_": "demo", "_ns_": f"ns{i % 20}",
        }
        idx.add_partkey(i, tags, 0)
        oracle.add_partkey(i, tags, 0)
    warmup_s = time.perf_counter() - t0
    sys.stderr.write(f"index build 2x{n}: {warmup_s:.1f}s\n")

    pool = [[regex("host", f"h1{i:02d}[0-9]?")] for i in range(64)]
    probes = pool + [
        [equals("_metric_", "metric_5")],
        [regex("host", "h123.*")],
        [regex("host", "h1|h2|h33")],
        [equals("_ws_", "demo"), regex("host", "h77[0-9]?")],
        [ColumnFilter("dc", "!=", "dc3"), equals("_ns_", "ns7")],
    ]
    ok = all(
        idx.part_ids_from_filters(f, 0, 2**62).tolist()
        == oracle.part_ids_from_filters(f, 0, 2**62).tolist()
        for f in probes
    )

    for f in pool:  # warm: dictionary pass + match-cache fill
        idx.part_ids_from_filters(f, 0, 2**62)
    reps = 2000
    t0 = time.perf_counter()
    for k in range(reps):
        idx.part_ids_from_filters(pool[k % len(pool)], 0, 2**62)
    dt = time.perf_counter() - t0
    rate = reps / dt

    # secondary visibility: eq + cold-cache (first-touch) rates
    f_eq = [equals("_metric_", "metric_5")]
    idx.part_ids_from_filters(f_eq, 0, 2**62)
    t0 = time.perf_counter()
    for _ in range(reps):
        idx.part_ids_from_filters(f_eq, 0, 2**62)
    eq_rate = reps / (time.perf_counter() - t0)
    cold = [[regex("host", f"h2{i:02d}[0-9]?")] for i in range(64)]
    t0 = time.perf_counter()
    for f in cold:
        idx.part_ids_from_filters(f, 0, 2**62)
    cold_rate = len(cold) / (time.perf_counter() - t0)

    sys.stderr.write(
        f"regex warm={rate:.0f}/s cold={cold_rate:.0f}/s eq={eq_rate:.0f}/s "
        f"match={ok}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(rate, 1),
        "unit": "lookups/s",
        # vs the recorded set-arithmetic baseline (BENCH_LOCAL 6818.8/s)
        "vs_baseline": round(rate / 6818.8, 2),
        "backend": "host",
        "series": n,
        "match": bool(ok),
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {
            "eq_lookups_per_s": round(eq_rate, 1),
            "cold_regex_per_s": round(cold_rate, 1),
        },
    }))


def run_benchmark_query_hicard():
    """End-to-end hicard query throughput with the bitmap index in the
    selector path: 8000 series (4 tenants x 2000), 2000 queried —
    benchmarks/run.py bench_query_hicard's shape (recorded ~98 qps on the
    set-based index at PR 13; ISSUE 14 bar: >=2x). match = the bitmap-index
    engine's matrix is IDENTICAL (bit-equal, NaNs aligned) to a second
    engine over the same data with index_backend="set" — the new index in
    the path must not change a single sample."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.memstore.shard import StoreConfig
    from filodb_tpu.testkit import counter_batch

    _enable_compile_cache()

    def build(backend: str):
        ms = TimeSeriesMemStore(StoreConfig(index_backend=backend))
        ms.setup(Dataset("prometheus"), range(8))
        for ns in range(4):
            ms.ingest_routed(
                "prometheus",
                counter_batch(n_series=2000, n_samples=120, start_ms=BASE,
                              ns=f"App-{ns}"),
                spread=3,
            )
        return QueryEngine(ms, "prometheus")

    t0 = time.perf_counter()
    engine = build("python")
    engine_set = build("set")
    warmup_s = time.perf_counter() - t0
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = 'sum(rate(http_requests_total{_ns_="App-1"}[5m]))'

    def run(eng):
        res = eng.query_range(q, start, end, 60)
        return np.asarray(res.grids[0].values_np())

    got = run(engine)
    want = run(engine_set)
    ok = got.shape == want.shape and bool(
        np.array_equal(got, want, equal_nan=True)
    )

    times = []
    for _ in range(max(TIMED_RUNS, 10)):
        t0 = time.perf_counter()
        run(engine)
        times.append(time.perf_counter() - t0)
    p50_ms = float(np.median(times) * 1e3)
    qps = 1e3 / p50_ms
    import jax

    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"hicard p50={p50_ms:.2f}ms qps={qps:.1f} match={ok}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(qps, 1),
        "unit": "qps",
        # vs the recorded pre-bitmap measurement (BENCH_LOCAL ~98 qps)
        "vs_baseline": round(qps / 98.0, 2),
        "backend": backend,
        "series": 8000,
        "match": bool(ok),
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {"p50_ms": round(p50_ms, 3)},
    }))


def run_benchmark_long_range_quantile():
    """Sketch rollup tier on the long-range dashboard shape (doc/perf.md
    "Sketch rollup tier"): 30-day span at 1h step, `quantile_over_time`
    over gauges + `histogram_quantile` over classic bucket counters.

    One memstore, two engines: the rollup engine substitutes the
    per-period summary blocks (O(periods) per query — 719 rollup periods
    here), the raw engine reads every sample (O(raw) — 43,200 samples per
    series). value = rollup-path p50 of the quantile_over_time query
    (ms, LOWER is better); vs_baseline = raw_p50 / rollup_p50. match
    requires ALL of: both rollup-engine queries recorded querylog
    path=rollup and both raw-engine queries did not; every
    quantile_over_time cell within the sketch's 2^(1/32)-1 relative
    error bound of the numpy quantile bracket over the SAME
    period-mapped windows; histogram_quantile parity vs the raw path
    (identical NaN masks, values within the documented rate-boundary
    tolerance); and raw_p50 >= 10x rollup_p50 (the ISSUE acceptance
    bar — losing the substitution flips match before it shows as
    latency)."""
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import (
        Dataset, GAUGE, METRIC_TAG, PROM_COUNTER, shard_for,
    )
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.downsample.rollup import RollupManager
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.memstore.shard import StoreConfig
    from filodb_tpu.obs.querylog import QUERY_LOG
    from filodb_tpu.query import logical as L
    from filodb_tpu.query.promql import query_range_to_logical_plan

    RES = 3_600_000  # the 1h rollup resolution under test
    DAYS, IVL = 30, 60_000
    T = DAYS * 24 * 60  # minute samples per series
    S_GAUGE, S_INST = 8, 16
    LES = ["0.1", "0.25", "0.5", "1", "2.5", "+Inf"]
    # hour-aligned data origin (BASE itself is NOT aligned: BASE % 1h =
    # 1.6e6 ms) — rollup eligibility requires start % resolution == 0
    align0 = BASE + (RES - BASE % RES)
    ts = align0 + np.arange(T, dtype=np.int64) * IVL
    rng = np.random.default_rng(42)
    ms = TimeSeriesMemStore(StoreConfig(max_chunk_size=T))
    ms.setup(Dataset("prometheus"), range(N_SHARDS))
    t0 = time.time()
    gvals = 100.0 * np.exp(0.4 * rng.standard_normal((S_GAUGE, T)))
    for i in range(S_GAUGE):
        tags = {METRIC_TAG: "disk_usage", "_ws_": "demo", "_ns_": "App-2",
                "instance": f"host-{i}"}
        # single-shard placement for the gauge metric: the raw baseline's
        # per-series tree walk costs ~140ms PER WINDOW PER SHARD-GRID on
        # the 1-cpu bench box (719 windows x 4 shards would blow the
        # bench-smoke budget on its own); placement is an ingest-routing
        # detail, not query semantics, and the rollup path is
        # placement-independent either way
        ms.shard("prometheus", 0).ingest_series(
            SeriesBatch(GAUGE, tags, ts, {"value": gvals[i]}))
    # classic cumulative bucket counters: le-cumulative, time-cumulative
    incr = rng.poisson(3.0, size=(S_INST, T, len(LES))).astype(np.float64)
    bvals = np.cumsum(np.cumsum(incr, axis=2), axis=1)
    for i in range(S_INST):
        for b, le in enumerate(LES):
            tags = {METRIC_TAG: "http_request_duration_seconds_bucket",
                    "_ws_": "demo", "_ns_": "App-2",
                    "instance": f"host-{i}", "le": le}
            ms.shard("prometheus",
                     shard_for(tags, spread=3, num_shards=N_SHARDS)
                     ).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, ts, {"count": bvals[i, :, b]}))
    sys.stderr.write(
        f"ingest: {S_GAUGE} gauge + {S_INST * len(LES)} bucket series x "
        f"{T} samples in {time.time() - t0:.1f}s\n"
    )
    _enable_compile_cache()
    q1 = "quantile_over_time(0.99, disk_usage[1h])"
    q2 = ("histogram_quantile(0.99, sum by (le) "
          "(rate(http_request_duration_seconds_bucket[1h])))")
    # start leaves TWO lead periods (rate needs one before the window)
    start_s = (align0 + 2 * RES) / 1e3
    end_s = (align0 + DAYS * 24 * RES) / 1e3
    step_s = RES / 1e3
    rollups = RollupManager(ms)
    t0 = time.perf_counter()
    for q in (q1, q2):
        plan = query_range_to_logical_plan(q, start_s, end_s, step_s)
        node = plan
        while isinstance(node, (L.Aggregate, L.ApplyInstantFunction)):
            node = node.inner
        rollups.ensure("prometheus", node.raw.filters, RES, build=True)
    fold_s = time.perf_counter() - t0
    eng_ru = QueryEngine(ms, "prometheus", PlannerParams(rollups=rollups))
    eng_raw = QueryEngine(ms, "prometheus", PlannerParams())

    def timed(eng, q, runs):
        # latency = time to MATERIALIZED values: result grids hold lazy
        # device arrays, so stopping the clock at query_range() return
        # would credit the raw path with work it merely enqueued (the
        # async backlog then stalls whoever syncs next)
        out, paths = [], []
        for _ in range(runs):
            t0 = time.perf_counter()
            res = eng.query_range(q, start_s, end_s, step_s)
            for g in res.grids:
                np.asarray(g.values_np())
            out.append(time.perf_counter() - t0)
            paths.append(QUERY_LOG.entries(1)[0].get("path"))
        return res, float(np.median(out) * 1e3), paths, out

    t0 = time.perf_counter()
    for eng, q in ((eng_ru, q1), (eng_ru, q2), (eng_raw, q2)):
        # compile + stage warmup; raw q1 (the O(raw-samples) tree path,
        # ~minutes per pass on the 1-cpu bench box) warms inside its own
        # timed runs instead — its first-run compile share is reported
        # separately via the min/median split below
        res = eng.query_range(q, start_s, end_s, step_s)
        for g in res.grids:
            np.asarray(g.values_np())
    warmup_s = time.perf_counter() - t0
    res1_ru, ru1_ms, p1_ru, _ = timed(eng_ru, q1, TIMED_RUNS)
    res2_ru, ru2_ms, p2_ru, _ = timed(eng_ru, q2, TIMED_RUNS)
    # ONE raw q1 pass: the O(raw-samples) tree walk costs minutes per run
    # and re-running it would not move the needle on a >=10x acceptance
    # bar (warm runs measured within ~15% of cold — the cost is per-window
    # dispatch, not compile)
    res1_raw, _, p1_raw, t1_raw = timed(eng_raw, q1, 1)
    raw1_ms = float(min(t1_raw) * 1e3)
    res2_raw, raw2_ms, p2_raw, _ = timed(eng_raw, q2, min(TIMED_RUNS, 3))
    paths_ok = (all(p == "rollup" for p in p1_ru + p2_ru)
                and all(p != "rollup" for p in p1_raw + p2_raw))
    # quantile_over_time oracle over the SAME period-mapped windows: with
    # window == step == resolution every output step j covers exactly the
    # samples of hour j+1, so the sketch's bin bound applies cleanly
    hours = gvals.reshape(S_GAUGE, DAYS * 24, 60)
    lo = np.quantile(hours, 0.99, axis=2, method="lower")[:, 1:]
    hi = np.quantile(hours, 0.99, axis=2, method="higher")[:, 1:]
    bound = 2.0 ** (1.0 / 32.0) - 1.0 + 1e-6
    g1 = res1_ru.grids[0]
    est = np.asarray(g1.values_np(), dtype=np.float64)
    order = [int(lbl["instance"].split("-")[1]) for lbl in g1.labels]
    lo, hi = lo[order], hi[order]
    q_ok = bool(est.shape == lo.shape and np.all(
        (est >= lo * (1 - bound)) & (est <= hi * (1 + bound))
    ))
    # histogram_quantile parity vs the raw path: rollup rate is a period-
    # boundary difference vs PromQL's window-edge extrapolation — the
    # extrapolation factor cancels in the quantile's rank ratio, leaving
    # O(interval/window) boundary effects
    h_ru = np.asarray(res2_ru.grids[0].values_np(), dtype=np.float64)
    h_raw = np.asarray(res2_raw.grids[0].values_np(), dtype=np.float64)
    with np.errstate(invalid="ignore"):
        h_ok = bool(
            h_ru.shape == h_raw.shape
            and np.array_equal(np.isnan(h_ru), np.isnan(h_raw))
            and np.allclose(h_ru, h_raw, rtol=0.06, equal_nan=True)
        )
    speedup = raw1_ms / ru1_ms if ru1_ms > 0 else 0.0
    ok = paths_ok and q_ok and h_ok and speedup >= 10.0
    import jax

    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"rollup_p50={ru1_ms:.2f}ms raw_p50={raw1_ms:.2f}ms "
        f"speedup={speedup:.1f}x hist rollup={ru2_ms:.2f}ms "
        f"raw={raw2_ms:.2f}ms paths_ok={paths_ok} quantile_ok={q_ok} "
        f"hist_ok={h_ok}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(ru1_ms, 3),
        "unit": "ms",
        "vs_baseline": round(speedup, 2),
        "backend": backend,
        "series": S_GAUGE + S_INST * len(LES),
        "match": bool(ok),
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {
            "rollup_quantile_p50": round(ru1_ms, 3),
            "raw_quantile_p50": round(raw1_ms, 3),
            "rollup_hist_p50": round(ru2_ms, 3),
            "raw_hist_p50": round(raw2_ms, 3),
            "fold_s": round(fold_s, 2),
        },
    }))


def run_benchmark_failover_storm():
    """Replicated shard plane under a node kill (doc/robustness.md
    "Replicated shard plane"): an RF=2 in-process replica cluster at
    N_SERIES series, QPS_CLIENTS client threads looping the canonical
    dashboard aggregation through the front coordinator's ReplicaRouter
    (one shard-pinned gRPC leg per shard, siblings attached). Three
    measured windows: ``before`` (both nodes up), ``during`` (one node
    killed mid-window — in-flight legs re-pin to their sibling replica),
    ``after`` (steady state on the survivor).

    value = during-kill throughput (qps, HIGHER is better — the smoke
    floor gates it via qps_floor_min); vs_baseline = during/before qps
    ratio; phases_ms carries all three windows' qps + p50/p99. match =
    ZERO failed queries across all windows with partial results OFF and
    every result BIT-equal to the pre-kill baseline (per-shard legs keep
    the merge tree invariant, so failover may not change a single bit)."""
    import threading

    from filodb_tpu.testkit import machine_metrics, replica_cluster

    n_samples = 360  # 1h @ 10s; RF=2 doubles resident data
    batch = machine_metrics(n_series=N_SERIES, n_samples=n_samples)
    c = replica_cluster(batch=batch, n_shards=N_SHARDS)
    promql = "sum(heap_usage0)"
    q_start = BASE / 1000.0
    q_end = (BASE + (n_samples - 1) * INTERVAL_MS) / 1000.0

    def rows(res):
        return sorted(
            (tuple(sorted(l.items())), np.asarray(v).tobytes())
            for g in res.grids for l, v in zip(g.labels, g.values_np())
        )

    try:
        assert c.engine.planner.params.allow_partial_results is False
        baseline = rows(c.engine.query_range(promql, q_start, q_end, STEP_S))
        failures = [0]
        mismatches = [0]

        def measure(kill: str | None = None):
            lat: list[list[float]] = [[] for _ in range(QPS_CLIENTS)]
            gate = threading.Barrier(QPS_CLIENTS + 1)
            stop_at = [0.0]

            def client(i):
                gate.wait()
                while time.perf_counter() < stop_at[0]:
                    t0 = time.perf_counter()
                    try:
                        res = c.engine.query_range(promql, q_start, q_end,
                                                   STEP_S)
                    except Exception:
                        failures[0] += 1
                        continue
                    lat[i].append(time.perf_counter() - t0)
                    if rows(res) != baseline:
                        mismatches[0] += 1

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(QPS_CLIENTS)]
            for t in threads:
                t.start()
            stop_at[0] = time.perf_counter() + QPS_DURATION_S
            t_begin = time.perf_counter()
            gate.wait()
            if kill is not None:
                # the kill lands mid-window, under in-flight queries
                time.sleep(QPS_DURATION_S / 3.0)
                c.kill(kill)
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_begin
            flat = [x for l in lat for x in l]
            if not flat:
                return 0.0, 0.0, 0.0
            return (
                len(flat) / elapsed,
                float(np.percentile(flat, 50) * 1e3),
                float(np.percentile(flat, 99) * 1e3),
            )

        b_qps, b_p50, b_p99 = measure()
        d_qps, d_p50, d_p99 = measure(kill="node-0")
        a_qps, a_p50, a_p99 = measure()
    finally:
        c.stop()
    import jax

    backend = jax.devices()[0].platform
    ok = failures[0] == 0 and mismatches[0] == 0 and d_qps > 0
    sys.stderr.write(
        f"clients={QPS_CLIENTS} before={b_qps:.1f}qps (p99={b_p99:.1f}ms) "
        f"during-kill={d_qps:.1f}qps (p99={d_p99:.1f}ms) "
        f"after={a_qps:.1f}qps (p99={a_p99:.1f}ms) "
        f"failures={failures[0]} mismatches={mismatches[0]} match={ok}\n"
    )
    print(json.dumps({
        "metric": METRIC,
        "value": round(d_qps, 1),
        "unit": "qps",
        "vs_baseline": round(d_qps / b_qps, 3) if b_qps > 0 else 0.0,
        "backend": backend,
        "series": N_SERIES,
        "clients": QPS_CLIENTS,
        "match": bool(ok),
        "phases_ms": {
            "before_qps": round(b_qps, 1),
            "during_qps": round(d_qps, 1),
            "after_qps": round(a_qps, 1),
            "before_p50": round(b_p50, 2),
            "before_p99": round(b_p99, 2),
            "during_p50": round(d_p50, 2),
            "during_p99": round(d_p99, 2),
            "after_p50": round(a_p50, 2),
            "after_p99": round(a_p99, 2),
        },
    }))


def run_benchmark_render_2m():
    """Result-plane streaming render (doc/perf.md "Result plane"): a ~2M
    sample per-series matrix (rate() without aggregation at native 10s
    step) served over live HTTP through the chunked-streaming edge —
    stream_matrix pulls device blocks through the double-buffered D2H
    prefetcher while earlier blocks encode and hit the socket.

    value = end-to-end body throughput in Msamples/s (HIGHER is better;
    qps_floor_min gates it). phases_ms carries first-byte latency (must
    land well before the body completes — the streaming claim), total
    body wall, and the encoder's prefetch-stall count for the measured
    runs (dispatch-stall ~0 when D2H keeps ahead of encode). match =
    the streamed body's data.result is IDENTICAL (exact decimal strings)
    to an in-process buffered render of the same engine result, AND the
    warm CANONICAL query (fused sum(rate(...))) over the same data stays
    exactly ONE kernel dispatch with the streaming edge on — the
    prefetcher's per-block device slicing must not show up as dispatches.
    (The 2M per-series matrix itself legitimately dispatches per shard —
    its per-query count rides phases_ms for the record.)"""
    import http.client
    import urllib.parse

    from filodb_tpu.api import promjson as PJ
    from filodb_tpu.api.http import serve_background
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.metrics import REGISTRY
    from filodb_tpu.testkit import kernel_dispatch_total

    def stall_total() -> float:
        total = 0.0
        with REGISTRY._lock:
            for (name, _lbls), m in REGISTRY._metrics.items():
                if name == "filodb_render_stream_stalls":
                    total += m.value
        return total

    ms, _ts = build_memstore()
    _enable_compile_cache()
    engine = QueryEngine(ms, "prometheus", PlannerParams())
    srv, port = serve_background(engine)
    step_s = INTERVAL_MS / 1000.0  # native resolution: per-series matrix
    q = urllib.parse.quote("rate(http_requests_total[5m])")
    path = (f"/api/v1/query_range?query={q}"
            f"&start={START_S}&end={END_S}&step={step_s}")

    def fetch():
        """One streamed request; returns (body, first_byte_s, total_s)."""
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        t0 = time.perf_counter()
        conn.request("GET", path, headers={"Accept-Encoding": "identity"})
        r = conn.getresponse()
        first = r.read(1)
        t_first = time.perf_counter() - t0
        body = first + r.read()
        t_total = time.perf_counter() - t0
        chunked = r.getheader("Transfer-Encoding") == "chunked"
        conn.close()
        return body, t_first, t_total, chunked

    t0 = time.perf_counter()
    body, _, _, chunked0 = fetch()  # compile + stage + cache warm
    warmup_s = time.perf_counter() - t0
    n_samples = sum(len(s["values"])
                    for s in json.loads(body)["data"]["result"])
    sys.stderr.write(
        f"warmup {warmup_s:.1f}s, body {len(body) / 1e6:.1f}MB, "
        f"{n_samples / 1e6:.2f}M samples, chunked={chunked0}\n")
    before_dispatch = kernel_dispatch_total()
    before_stalls = stall_total()
    firsts, totals = [], []
    for _ in range(TIMED_RUNS):
        body, t_first, t_total, _ck = fetch()
        firsts.append(t_first)
        totals.append(t_total)
    warm_dispatches = kernel_dispatch_total() - before_dispatch
    stalls = stall_total() - before_stalls
    # canonical-query invariant with the streaming edge enabled: warm
    # fused sum(rate(...)) stays exactly ONE dispatch
    canon = urllib.parse.quote("sum(rate(http_requests_total[5m]))")
    canon_path = (f"/api/v1/query_range?query={canon}"
                  f"&start={START_S}&end={END_S}&step={STEP_S}")
    for _ in range(2):  # compile + stage warm
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        conn.request("GET", canon_path)
        conn.getresponse().read()
        conn.close()
    before_canon = kernel_dispatch_total()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
    conn.request("GET", canon_path)
    conn.getresponse().read()
    conn.close()
    single = kernel_dispatch_total() - before_canon == 1
    # oracle: buffered in-process render of the same engine result — the
    # streamed body's payload must be exactly it (same decimal strings)
    res = engine.query_range("rate(http_requests_total[5m])", START_S, END_S,
                             step_s)
    oracle = json.loads(b"".join(PJ.stream_matrix(res)))["data"]["result"]
    got = json.loads(body)["data"]["result"]
    key = lambda s: json.dumps(s["metric"], sort_keys=True)  # noqa: E731
    payload_eq = ({key(s): s["values"] for s in got}
                  == {key(s): s["values"] for s in oracle})
    streamed = chunked0 and float(np.median(firsts)) < float(
        np.median(totals)) / 2.0
    srv.shutdown()
    p50_total = float(np.median(totals))
    msps = n_samples / p50_total / 1e6
    ok = payload_eq and single and streamed
    import jax

    backend = jax.devices()[0].platform
    sys.stderr.write(
        f"render_2m: {msps:.2f} Msamples/s first_byte_p50="
        f"{np.median(firsts) * 1e3:.1f}ms total_p50={p50_total * 1e3:.0f}ms "
        f"stalls={stalls:.0f} matrix_dispatches={warm_dispatches}/"
        f"{len(totals)} canonical_single_dispatch={single} "
        f"payload_eq={payload_eq} streamed={streamed}\n")
    print(json.dumps({
        "metric": METRIC,
        "value": round(msps, 3),
        "unit": "Msamples/s",
        "backend": backend,
        "series": N_SERIES,
        "match": bool(ok),
        "warmup_s": round(warmup_s, 2),
        "phases_ms": {
            "first_byte_p50": round(float(np.median(firsts)) * 1e3, 2),
            "total_p50": round(p50_total * 1e3, 2),
            "stream_stalls": round(stalls, 1),
            "samples_m": round(n_samples / 1e6, 3),
            "matrix_dispatches_per_query": round(warm_dispatches / max(len(totals), 1), 1),
        },
    }))


def run_benchmark():
    if WORKLOAD == "render_2m":
        return run_benchmark_render_2m()
    if WORKLOAD == "failover_storm":
        return run_benchmark_failover_storm()
    if WORKLOAD == "long_range_quantile":
        return run_benchmark_long_range_quantile()
    if WORKLOAD == "standing_refresh":
        return run_benchmark_standing_refresh()
    if WORKLOAD == "ingest_impact":
        return run_benchmark_ingest_impact()
    if WORKLOAD == "concurrent_qps":
        return run_benchmark_concurrent_qps()
    if WORKLOAD == "mixed_cost_storm":
        return run_benchmark_mixed_cost_storm()
    if WORKLOAD == "fused_mesh":
        return run_benchmark_fused_mesh()
    if WORKLOAD == "fused_jitter":
        return run_benchmark_fused_jitter()
    if WORKLOAD == "index_regex":
        return run_benchmark_index_regex()
    if WORKLOAD == "query_hicard":
        return run_benchmark_query_hicard()
    if WORKLOAD == "hist_quantile":
        ms, ts = build_memstore_hist()
    else:
        ms, ts = build_memstore()
    tpu_ms, tpu_vals, res, warmup_s, phases = tpu_query(ms)
    if WORKLOAD == "hist_quantile":
        cpu_ms, cpu_vals = cpu_baseline_hist(ms, ts)
    else:
        cpu_ms, cpu_vals = cpu_baseline(ms, ts)
    # cross-check: TPU result must match the CPU oracle. Only hist_quantile
    # legitimately produces aligned NaNs (quantile of an empty window); for
    # the scalar workload any NaN stays a mismatch, as before
    n = min(len(tpu_vals), len(cpu_vals))
    with np.errstate(invalid="ignore"):
        ok = np.allclose(tpu_vals[:n], cpu_vals[:n], rtol=5e-3,
                         equal_nan=WORKLOAD == "hist_quantile")
    import jax

    backend = jax.devices()[0].platform  # honest label: "cpu" on fallback
    sys.stderr.write(
        f"{backend}_p50={tpu_ms:.2f}ms numpy_p50={cpu_ms:.2f}ms match={ok} "
        f"series/sec={N_SERIES / (tpu_ms / 1e3):.3g}\n"
    )
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(tpu_ms, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / tpu_ms, 2),
                "backend": backend,
                "series": N_SERIES,
                "match": bool(ok),
                "warmup_s": round(warmup_s, 2),
                "phases_ms": phases,
            }
        )
    )


def _dump_kernel_snapshot() -> None:
    """Write the worker's kernel-observatory snapshot (obs/kernels.py) to
    FILODB_KERNEL_SNAPSHOT when set — the attestation harness
    (tools/attest.py) collects these to PROVE which executables actually
    compiled/dispatched during each floor workload (fused paths served,
    which fallbacks fired) instead of trusting latency numbers alone."""
    path = os.environ.get("FILODB_KERNEL_SNAPSHOT")
    if not path:
        return
    try:
        from filodb_tpu.metrics import REGISTRY
        from filodb_tpu.obs.kernels import KERNELS

        snap = {
            "totals": KERNELS.totals(),
            "kernels": KERNELS.snapshot(limit=64),
            "counters": REGISTRY.counter_samples(
                "filodb_fused_fallback", "filodb_compile_cache_hits",
                "filodb_compile_cache_misses", "filodb_xla_recompile_storms",
            ),
        }
        with open(path, "w") as f:
            json.dump(snap, f)
    except Exception as e:  # noqa: BLE001 — the snapshot must not fail a bench
        sys.stderr.write(f"kernel snapshot failed: {e}\n")


# one probe per process: the verdict is cached so a wedged plugin costs ONE
# 60s child timeout instead of ~20 spammed "probe timed out" lines per run
# (the watchdog loop used to re-probe for its whole budget). A wedged
# backend does not un-wedge within a process's lifetime; a fresh bench run
# (new process) re-probes.
_PROBE_VERDICT: bool | None = None


def _probe_tpu(timeout_s: int) -> bool:
    """Check in a short-lived child that a real accelerator backend can
    initialize AND run a matmul. The image's TPU plugin can wedge forever on
    backend init, so this must happen in a child with a hard timeout — never
    in the watchdog process itself. The verdict is probed ONCE per process
    and cached."""
    global _PROBE_VERDICT

    if _PROBE_VERDICT is not None:
        return _PROBE_VERDICT
    _PROBE_VERDICT = _probe_tpu_uncached(timeout_s)
    return _PROBE_VERDICT


def _probe_tpu_uncached(timeout_s: int) -> bool:
    import subprocess

    code = (
        "import jax, jax.numpy as jnp\n"
        "d = jax.devices()\n"
        "assert d and d[0].platform != 'cpu', d\n"
        "x = jnp.ones((256, 256), jnp.bfloat16)\n"
        "(x @ x).block_until_ready()\n"
        "print('TPU_OK', d[0].platform, d[0].device_kind)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, text=True,
        )
        if proc.returncode == 0 and "TPU_OK" in proc.stdout:
            sys.stderr.write(f"tpu probe: {proc.stdout.strip()}\n")
            return True
        sys.stderr.write(
            f"tpu probe failed rc={proc.returncode}: {proc.stderr[-500:]}\n"
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"tpu probe timed out after {timeout_s}s (wedged plugin)\n")
    return False


QUICK_SERIES = int(os.environ.get("FILODB_BENCH_QUICK_SERIES", 25_000))

# result ranks: a line is only (re)printed when strictly better, so the LAST
# JSON line in the driver's captured output is always the best measurement
_RANK_FULL_TPU = 4
_RANK_QUICK_TPU = 3
_RANK_FULL_CPU = 2
_RANK_QUICK_CPU = 1


class _Best:
    rank = 0

    @classmethod
    def emit(cls, parsed: dict, rank: int) -> None:
        if rank > cls.rank:
            print(json.dumps(parsed), flush=True)
            cls.rank = rank


def _run_worker(here, cpu: bool, series: int, timeout_s: int) -> dict | None:
    """Run one worker child; returns its parsed JSON line or None."""
    import subprocess

    args = ["--worker"] + (["--cpu"] if cpu else [])
    env = dict(
        os.environ,
        FILODB_BENCH_SERIES=str(series),
        FILODB_BENCH_WORKER_DEADLINE=str(time.time() + timeout_s - 30),
    )
    try:
        proc = subprocess.run(
            [sys.executable, here] + args, timeout=timeout_s,
            capture_output=True, text=True, cwd=os.path.dirname(here), env=env,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench worker {args} series={series} timed out after {timeout_s}s\n")
        return None
    sys.stderr.write(proc.stderr[-2000:])
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    if proc.returncode == 0 and lines:
        try:
            return json.loads(lines[-1])
        except ValueError:
            pass
    sys.stderr.write(f"bench worker {args} series={series} failed rc={proc.returncode}\n")
    return None


def main():
    """Watchdog wrapper. The TPU tunnel in this environment wedges
    intermittently, and a wedged plugin costs a full child timeout per
    probe. Strategy:

    - probe the accelerator ONCE per process in a short-timeout child and
      cache the verdict (_probe_tpu) — a wedged backend stays wedged for
      the process's lifetime, and the old keep-re-probing loop just spammed
      ~20 "probe timed out" lines per run;
    - on a good verdict, capture a quick-mode TPU measurement (small series
      count, small tunnel exposure) and print it immediately, then scale to
      the full 100k workload and print again if it completes
      (strictly-better results only, so the last JSON line is the best);
    - on a bad verdict, record the honest CPU fallback and exit."""
    if "--worker" in sys.argv:
        if "--cpu" in sys.argv:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        run_benchmark()
        _dump_kernel_snapshot()
        return

    here = os.path.abspath(__file__)
    total = int(os.environ.get("FILODB_BENCH_TIMEOUT_S", 1800))
    deadline = time.time() + total
    cpu_reserve = min(420, max(240, total // 4))
    probe_t = 60

    def remaining() -> float:
        return deadline - time.time()

    def rank_of(parsed: dict, full: bool) -> int:
        tpu = parsed.get("backend", "cpu") != "cpu"
        if tpu:
            return _RANK_FULL_TPU if full else _RANK_QUICK_TPU
        return _RANK_FULL_CPU if full else _RANK_QUICK_CPU

    first_probe_ok = remaining() > probe_t + 90 and _probe_tpu(probe_t)
    if not first_probe_ok and remaining() > 90:
        # insurance first: an honest CPU number beats an empty artifact
        budget = int(min(cpu_reserve, remaining() - 30))
        got = _run_worker(here, cpu=True, series=N_SERIES, timeout_s=budget)
        if got is None and remaining() > 120:
            got = _run_worker(here, cpu=True, series=QUICK_SERIES,
                              timeout_s=int(min(180, remaining() - 30)))
            if got is not None:
                _Best.emit(got, _RANK_QUICK_CPU)
        elif got is not None:
            _Best.emit(got, _RANK_FULL_CPU)

    skip_probe = first_probe_ok  # the very first loop pass rides the initial probe
    while _Best.rank < _RANK_FULL_TPU and remaining() > 90:
        healthy = skip_probe or _probe_tpu(int(min(probe_t, remaining() - 30)))
        skip_probe = False
        if not healthy:
            # the per-process probe verdict is cached (one probe per
            # process): a bad verdict is final, so stop here with the CPU
            # insurance number instead of sleep-spinning the whole budget
            break
        if _Best.rank < _RANK_QUICK_TPU:
            got = _run_worker(here, cpu=False, series=QUICK_SERIES,
                              timeout_s=int(min(360, remaining() - 30)))
            if got is not None:
                _Best.emit(got, rank_of(got, full=False))
                if rank_of(got, full=False) < _RANK_QUICK_TPU:
                    # worker silently fell back to CPU: the cached verdict
                    # is stale — drop it so the next pass re-probes for real
                    global _PROBE_VERDICT
                    _PROBE_VERDICT = None
                    continue
        if _Best.rank >= _RANK_QUICK_TPU and remaining() > 120:
            got = _run_worker(here, cpu=False, series=N_SERIES,
                              timeout_s=int(remaining() - 30))
            if got is not None:
                _Best.emit(got, rank_of(got, full=True))

    if _Best.rank == 0:
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": -1.0,
                    "unit": "ms",
                    "vs_baseline": 0.0,
                }
            )
        )


if __name__ == "__main__":
    main()
